"""The eager Tensor.

TPU-native analogue of the reference's eager tensor stack:
  - phi::DenseTensor (paddle/phi/core/dense_tensor.h:38) — the buffer+meta;
    here the buffer is a jax.Array owned by PJRT (XLA manages HBM, replacing
    paddle/fluid/memory/allocation/allocator_facade.h:43);
  - imperative::VarBase / the eager paddle.Tensor with autograd fields
    (paddle/fluid/eager/, python/paddle/fluid/dygraph/varbase_patch_methods.py);
  - in-place version counters (imperative/variable_wrapper.h inplace_version).

Mutation semantics on a functional runtime: a Tensor is a mutable *cell*
holding an immutable jax.Array. In-place ops rebind the cell and bump
`_inplace_version`; autograd residuals capture the immutable arrays, so
mutation never corrupts recorded history (the reference needs version checks
for this; here it is safe by construction — the version counter is kept for
API parity and error parity on leaf params).

Most tensor methods (x.add, x.reshape, …) are monkey-patched in
paddle_tpu/tensor_api.py, mirroring how the reference patches VarBase methods
at import (varbase_patch_methods.py:197).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dispatch
from .dtype import DType, to_np_dtype, to_paddle_dtype, get_default_dtype
from .lazy import LazyRef, materialize as _mat
from .place import CPUPlace, Place, TPUPlace, _expected_place


def _commit(value, place: Optional[Place]):
    """Put a concrete array on the expected device (no-op for tracers)."""
    if place is None:
        return value
    if isinstance(value, jax.Array) and not isinstance(value, jax.core.Tracer):
        try:
            return jax.device_put(value, place.jax_device)
        except Exception:
            return value
    return value


class Tensor:
    """Mutable eager tensor over a jax.Array (which may be a tracer under jit)."""

    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "_backward_hooks",
        "_inplace_version",
        "name",
        "persistable",
        "is_parameter",
        "__weakref__",
        "__dict__",
    )

    def __init__(
        self,
        value,
        dtype=None,
        place: Optional[Place] = None,
        stop_gradient: bool = True,
        name: Optional[str] = None,
    ):
        if isinstance(value, Tensor):
            value = value._value
        if not isinstance(value, jax.Array) or isinstance(value, np.ndarray):
            npd = to_np_dtype(dtype) if dtype is not None else None
            from_ndarray = isinstance(value, (np.ndarray, np.generic))
            arr = np.asarray(value)
            if npd is None and not from_ndarray and arr.dtype == np.float64:
                # python floats default to paddle's default dtype (float32);
                # explicit numpy float64 arrays keep their dtype (paddle parity)
                npd = to_np_dtype(get_default_dtype())
            value = jnp.asarray(arr, dtype=npd)
            value = _commit(value, place or _expected_place())
        elif dtype is not None:
            value = value.astype(to_np_dtype(dtype))
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self._backward_hooks = []
        self._inplace_version = 0
        self.name = name or ""
        self.persistable = False
        self.is_parameter = False

    # -- meta ---------------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self) -> DType:
        return to_paddle_dtype(self._value.dtype)

    @property
    def place(self) -> Place:
        v = self._value
        if isinstance(v, jax.core.Tracer) or type(v) is LazyRef:
            # pending lazy values commit to the expected device at flush;
            # answering from metadata keeps .place from forcing a flush
            return _expected_place()
        dev = next(iter(v.devices()), None) if hasattr(v, "devices") else None
        if dev is not None and dev.platform == "cpu":
            return CPUPlace(dev.id)
        return TPUPlace(getattr(dev, "id", 0))

    @property
    def is_leaf(self):
        return self._grad_node is None

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        if isinstance(self._value, jax.core.Tracer):
            return f"Tensor(traced, shape={self.shape}, dtype={self.dtype.name})"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place.device_type}, stop_gradient={sg},\n"
            f"       {np.array2string(np.asarray(self._value), prefix='       ')})"
        )

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        # host read = materialization point: flush any pending lazy segment
        # (item/tolist/__float__/__int__/__bool__/__array__ all funnel here)
        return np.asarray(jax.device_get(_mat(self._value)))

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is ambiguous"
            )
        return bool(self.item())

    def __index__(self):
        return int(self.item())

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        """reference: varbase_patch_methods.py:197 → pybind dygraph_run_backward
        → BasicEngine::Execute (imperative/basic_engine.cc:392)."""
        dispatch.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        self._backward_hooks.append(hook)

        class _Handle:
            def remove(_self):
                if hook in self._backward_hooks:
                    self._backward_hooks.remove(hook)

        return _Handle()

    def detach(self) -> "Tensor":
        t = Tensor.__new__(Tensor)
        t._value = self._value
        t.stop_gradient = True
        t.grad = None
        t._grad_node = None
        t._out_index = 0
        t._backward_hooks = []
        t._inplace_version = self._inplace_version
        t.name = self.name
        t.persistable = False
        t.is_parameter = False
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        return dispatch.apply(jnp.copy, self, op_name="clone")

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # -- mutation (in-place) -------------------------------------------------
    def _bump_version(self):
        self._inplace_version += 1

    def set_value(self, value):
        """In-place rebind, keeping identity (optimizer.step / load_state_dict)."""
        if isinstance(value, Tensor):
            new = value._value
        elif isinstance(value, jax.Array):
            new = value
        else:
            new = jnp.asarray(np.asarray(value), dtype=self._value.dtype)
        if tuple(new.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {new.shape} vs {self._value.shape}"
            )
        if new.dtype != self._value.dtype:
            new = new.astype(self._value.dtype)
        self._value = _commit(new, None)
        self._bump_version()
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    def fill_(self, value):
        self._value = jnp.full_like(_mat(self._value), value)
        self._bump_version()
        return self

    def zero_(self):
        return self.fill_(0)

    # -- device movement ----------------------------------------------------
    def cpu(self):
        t = self.detach()
        t._value = jax.device_put(_mat(self._value), jax.devices("cpu")[0])
        t.stop_gradient = self.stop_gradient
        return t

    def cuda(self, device_id=None, blocking=True):
        """Compat: move to the default accelerator (TPU here)."""
        t = self.detach()
        t._value = jax.device_put(_mat(self._value), jax.devices()[device_id or 0])
        t.stop_gradient = self.stop_gradient
        return t

    def pin_memory(self):
        return self  # PJRT stages H2D transfers itself; no pinned-pool API

    def element_size(self) -> int:
        return int(np.dtype(self._value.dtype).itemsize)

    def ndimension(self) -> int:
        return int(self._value.ndim)

    def is_contiguous(self) -> bool:
        return True  # XLA arrays have no user-visible strides

    def contiguous(self):
        return self

    def to(self, *args, **kwargs):
        device = kwargs.get("device")
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, (str, Place)):
                if isinstance(a, str) and a in (
                    "float16", "bfloat16", "float32", "float64",
                    "int32", "int64", "bool", "uint8", "int8",
                ):
                    dtype = a
                else:
                    device = a
            elif isinstance(a, DType):
                dtype = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            from .place import set_device

            place = device if isinstance(device, Place) else None
            if place is None:
                import paddle_tpu.core.place as _p

                prev = _p._expected_place()
                place = _p.set_device(device)
                _p._set_expected_place(prev)
            t = out.detach()
            t._value = jax.device_put(_mat(out._value), place.jax_device)
            t.stop_gradient = out.stop_gradient
            out = t
        return out

    def astype(self, dtype):
        npd = to_np_dtype(dtype)
        return dispatch.apply(
            lambda x, dtype: x.astype(dtype), self, dtype=str(npd), op_name="cast"
        )

    cast = astype

    # -- indexing (dynamic — bypasses per-op jit cache) ----------------------
    def __iter__(self):
        """Bounded iteration over axis 0 (reference Tensor iterates rows).

        Without this, Python falls back to __getitem__ iteration, and jax's
        clamped out-of-bounds indexing would yield the last row forever."""
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d Tensor")
        return (self[i] for i in range(self.shape[0]))

    def __getitem__(self, idx):
        # plain leading-axis int: validate bounds eagerly (jax clamps
        # silently; the reference raises). bool is an int subclass but is a
        # mask/newaxis index, not a position.
        if isinstance(idx, (int, np.integer)) and not isinstance(
            idx, (bool, np.bool_)
        ):
            n = self.shape[0] if self.ndim else 0
            if not -n <= idx < n:
                raise IndexError(
                    f"index {idx} is out of bounds for axis 0 with size {n}"
                )
        idx = _unwrap_index(idx)

        # a bare int (or all-int tuple) varies call to call — pass it as a
        # TRACED scalar so ONE compiled program serves every index value
        # (static-kwarg caching here would compile per index: a row-iteration
        # loop would trigger a compile storm and unbounded cache growth)
        if isinstance(idx, (int, np.integer)) and not isinstance(
            idx, (bool, np.bool_)
        ):
            i = int(idx)
            i += self.shape[0] if i < 0 else 0  # bounds checked above
            return dispatch.apply(
                _take_leading, self, jnp.asarray(i, jnp.int32), op_name="getitem"
            )
        if (
            isinstance(idx, tuple)
            and idx
            and len(idx) <= self.ndim
            and all(
                isinstance(e, (int, np.integer))
                and not isinstance(e, (bool, np.bool_))
                for e in idx
            )
        ):
            wrapped = [
                _checked_traced_int(e, self._value.shape[ax], ax)
                for ax, e in enumerate(idx)
            ]
            return dispatch.apply(
                _getitem_ints, self, *wrapped, op_name="getitem"
            )

        # mixed tuple (ints among slices/None/Ellipsis): wrap the ints as
        # traced scalars so one program per tuple STRUCTURE serves every int
        # value — `x[i, :]` in a loop must not compile per i
        if (
            isinstance(idx, tuple)
            and any(
                isinstance(e, (int, np.integer))
                and not isinstance(e, (bool, np.bool_))
                for e in idx
            )
            and not any(isinstance(e, (bool, np.bool_)) for e in idx)
            and _index_is_static(idx)
        ):
            spec, ints = [], []
            ax = 0
            for e in idx:
                if e is None:
                    spec.append(None)
                    continue
                if e is Ellipsis:
                    spec.append(e)
                    ax += self.ndim - sum(
                        1 for q in idx if q is not None and q is not Ellipsis
                    )
                    continue
                if isinstance(e, (int, np.integer)) and not isinstance(
                    e, (bool, np.bool_)
                ):
                    ints.append(
                        _checked_traced_int(e, self._value.shape[ax], ax)
                    )
                    spec.append(_INT_SLOT)
                else:
                    spec.append(e)
                ax += 1
            return dispatch.apply(
                _getitem_mixed, self, *ints, spec=tuple(spec), op_name="getitem"
            )

        # fully-static indices (slices/None/Ellipsis) are hashable → pass as
        # a static kwarg so the op hits the per-op jit + vjp caches instead
        # of re-linearizing on every call (ADVICE r1 / VERDICT r2 item 9).
        # Slice patterns mostly repeat; a bounded guard keeps pathological
        # non-repeating patterns (sliding windows) from growing the jit
        # cache without limit — beyond the cap they take the uncached path.
        if _index_is_static(idx):
            try:  # slices are unhashable before Python 3.12 → closure path
                cacheable = idx in _static_idx_seen or len(_static_idx_seen) < 512
                if cacheable:
                    _static_idx_seen.add(idx)
            except TypeError:
                cacheable = False
            if cacheable:
                return dispatch.apply(
                    _getitem_static, self, idx=idx, op_name="getitem"
                )

        # array-valued index → closure; dispatch skips the jit cache for it,
        # but still records the tape (vjp handles the scatter-back for gathers)
        def _getitem(x):
            return x[idx]

        if _index_is_traceable(idx):
            return dispatch.apply(_getitem, self, op_name="getitem")
        # boolean-mask indexing → dynamic output shape: must stay out of any
        # jit trace, but eager vjp with a concrete mask is well-defined
        if isinstance(self._value, jax.core.Tracer):
            raise ValueError(
                "boolean-mask indexing inside jit produces a dynamic shape; "
                "use paddle.masked_select outside jit or paddle.where instead"
            )
        return dispatch.apply(_getitem, self, op_name="getitem_mask")

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        v = value._value if isinstance(value, Tensor) else value
        if isinstance(v, (int, float, bool)):
            pass
        else:
            v = jnp.asarray(v)
            if v.dtype != self._value.dtype:
                v = v.astype(self._value.dtype)
        self._value = self._value.at[idx].set(v)
        self._bump_version()

    # pytree-friendliness: jax can flatten Tensors transparently. Direct jnp
    # consumption outside the dispatcher is a materialization point for lazy
    # values (tracers pass through untouched).
    def __jax_array__(self):
        return _mat(self._value)


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx))
    return idx


def _getitem_static(x, *, idx):
    return x[idx]


def _take_leading(x, i):
    return jnp.take(x, i, axis=0)


def _getitem_ints(x, *idxs):
    return x[idxs]


def _checked_traced_int(e, n, ax):
    """Bounds-check int index `e` on an axis of size `n`, wrap negatives,
    and return it as a traced i32 scalar (shared by every int-index path)."""
    e = int(e)
    if not -n <= e < n:
        raise IndexError(
            f"index {e} is out of bounds for axis {ax} with size {n}"
        )
    return jnp.asarray(e + n if e < 0 else e, jnp.int32)


# placeholder marking traced-int positions inside a mixed index tuple
_INT_SLOT = "__traced_int__"

# distinct static index values routed through the jit cache (bounded guard)
_static_idx_seen: set = set()


def _getitem_mixed(x, *ints, spec):
    it = iter(ints)
    idx = tuple(next(it) if e == _INT_SLOT else e for e in spec)
    return x[idx]


def _index_is_static(idx) -> bool:
    """True when idx is fully hashable static metadata (no arrays)."""
    if idx is None or idx is Ellipsis:
        return True
    if isinstance(idx, (int, np.integer, bool, np.bool_)):
        return True
    if isinstance(idx, slice):
        return all(
            s is None or isinstance(s, (int, np.integer))
            for s in (idx.start, idx.stop, idx.step)
        )
    if isinstance(idx, tuple):
        return all(_index_is_static(i) for i in idx)
    return False


def _index_is_traceable(idx) -> bool:
    """Boolean masks produce dynamic shapes — keep those out of jit."""
    if isinstance(idx, (jax.Array, np.ndarray)) and idx.dtype == np.bool_:
        return False
    if isinstance(idx, tuple):
        return all(_index_is_traceable(i) for i in idx)
    return True


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py:87)."""
    if isinstance(data, Tensor):
        t = data.astype(dtype) if dtype is not None else data.clone()
        t.stop_gradient = stop_gradient
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


# register Tensor as a jax pytree leaf-unwrapper? Tensors are treated as
# leaves; functional bridges unwrap explicitly (see paddle_tpu/jit/).
