"""Core runtime: Place/device, dtype, flags, RNG, Tensor, dispatch+autograd.

The TPU-native replacement for the reference's L0-L2 + eager autograd core
(see SURVEY.md §1): platform/device runtime, memory (owned by PJRT/XLA here),
phi::DenseTensor, KernelFactory dispatch, and the eager GradNode engine.
"""
from . import dispatch, dtype, flags, place, random  # noqa: F401
from .dispatch import enable_grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
from .dtype import (  # noqa: F401
    DType,
    bfloat16,
    bool_,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .place import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)
from .random import Generator, get_rng_state, seed, set_rng_state  # noqa: F401
from .tensor import Tensor, to_tensor  # noqa: F401
