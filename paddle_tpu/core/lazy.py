"""Deferred (lazy) eager dispatch: batch per-op launches into fused segments.

The per-op eager path (dispatch.apply) launches one XLA program per op, so
an eager LeNet train step costs ~13 device-program round-trips — and
PROFILE_EAGER.md shows the program *count*, not host Python, is the ceiling
on eager throughput through the relay. This module is the classic
LazyTensor-style fix proven by torch-xla (XLATensor + pending IR graph,
torch_xla/csrc/tensor.cpp) and by the reference's own to_static tracing:

  - with FLAGS_eager_lazy_dispatch on, `apply()` does not execute: the op is
    appended to a per-thread pending *segment* and the caller gets a Tensor
    backed by a `LazyRef` (shape/dtype known via jax.eval_shape, value
    pending);
  - materialization points — host reads (numpy/item/float/bool), backward,
    explicit paddle_tpu.device.synchronize(), uncacheable/jit=False ops, a
    mid-segment AMP region — flush the whole pending segment as ONE jitted
    program;
  - the compiled segment is cached by *segment signature* (sequence of op
    cache-tokens + static kwargs + input bindings + external input avals),
    so a steady-state eager train step replays a cached fused executable:
    1 forward segment + 1 compiled-tape backward + 1 fused optimizer update.

Autograd composes unchanged: recorded ops get their GradNode at defer time
(so later ops snapshot correct Edges), and the segment program computes each
recorded op's jax.vjp *inside the fused trace* — at flush the pytree vjp
closures come back as concrete residuals and are slotted into the pending
GradNodes, which then behave exactly like per-op-path nodes (including the
compiled-tape backward and create_graph re-derivation).
"""
from __future__ import annotations

import functools
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import async_compile as _async
from . import flags

__all__ = [
    "LazyRef",
    "captured_step_donation_verdicts",
    "captured_step_handle",
    "captured_step_program",
    "captured_step_shard_info",
    "drain_async",
    "flush_if_pending",
    "materialize",
    "pending_op_count",
    "pending_segment_jaxpr",
    "reset_serve_programs",
    "serve_capture_state",
    "serve_program",
    "step_capture_state",
    "step_signature_id",
]


def _add_time(key: str, t0: float) -> float:
    from . import dispatch

    dt_ms = (time.perf_counter() - t0) * 1000.0
    dispatch._counters[key] += dt_ms
    return dt_ms


def _note_program(key: str, category: str, dt_ms: float):
    """Feed one measured program run into the attribution cost registry
    (paddle.profiler.attribution) — the same duration the dispatch timers
    book, so the per-key EMA and replay_time_ms agree."""
    try:
        from ..profiler import attribution as _attribution

        _attribution.note_run(key, category, dt_ms)
    except Exception:
        pass  # attribution must never break the program


def _register_program(key: str, category: str, **kw):
    try:
        from ..profiler import attribution as _attribution

        _attribution.register(key, category, **kw)
    except Exception:
        pass


def _sig_id(sig) -> str:
    try:
        return f"{hash(sig) & 0xFFFF:04x}"
    except TypeError:
        return "anon"


def drain_async():
    """Join every background compile job (FLAGS_eager_async_compile). An
    explicit sync point for benchmarks/tests; steady-state code never needs
    it — pending compiles install themselves at the next flush/replay of
    their signature."""
    _async.drain()

# sentinel returned by lazy_apply when the op must take the per-op path
_FALLBACK = object()

_tls = threading.local()

# binding kinds inside a segment: op input comes from an external array, a
# previous op's output, or an embedded python-scalar literal
_EXT, _RES, _LIT = 0, 1, 2


def _np_dtype(dt):
    """np.dtype when possible; jax extended dtypes (PRNG keys, float8 wrap
    types) pass through as-is — they are hashable and aval-comparable."""
    try:
        return np.dtype(dt)
    except TypeError:
        return dt


class LazyRef:
    """Pending value of one output of one deferred op.

    Carries the inferred aval so shape/dtype-dependent control flow does NOT
    flush; any other attribute access (or numpy/jax conversion) materializes
    by flushing the owning segment. After the flush `_concrete` holds the
    real array and all access delegates to it.
    """

    __slots__ = (
        "_segment",
        "_op_index",
        "_out_index",
        "_shape",
        "_dtype",
        "_concrete",
        "__weakref__",
    )

    def __init__(self, segment, op_index, out_index, shape, dtype):
        self._segment = segment
        self._op_index = op_index
        self._out_index = out_index
        self._shape = tuple(shape)
        self._dtype = _np_dtype(dtype)
        self._concrete = None

    # -- aval surface (no flush) -------------------------------------------
    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        return int(np.prod(self._shape)) if self._shape else 1

    # -- materialization ----------------------------------------------------
    def materialize(self):
        if self._concrete is None:
            _flush(self._segment, "sync")
            if self._concrete is None:
                # the owning segment's flush failed earlier (compile or
                # runtime error): surface the root cause on every read
                # instead of silently yielding None
                raise RuntimeError(
                    "lazy-dispatch segment flush failed; this tensor's value "
                    "is unavailable"
                ) from self._segment.error
        return self._concrete

    def __getattr__(self, name):
        # anything beyond the aval surface needs the real array
        return getattr(self.materialize(), name)

    def __jax_array__(self):
        return self.materialize()

    def __array__(self, dtype=None):
        arr = np.asarray(jax.device_get(self.materialize()))
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        state = "pending" if self._concrete is None else "materialized"
        return f"<LazyRef {state} shape={self._shape} dtype={self._dtype}>"


def _delegating(name):
    def method(self, *args, **kwargs):
        return getattr(self.materialize(), name)(*args, **kwargs)

    method.__name__ = name
    return method


# operators bypass instance __getattr__ — install explicit delegates so a
# LazyRef that leaks into raw jnp/python arithmetic still behaves like its
# (materialized) array instead of raising
for _name in (
    "__add__", "__radd__", "__sub__", "__rsub__", "__mul__", "__rmul__",
    "__truediv__", "__rtruediv__", "__floordiv__", "__rfloordiv__",
    "__mod__", "__rmod__", "__pow__", "__rpow__", "__matmul__",
    "__rmatmul__", "__neg__", "__pos__", "__abs__", "__getitem__",
    "__lt__", "__le__", "__gt__", "__ge__", "__eq__", "__ne__",
    "__float__", "__int__", "__bool__", "__len__", "__iter__",
):
    setattr(LazyRef, _name, _delegating(_name))
LazyRef.__hash__ = object.__hash__  # __eq__ delegate must not kill identity hash


def materialize(v):
    """Concrete value of `v` (flushes the pending segment for LazyRefs)."""
    return v.materialize() if type(v) is LazyRef else v


class _SegOp:
    """One deferred op inside a pending segment."""

    __slots__ = ("fn", "kw", "bindings", "diff_idx", "record", "node", "outs")

    def __init__(self, fn, kw, bindings, diff_idx, record, node):
        self.fn = fn
        self.kw = kw
        self.bindings = bindings
        self.diff_idx = diff_idx
        self.record = record
        self.node = node
        self.outs = []  # [(LazyRef, Tensor)] — filled by lazy_apply


class _Segment:
    """Per-thread pending op trace, flushed as one jitted program."""

    __slots__ = (
        "ops", "ext_vals", "ext_ids", "ext_specs", "sig_parts", "flushed",
        "error",
    )

    def __init__(self):
        self.ops: List[_SegOp] = []
        self.ext_vals: List[Any] = []
        self.ext_ids: Dict[int, int] = {}
        self.ext_specs: List[Tuple] = []
        self.sig_parts: List[Tuple] = []
        self.flushed = False
        self.error: Optional[BaseException] = None


def _current_segment() -> _Segment:
    seg = getattr(_tls, "segment", None)
    if seg is None or seg.flushed:
        seg = _Segment()
        _tls.segment = seg
    return seg


def pending_op_count() -> int:
    seg = getattr(_tls, "segment", None)
    return 0 if seg is None or seg.flushed else len(seg.ops)


def flush_if_pending(reason: str = "explicit_sync"):
    """Flush this thread's pending segment (no-op when nothing is pending).

    Also a resolution point for a deferred captured-step backward
    (FLAGS_eager_step_capture): anything that forces materialization before
    optimizer.step() replays the capture aborts it back to the normal
    3-program path first — numerics never change, only the program count."""
    if getattr(_tls, "capture_deferred", None) is not None:
        _abort_capture(reason)
    seg = getattr(_tls, "segment", None)
    if seg is not None and not seg.flushed and seg.ops:
        _flush(seg, reason)


# ---------------------------------------------------------------------------
# Output-aval inference, cached by (op token, statics, input specs): one
# host-side jax.eval_shape per new op configuration, dict lookups after.
# ---------------------------------------------------------------------------
_aval_cache: "OrderedDict[Tuple, Tuple]" = OrderedDict()


def _infer_out_specs(fn, kw, arg_specs):
    args = []
    for spec in arg_specs:
        if spec[0] == "arr":
            args.append(jax.ShapeDtypeStruct(spec[1], spec[2]))
        else:
            args.append(spec[1])
    out = jax.eval_shape(functools.partial(fn, **kw), *args)
    if isinstance(out, (tuple, list)):
        flat, is_seq = list(out), True
    else:
        flat, is_seq = [out], False
    return [(tuple(o.shape), _np_dtype(o.dtype)) for o in flat], is_seq


# ---------------------------------------------------------------------------
# Segment compile cache: signature -> jitted segment program (LRU-bounded).
# With FLAGS_eager_async_compile, a fresh signature's fused program compiles
# on the background thread first (_pending_seg_compiles holds the future)
# and is installed here at the next flush of the same signature.
# ---------------------------------------------------------------------------
_segment_cache: "OrderedDict[Tuple, Callable]" = OrderedDict()
_pending_seg_compiles: Dict[Tuple, Any] = {}
_pending_lock = threading.Lock()


def _segment_fn(plan, check=False):
    """Raw (unjitted) segment program over the external-input list.

    plan: [(fn, kw, bindings, diff_idx, record)] — deliberately stripped
    of _SegOp/GradNode/Tensor refs so the cached closure pins no user data.

    With `check=True` (FLAGS_check_nan_inf under lazy dispatch) the program
    additionally returns one bool per op — any(~isfinite) over that op's
    float outputs — folded INTO the fused trace: the finite scan costs zero
    extra program launches and is read once at flush."""

    def seg_fn(ext):
        results = []
        vjps = []
        bad_flags = []
        for fn, kw, bindings, diff_idx, record in plan:
            vals = []
            for kind, a, b in bindings:
                if kind == _EXT:
                    vals.append(ext[a])
                elif kind == _RES:
                    vals.append(results[a][b])
                else:
                    vals.append(a)
            if record:

                def partial(*dv, _fn=fn, _kw=kw, _vals=tuple(vals), _di=diff_idx):
                    full = list(_vals)
                    for i, v in zip(_di, dv):
                        full[i] = v
                    res = _fn(*full, **_kw)
                    return tuple(res) if isinstance(res, list) else res

                out, vjp = jax.vjp(partial, *[vals[i] for i in diff_idx])
                vjps.append(vjp)
            else:
                out = fn(*vals, **kw)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            results.append(outs)
            if check:
                bad = jnp.asarray(False)
                for o in outs:
                    if jnp.issubdtype(jnp.result_type(o), jnp.inexact):
                        bad = bad | jnp.any(~jnp.isfinite(o))
                bad_flags.append(bad)
        if check:
            return results, vjps, jnp.stack(bad_flags)
        return results, vjps

    return seg_fn


def _build_segment_fn(plan, check=False):
    return jax.jit(_segment_fn(plan, check))


def _seg_signature(seg: _Segment) -> Tuple:
    """Canonical compile-cache / capture signature of a segment. The
    finite-check flag is part of it: a checking segment compiles a different
    program (one extra bool-vector output) than a non-checking one."""
    return (
        tuple(seg.sig_parts),
        tuple(seg.ext_specs),
        bool(flags.flag("check_nan_inf")),
    )


def _seg_plan(seg: _Segment):
    return [(op.fn, op.kw, op.bindings, op.diff_idx, op.record) for op in seg.ops]


def _segment_jaxpr(plan, ext_specs):
    """Closed jaxpr of the fused segment program (for the verifier).

    Preserves the recorded weak_type flags: weak scalars promote
    differently, and the verified jaxpr must match the jaxpr the segment
    actually compiles (a weak f64 literal is benign; a strong one is the
    upcast the dtype pass hunts)."""
    specs = [
        jax.ShapeDtypeStruct(
            shape, dtype, weak_type=bool(rest[0]) if rest else False
        )
        for shape, dtype, *rest in ext_specs
    ]
    return jax.make_jaxpr(_segment_fn(plan))(specs)


def pending_segment_jaxpr():
    """Trace this thread's pending segment WITHOUT flushing it; None when
    nothing is pending. Feeds paddle_tpu.analysis.check_pending_segment."""
    seg = getattr(_tls, "segment", None)
    if seg is None or seg.flushed or not seg.ops:
        return None
    return _segment_jaxpr(_seg_plan(seg), seg.ext_specs)


def _flush(seg: _Segment, reason: str):
    from . import dispatch

    if seg.flushed:
        return
    rec = getattr(_tls, "capture_deferred", None)
    if rec is not None and (seg is rec.segment or seg is rec.stub_seg):
        # a read reached a deferred captured step (the unflushed forward
        # segment or one of the placeholder grads) before optimizer.step()
        # replayed it: resolve by the normal flush + tape-backward path
        _abort_capture(reason)
        return
    seg.flushed = True
    if getattr(_tls, "segment", None) is seg:
        _tls.segment = None
    if not seg.ops:
        return

    check = bool(flags.flag("check_nan_inf"))
    n_ops = len(seg.ops)
    sig = _seg_signature(seg)
    skey = f"segment:{_sig_id(sig)}"
    jfn = dispatch._lru_get(_segment_cache, sig)
    fresh = jfn is None
    fut = None
    if fresh:
        with _pending_lock:
            fut = _pending_seg_compiles.get(sig)
    # the op plan is only needed to build a fresh segment fn, by the async
    # bridge, and by the per-op fault fallback below — cache-hit steady
    # state skips the O(num_ops) build entirely
    plan = _seg_plan(seg) if (fresh and fut is None) else None
    if fresh and fut is None:
        dispatch._counters["segment_cache_misses"] += 1
    elif not fresh:
        dispatch._counters["segment_cache_hits"] += 1

    fused = True
    bridged = False
    try:
        if plan is not None and int(flags.flag("check_programs")):
            # FLAGS_check_programs: verify the fused segment before its
            # first compile (cached replays were already verified). A
            # level-2 raise lands in the except path below, so reads of
            # this segment's tensors re-raise the verification error.
            from .. import analysis

            analysis.enforce(
                analysis.check(
                    _segment_jaxpr(plan, seg.ext_specs),
                    source="lazy-segment",
                ),
                where=f"lazy-segment flush ({reason})",
            )
        if not fresh:
            t0 = time.perf_counter()
            out = dispatch._rexec("segment", lambda: jfn(seg.ext_vals))
            _note_program(skey, "segment", _add_time("replay_time_ms", t0))
        elif fut is not None:
            # second flush of a signature whose fused program is compiling
            # in the background: join it (a compile-thread exception
            # re-raises HERE with its original traceback and lands in the
            # except path below, exactly like a synchronous compile error)
            t0 = time.perf_counter()
            with _pending_lock:
                # drop the pending entry up front: a compile-thread error
                # surfaces HERE once, and the next flush of this signature
                # starts a fresh compile instead of re-raising forever
                _pending_seg_compiles.pop(sig, None)
            jfn = fut.result()
            # any wait on a still-unfinished background compile is
            # main-thread-blocking compile time, not replay time
            _add_time("compile_time_ms", t0)
            dispatch._lru_put(
                _segment_cache, sig, jfn,
                evict_counter="segment_cache_evictions",
                cap=int(flags.flag("eager_segment_cache_size")),
            )
            dispatch._counters["async_compile_joins"] += 1
            dispatch._counters["segment_cache_hits"] += 1
            dispatch._emit("async_join", site="segment")
            t0 = time.perf_counter()
            out = dispatch._rexec("segment", lambda: jfn(seg.ext_vals))
            _note_program(skey, "segment", _add_time("replay_time_ms", t0))
        else:
            # attribution cost registry: a fresh segment signature
            # registers its static profile at build time (spec-only
            # thunk — the plan pins no user data, per _segment_fn)
            _register_program(
                skey, "segment",
                jaxpr_thunk=(
                    lambda _plan=plan, _specs=tuple(seg.ext_specs):
                    _segment_jaxpr(_plan, _specs)),
                ops=n_ops,
            )
            submitted = None
            if _async.enabled():
                jfn_bg = _build_segment_fn(plan, check)
                ext_snapshot = list(seg.ext_vals)

                def _compile_job(_jfn=jfn_bg, _ext=ext_snapshot):
                    # jax AOT: trace + compile from the snapshot's avals
                    # without EXECUTING the program (a plain first call
                    # would run the whole segment on device a second time,
                    # racing the main thread's bridged execution for the
                    # accelerator). The Compiled takes the place of the
                    # jitted wrapper in _segment_cache: avals — weak_type
                    # included — are part of the cache signature, so every
                    # later flush of this signature calls it with exactly
                    # the avals it was lowered for.
                    return _jfn.lower(_ext).compile()

                submitted = _async.submit(_compile_job)
            if submitted is not None:
                # async bridge: run the SAME op plan eagerly for immediate
                # results (identical ops and vjps — the rung the fault
                # fallback below already relies on) while the fused program
                # compiles off-thread. Fault injection, retries, and ladder
                # accounting wrap this main-thread execution as usual.
                with _pending_lock:
                    _pending_seg_compiles[sig] = submitted
                    # entries normally pop at the join; a signature-churning
                    # loop never joins, so bound the map (oldest first —
                    # dicts preserve insertion order) instead of pinning
                    # compiled programs for signatures that never recur
                    while len(_pending_seg_compiles) > 64:
                        _pending_seg_compiles.pop(
                            next(iter(_pending_seg_compiles))
                        )
                dispatch._counters["async_bridge_flushes"] += 1
                dispatch._emit("async_compile", site="segment",
                               phase="submit")
                t0 = time.perf_counter()
                out = dispatch._rexec(
                    "segment",
                    lambda: _segment_fn(plan, check)(seg.ext_vals),
                    fresh=True,
                )
                _add_time("replay_time_ms", t0)
                bridged = True
            else:
                jfn = _build_segment_fn(plan, check)
                t0 = time.perf_counter()
                out = dispatch._rexec(
                    "segment", lambda: jfn(seg.ext_vals), fresh=True
                )
                _add_time("compile_time_ms", t0)
    except BaseException as e:
        # a failed flush must leave no pending background compile keyed by
        # its signature: the submitted job compiled THIS segment's plan, and
        # a later (healthy) flush of the same signature joining it would
        # re-raise this flush's failure instead of compiling cleanly
        if fresh:
            with _pending_lock:
                _pending_seg_compiles.pop(sig, None)
        # graceful degradation (paddle.resilience): when the FUSED launch
        # keeps failing transiently (retries exhausted), re-execute the
        # same plan per-op — identical ops and vjps, one rung down the
        # ladder. Deterministic failures keep the fail-loud contract.
        out = None
        if isinstance(e, Exception) and dispatch._resilience_module().is_transient(e):
            try:
                if plan is None:
                    plan = _seg_plan(seg)  # cache-hit flush skipped the build
                out = _segment_fn(plan, check)(seg.ext_vals)
            except Exception:
                out = None
        if out is None:
            # record the root cause: every later materialize() of this
            # segment's refs re-raises it instead of silently yielding None.
            # A program that never ran successfully is never cached.
            seg.error = e
            seg.ops = []
            raise
        fused = False
        dispatch._counters["segment_per_op_fallbacks"] += 1
        for _ in plan:  # per-op programs, and the step is no longer capturable
            dispatch._count_program("op")
    if fused:
        if fresh and not bridged:
            # the bridged path has no jfn yet — its fused program installs
            # at the join (next flush of this signature), never a None here
            dispatch._lru_put(
                _segment_cache, sig, jfn,
                evict_counter="segment_cache_evictions",
                cap=int(flags.flag("eager_segment_cache_size")),
            )
        dispatch._count_program("segment")
    dispatch._counters["segments_flushed"] += 1
    reasons = dispatch._counters["flush_reasons"]
    reasons[reason] = reasons.get(reason, 0) + 1
    dispatch._emit(
        "flush", site="segment", reason=reason, ops=n_ops,
        cache=("join" if (fresh and fut is not None)
               else "miss" if fresh else "hit"),
        fused=fused, bridged=bridged,
    )
    if fused:
        _observe_event(("seg", sig))

    if check:
        results, vjps, bad_flags = out
        dispatch._counters["segment_nan_checks"] += 1
    else:
        results, vjps = out
        bad_flags = None
    bad_op = None
    if bad_flags is not None:
        badvec = np.asarray(bad_flags)
        if badvec.any():
            bad_op = getattr(
                seg.ops[int(np.argmax(badvec))].fn, "__name__", "op"
            )

    vi = 0
    for op, outs in zip(seg.ops, results):
        for (ref, t), val in zip(op.outs, outs):
            ref._concrete = val
            if t._value is ref:
                t._value = val
        if op.record:
            node = op.node
            node.vjp_fn = vjps[vi]
            vi += 1
            node.jit_vjp = True
            # replace predicted avals with the real ones (weak-type exactness)
            node.out_avals = [(tuple(v.shape), v.dtype) for v in outs]
    seg.ops = []  # drop op/node/tensor refs — the segment is spent
    if bad_op is not None:
        # the fused finite-check fired: same FloatingPointError contract as
        # the per-op FLAGS_check_nan_inf scan, raised once at flush (values
        # are already written back, so the bad tensors are inspectable)
        raise FloatingPointError(
            f"NaN/Inf detected in output of op '{bad_op}' "
            "(lazy-segment flush, FLAGS_check_nan_inf)"
        )


# ---------------------------------------------------------------------------
# The deferral entry point, called from dispatch.apply when the flag is on
# ---------------------------------------------------------------------------
def lazy_apply(
    fn: Callable,
    args: Tuple,
    kw_items: Tuple,
    *,
    op_name: Optional[str],
    differentiable: bool,
    jit: bool,
    cache_token,
):
    """Defer `fn` onto the pending segment; `_FALLBACK` sends the caller to
    the per-op path (after flushing, so program order is preserved)."""
    from . import dispatch
    from .tensor import Tensor

    # bail-outs: ops the segment trace cannot host take the per-op path.
    # jit=False ops have data-dependent output shapes; closure-captured fns
    # have no stable cache token; explicit cache_token ops (to_static
    # closures) manage their own compile caches; AMP casting and the debug
    # flags read per-call state the segment signature doesn't cover.
    if not jit:
        flush_if_pending("fallback_nojit")
        return _FALLBACK
    if cache_token is not None:
        flush_if_pending("fallback_token")
        return _FALLBACK
    token = dispatch._cache_token(fn)
    if token is None:
        flush_if_pending("fallback_uncacheable")
        return _FALLBACK
    if flags.flag("benchmark"):
        # FLAGS_check_nan_inf no longer forces the per-op path: the finite
        # scan is folded into the fused segment and read once at flush
        # (_segment_fn(check=True)), so programs-per-step is unchanged
        flush_if_pending("fallback_debug")
        return _FALLBACK
    amp = dispatch._amp_module()
    if amp.amp_active():
        flush_if_pending("fallback_amp")
        return _FALLBACK
    try:
        hash(kw_items)
    except TypeError:
        flush_if_pending("fallback_unhashable")
        return _FALLBACK

    # unwrap + classify args; tracer-backed values mean we are inside
    # someone's jit trace (to_static / recompute) — defer nothing there
    vals: List[Any] = []
    diff_idx: List[int] = []
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            v = a._value
            if isinstance(v, jax.core.Tracer):
                return _FALLBACK
            vals.append(v)
            if not a.stop_gradient and (
                getattr(v, "dtype", None) in dispatch._FLOAT_DTYPES
            ):
                diff_idx.append(i)
        else:
            if isinstance(a, jax.core.Tracer):
                return _FALLBACK
            vals.append(a)

    seg = _current_segment()

    # pass 1 — classify without mutating the segment, so any fallback below
    # leaves no stray external inputs in the signature
    pre: List[Tuple] = []
    arg_specs: List[Tuple] = []
    for v in vals:
        if type(v) is LazyRef:
            if v._concrete is not None:
                v = v._concrete
            elif v._segment is not seg:
                # pending ref from a stale/foreign segment: materialize it
                _flush(v._segment, "cross_segment")
                v = v._concrete
            else:
                pre.append((_RES, v._op_index, v._out_index))
                arg_specs.append(("arr", v._shape, v._dtype))
                continue
        if isinstance(v, (jax.Array, np.ndarray)):
            pre.append((_EXT, v, 0))
            arg_specs.append(
                ("arr", tuple(v.shape), _np_dtype(v.dtype),
                 bool(getattr(v, "weak_type", False)))
            )
        else:
            try:
                hash(v)
            except TypeError:
                flush_if_pending("fallback_unhashable")
                return _FALLBACK
            pre.append((_LIT, v, 0))
            arg_specs.append(("lit", v))

    record = (
        differentiable and bool(diff_idx) and dispatch._grad_state().grad_enabled
    )

    # output avals (cached eval_shape); failure → op is not traceable as-is
    kw = dict(kw_items)
    aval_key = (token, kw_items, tuple(arg_specs), record)
    hit = dispatch._lru_get(_aval_cache, aval_key)
    if hit is not None:
        out_specs, is_seq = hit
    else:
        t0 = time.perf_counter()
        try:
            out_specs, is_seq = _infer_out_specs(fn, kw, arg_specs)
        except Exception:
            # book only the failed inference itself — the fallback flush
            # below times its own work (replay/compile), and a finally here
            # would double-count it under trace_time_ms
            _add_time("trace_time_ms", t0)
            flush_if_pending("fallback_infer")
            return _FALLBACK
        _add_time("trace_time_ms", t0)
        # capped alongside the per-op compile caches (host-only metadata, no
        # jit wrappers, so no eviction counter)
        dispatch._lru_put(_aval_cache, aval_key, (out_specs, is_seq))

    # pass 2 — commit: intern external inputs, build final bindings
    bindings = []
    for kind, a, b in pre:
        if kind == _EXT:
            k = seg.ext_ids.get(id(a))
            if k is None:
                k = len(seg.ext_vals)
                seg.ext_vals.append(a)
                seg.ext_ids[id(a)] = k
                seg.ext_specs.append(
                    (tuple(a.shape), _np_dtype(a.dtype),
                     bool(getattr(a, "weak_type", False)))
                )
            bindings.append((_EXT, k, 0))
        else:
            bindings.append((kind, a, b))
    bindings = tuple(bindings)
    diff_t = tuple(diff_idx)

    node = None
    if record:
        node = dispatch.GradNode(
            None,
            [args[i] for i in diff_idx],
            list(out_specs),
            op_name or getattr(fn, "__name__", "op"),
            out_is_seq=is_seq,
        )

        # pure primal for create_graph double-grad re-derivation; non-diff
        # captures resolve at call time (post-flush they are concrete)
        def primal_fn(*dv, _fn=fn, _kw=kw, _vals=tuple(vals), _di=diff_t):
            full = [materialize(x) for x in _vals]
            for i, v in zip(_di, dv):
                full[i] = v
            res = _fn(*full, **_kw)
            return tuple(res) if isinstance(res, list) else res

        node.primal_fn = primal_fn

    op_index = len(seg.ops)
    op = _SegOp(fn, kw, bindings, diff_t, record, node)
    outs = []
    for i, (shape, dtype) in enumerate(out_specs):
        ref = LazyRef(seg, op_index, i, shape, dtype)
        # per-op parity: only RECORDED float outputs are differentiable;
        # non-recorded ops (no_grad, differentiable=False, int inputs) wrap
        # with stop_gradient=True exactly like _wrap_outputs does
        sg = True if not record else dtype not in dispatch._FLOAT_DTYPES
        t = _new_tensor(ref, stop_gradient=sg)
        if record and not t.stop_gradient:
            t._grad_node = node
            t._out_index = i
        op.outs.append((ref, t))
        outs.append(t)
    seg.ops.append(op)
    seg.sig_parts.append((token, kw_items, bindings, record, diff_t))
    dispatch._counters["lazy_ops_deferred"] += 1

    if len(seg.ops) >= int(flags.flag("eager_segment_max_ops")):
        _flush(seg, "segment_limit")

    return outs if is_seq else outs[0]


def _new_tensor(value, stop_gradient):
    from .tensor import Tensor

    t = Tensor.__new__(Tensor)
    t._value = value
    t.stop_gradient = stop_gradient
    t.grad = None
    t._grad_node = None
    t._out_index = 0
    t._backward_hooks = []
    t._inplace_version = 0
    t.name = ""
    t.persistable = False
    t.is_parameter = False
    return t


# ---------------------------------------------------------------------------
# Whole-step capture-and-replay (FLAGS_eager_step_capture).
#
# The LazyTensor / CUDA-Graphs idiom on top of lazy dispatch: the controller
# observes the per-step event sequence — one fused forward segment flush, one
# compiled-tape backward, one fused optimizer update — and once the same
# (segment signature, tape fingerprint, optimizer fingerprint) triple has
# recurred for FLAGS_eager_capture_warmup consecutive steps it re-traces the
# WHOLE step (forward + backward + optimizer update) as one jaxpr, compiled
# with donate_argnums over parameters and optimizer state so updates reuse
# their HBM buffers in place. The mechanics:
#
#   - run_backward, seeing an armed controller and a matching pending
#     segment + tape, DEFERS the backward: the segment stays unflushed, each
#     tape leaf gets a placeholder grad (a LazyRef on a stub segment), and
#     execution continues;
#   - optimizer.step() is the step boundary: with a deferred backward
#     pending it replays (or first compiles) the captured executable — ONE
#     device program for the whole step — and writes back op outputs, leaf
#     grads, new params, and new optimizer state;
#   - ANY materialization in between (host read of a pending tensor or a
#     placeholder grad, device.synchronize, a second backward, a signature
#     mismatch at either end) aborts transparently: the segment flushes, the
#     real tape backward runs, and the step completes on the 3-program path.
#     Fallback is a counted perf event, never a numerics change — the
#     captured program reproduces the tape's gradient contract structurally
#     (stop_gradient on every non-differentiable input position), so its
#     results match the per-op path exactly.
# ---------------------------------------------------------------------------
_capture_cache: "OrderedDict[Tuple, Any]" = OrderedDict()

# events a capturable step consists of, in order; kept small — anything else
# (per-op fallbacks, extra flushes, per-node backward sweeps) marks the step
# dirty / pattern-mismatched and the controller simply keeps observing. A
# k-step gradient-accumulation cycle observes [seg, bwd] * k before its one
# optimizer.step(), so the cap bounds the capturable accumulation period
# (k <= 32) rather than sitting at the plain 2-event step.
_MAX_OBSERVED_EVENTS = 64


class _Observer:
    """Per-thread step-signature observer / arming state.

    `cycle_len` is the armed accumulation period k (1 = plain step): the
    boundary pattern [seg, bwd] repeated k times before one optimizer.step()
    is *periodic* — once armed, microsteps 0..k-2 replay as one captured
    accumulate-only program each and microstep k-1 defers into the full
    captured update. `pos` tracks the position inside the current cycle."""

    __slots__ = ("events", "dirty", "prev", "stable", "armed", "cycle_len",
                 "pos")

    def __init__(self):
        self.events: List[Tuple] = []
        self.dirty = False
        self.prev: Optional[Tuple] = None
        self.stable = 0
        self.armed: Optional[Tuple] = None  # (seg_sig, tape_key, opt_fp)
        self.cycle_len = 1
        self.pos = 0


def _disarm(obs: "_Observer"):
    obs.armed, obs.prev, obs.stable = None, None, 0
    obs.cycle_len, obs.pos = 1, 0


class _DeferredStep:
    """One backward deferred between loss.backward() and optimizer.step().

    `grad_prev_vals` is None for a plain step; for the final microstep of an
    accumulation cycle it holds each leaf's k-1-step partial grad sum — a
    program input of the captured update, and the value the abort path
    restores before re-running the real sweep."""

    __slots__ = (
        "segment", "stub_seg", "root", "seg_sig", "tape_key",
        "leaves", "leaf_slots", "leaf_grads", "expected_opt_fp",
        "grad_prev_vals",
    )


class _CaptureEntry:
    """One compiled whole-step executable plus its slot bookkeeping.

    Everything here is structural (slot indices, plan closures, optimizer
    hyper floats) — no tensors or arrays are pinned, so a cached entry
    outlives any particular model instance with the same step signature."""

    __slots__ = ("exe", "param_idx", "extra_idx", "param_slots",
                 "extra_slots", "rest_slots", "warmed", "rescue",
                 # fused numerics telemetry (FLAGS_telemetry): the traced
                 # program carries one extra stacked vector output
                 "telemetry",
                 # async host pipeline: the in-flight background AOT
                 # compile (FLAGS_eager_async_compile); steps arriving
                 # before it finishes resolve on the 3-program path
                 "pending",
                 # static-analysis surface: the raw (unjitted) step fn, the
                 # arg ShapeDtypeStructs of the first replay, and whether
                 # params/state were donated — captured_step_program()
                 # retraces these for the memory planner without compiling
                 "step_fn", "arg_specs", "donated",
                 # proof-carrying parity (analysis.equivalence): the
                 # independently-built 3-program reference composition and
                 # the EquivalenceCertificate the FLAGS_check_programs=2
                 # gate produced before the first donated replay
                 "ref_fn", "certificate",
                 # planner-guided remat (analysis.plan): the RematPlan this
                 # build applied (or proved empty), None when FLAGS_memory_plan
                 # did not ask for one
                 "mem_plan",
                 # mesh-aware capture (FLAGS_eager_capture_sharded): the jax
                 # Mesh the executable was jitted against (structural —
                 # devices, not user buffers), the flat per-invar
                 # PartitionSpecs fed to the per-shard analyzer, and the
                 # per-position donation_safety verdicts recorded at build;
                 # all None for a single-chip capture
                 "mesh", "in_specs", "verdicts", "__weakref__")


class _CaptureIneligible(Exception):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _capture_mesh(rec) -> Optional[Any]:
    """Mesh of a deferred step's leaves when mesh-aware capture applies:
    the first leaf whose committed value carries a multi-device
    NamedSharding names it (shard_params / fleet.distributed_train_step
    placement), else None — single-chip capture, the pre-mesh contract.
    FLAGS_eager_capture_sharded=0 pins the single-chip path."""
    if not flags.flag("eager_capture_sharded"):
        return None
    from jax.sharding import NamedSharding

    for t in rec.leaves:
        sh = getattr(t._value, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh.devices.size > 1:
            return sh.mesh
    return None


def _mesh_axes(mesh) -> Dict[str, int]:
    return dict(zip((str(a) for a in mesh.axis_names),
                    (int(s) for s in mesh.devices.shape)))


def _mesh_tag(mesh) -> Optional[str]:
    """Compact mesh label for attribution keys / capture state / emits:
    'dp2mp2' on a dp2×mp2 mesh (size-1 axes elided)."""
    if mesh is None:
        return None
    return "".join(
        f"{a}{s}" for a, s in _mesh_axes(mesh).items() if s > 1) or None


def _mesh_fingerprint(mesh, rec) -> Optional[Tuple]:
    """The capture cache key's mesh/spec element: mesh axes/shape plus each
    leaf's committed PartitionSpec. A respec (shard_params, an elastic
    rescale, a topology change) re-captures under a fresh key instead of
    replaying a stale layout; None single-chip keeps pre-mesh keys
    unchanged."""
    if mesh is None:
        return None
    from jax.sharding import NamedSharding

    specs = []
    for t in rec.leaves:
        sh = getattr(t._value, "sharding", None)
        specs.append(sh.spec if isinstance(sh, NamedSharding) else None)
    return (tuple(_mesh_axes(mesh).items()), tuple(specs))


def _mesh_ladder_tag() -> Optional[Tuple]:
    """Mesh component of the degradation-ladder key: captured → lazy →
    per-op demotion is tracked per (step signature, mesh), so a fault
    history earned on one topology never gates another — a post-rescale
    world re-earns (or re-loses) capture on its own record."""
    try:
        from ..parallel.topology import get_mesh

        mesh = get_mesh()
    except Exception:
        return None
    if mesh is None or mesh.devices.size <= 1:
        return None
    return (tuple(str(a) for a in mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape))


def _ladder_key(sig):
    try:
        return hash((sig, _mesh_ladder_tag()))
    except TypeError:
        return hash(sig)


def _capture_on() -> bool:
    # FLAGS_check_nan_inf needs the per-flush finite scan, which the
    # captured 1-program replay bypasses — checking runs lazy at 3 programs
    return (
        bool(flags.flag("eager_lazy_dispatch"))
        and bool(flags.flag("eager_step_capture"))
        and not flags.flag("check_nan_inf")
    )


def _mem_plan_on() -> bool:
    # planner-guided remat for the captured step: FLAGS_memory_plan=auto
    # plans against FLAGS_memory_budget_mb (a budget of 0 keeps the linter
    # semantics — nothing to optimize against)
    return (
        str(flags.flag("memory_plan")) == "auto"
        and float(flags.flag("memory_budget_mb")) > 0
    )


def _telemetry_on() -> bool:
    # fused numerics telemetry (paddle.profiler.attribution): changes the
    # traced step/update program (one extra stacked output), so it keys
    # the capture cache exactly like the rescue sentinel
    return bool(flags.flag("telemetry"))


def _observer() -> _Observer:
    obs = getattr(_tls, "observer", None)
    if obs is None:
        obs = _Observer()
        _tls.observer = obs
    return obs


def _observe_event(ev: Tuple):
    if not _capture_on():
        return
    obs = _observer()
    if len(obs.events) < _MAX_OBSERVED_EVENTS:
        obs.events.append(ev)
    else:
        obs.dirty = True


def _observe_op_program():
    # called from dispatch._count_program on every per-op launch; a step
    # containing per-op programs is not capturable as one executable
    obs = getattr(_tls, "observer", None)
    if obs is not None:
        obs.dirty = True


def _capture_fallback(reason: str):
    from . import dispatch

    dispatch._counters["capture_fallbacks"] += 1
    rs = dispatch._counters["capture_fallback_reasons"]
    rs[reason] = rs.get(reason, 0) + 1
    dispatch._emit("capture", site="captured", phase="fallback",
                   reason=reason)


def _opt_fingerprint(opt) -> Optional[Tuple]:
    """Hashable identity of the optimizer part of a step signature: rule
    type + global AND per-param hypers + weight decay + the grad-clip
    fingerprint + the ids of the params that will be updated. Per-param
    overrides (e.g. AdamW's apply_decay_param_fun exclusions) are baked
    into the compiled executable, so they MUST key it — same convention as
    _apply_fused's _jit_update_cache key. lr VALUE is excluded (schedulers
    may vary it per step; it is a traced input of the captured program).

    The clip fingerprint is (type tag, hypers) for the three built-in clip
    configs and ("none",) for no clip — those fold into the captured trace
    as pure functions of the tape grads (nn/clip.py). A CUSTOM clip
    (anything overriding _clip) has semantics the capture cannot reproduce:
    clip_fingerprint returns None and so does this fingerprint, which keeps
    the step on the eager 3-program path.

    Deliberately NOT memoized: per-param overrides can only be validated by
    recomputing them (a memo keyed on anything cheaper replays stale
    hypers), and the per-step cost equals what _apply_fused already pays to
    rebuild per_hypers — work a captured step skips entirely."""
    from ..nn.clip import clip_fingerprint

    clip_fp = clip_fingerprint(getattr(opt, "_grad_clip", None))
    if clip_fp is None:
        return None
    upd = [
        p for p in opt._param_list()
        if not p.stop_gradient and p.grad is not None
    ]
    return (
        type(opt),
        tuple(sorted(opt._hyper().items())),
        tuple(tuple(sorted(opt._per_param_hyper(p).items())) for p in upd),
        opt._weight_decay,
        clip_fp,
        # the Pallas fused-update enablement changes the traced program
        (bool(flags.flag("pallas_fused_update")),
         bool(flags.flag("pallas_update_interpret"))),
        tuple(id(p) for p in upd),
    )


def _step_boundary(opt):
    """Fold this step's observed events into the stability counter; arm the
    controller after FLAGS_eager_capture_warmup consecutive identical
    steady-state steps."""
    obs = _observer()
    events, dirty = obs.events, obs.dirty
    obs.events, obs.dirty = [], False
    opt_fp = None
    k = len(events) // 2
    # a capturable step is PERIODIC: [seg, bwd] repeated k times before this
    # one optimizer.step(). k == 1 is the plain train step; k > 1 is k-step
    # gradient accumulation — all k forward segments share one signature and
    # all k backwards share one tape. Once armed, microsteps 0..k-2 replay
    # as one captured accumulate-only program each and microstep k-1 defers
    # into the full captured update program.
    periodic = (
        not dirty
        and k >= 1
        and len(events) == 2 * k
        and all(
            events[2 * i][0] == "seg" and events[2 * i][1] == events[0][1]
            for i in range(k)
        )
        and all(
            events[2 * i + 1][0] == "bwd" and events[2 * i + 1][1] == events[1][1]
            for i in range(k)
        )
    )
    if periodic:
        try:
            # returns None for custom grad-clip classes — the built-in
            # clips fold into the captured trace as pure functions of the
            # tape grads (nn/clip.py); custom ones keep the eager path
            opt_fp = _opt_fingerprint(opt)
        except Exception:
            opt_fp = None
    if opt_fp is None:
        _disarm(obs)
        return
    sig = (events[0][1], events[1][1], opt_fp, k)
    if sig == obs.prev:
        obs.stable += 1
    else:
        obs.prev, obs.stable = sig, 1
    armed = (
        sig if obs.stable >= int(flags.flag("eager_capture_warmup")) else None
    )
    if armed is not None:
        from . import dispatch

        if not dispatch._resilience_module().runtime.captured_tier_ok(
            _ladder_key(events[0][1])
        ):
            armed = None  # ladder demoted this signature — don't arm
    if armed is not None and obs.armed != armed:
        obs.cycle_len, obs.pos = k, 0
    obs.armed = armed


def step_capture_backward(root) -> bool:
    """run_backward's capture hook. With the controller armed and the
    pending segment + tape matching the armed signature, this backward is
    taken over by the capture machinery; returns True when the caller must
    return without sweeping.

    Plain step (cycle_len == 1) and the LAST microstep of an accumulation
    cycle: the backward is DEFERRED — the whole step resolves at
    optimizer.step() as one donated program. Accumulate-only microsteps
    (pos < cycle_len - 1): forward + backward + grad-accumulate replay HERE
    as one captured program and the grads become concrete immediately."""
    if not _capture_on():
        return False
    obs = getattr(_tls, "observer", None)
    if obs is None or obs.armed is None:
        return False
    if getattr(_tls, "capture_deferred", None) is not None:
        return False  # a second backward this step — flush path aborts it
    from . import dispatch

    seg = getattr(_tls, "segment", None)
    if seg is None or seg.flushed or not seg.ops:
        return False
    rv = root._value
    if type(rv) is not LazyRef or rv._segment is not seg or rv._concrete is not None:
        return False
    if rv.size != 1:
        return False
    seg_sig = _seg_signature(seg)
    if not dispatch._resilience_module().runtime.captured_tier_ok(
        _ladder_key(seg_sig)
    ):
        # degradation ladder demoted this step signature: stay on the
        # 3-program path until the cooldown re-promotes it
        return False
    armed_seg, armed_tape, armed_opt, cycle_len = obs.armed
    if seg_sig != armed_seg:
        _capture_fallback("signature_mismatch")
        _disarm(obs)
        return False
    seg_nodes = {id(op.node) for op in seg.ops if op.record}
    struct = dispatch._tape_structure(
        root, node_check=lambda n: n.vjp_fn is None and id(n) in seg_nodes
    )
    if struct is None:
        _capture_fallback("tape_ineligible")
        _disarm(obs)
        return False
    tape_key, order_nodes, leaves = struct
    if tape_key != armed_tape:
        _capture_fallback("tape_mismatch")
        _disarm(obs)
        return False
    if len(order_nodes) != len(seg_nodes):
        # the segment recorded differentiable ops that are NOT ancestors of
        # the loss (auxiliary outputs): a normal flush would give them vjp
        # closures for a later backward of their own, which the captured
        # replay cannot — keep such steps on the 3-program path
        _capture_fallback("non_tape_recorded_ops")
        _disarm(obs)
        return False
    # every tape leaf must be a distinct concrete external input of the
    # segment. Grad state must match the cycle position: the FIRST backward
    # of a cycle starts from grad=None (run_backward creates fresh grads),
    # later microsteps accumulate into an existing concrete grad — any other
    # mix (stale grads at cycle start, a cleared grad mid-cycle) is a
    # pattern the capture cannot reproduce and falls back.
    pos = obs.pos if cycle_len > 1 else 0
    slots: List[int] = []
    ineligible = None
    for t in leaves:
        v = t._value
        slot = None if type(v) is LazyRef else seg.ext_ids.get(id(v))
        if slot is None:
            ineligible = "leaf_ineligible"
            break
        g = t.grad
        if pos == 0:
            if g is not None:
                ineligible = "leaf_ineligible"
                break
        elif g is None or type(g._value) is LazyRef:
            ineligible = "accum_grad_ineligible"
            break
        slots.append(slot)
    if ineligible is None and len(set(slots)) != len(slots):
        ineligible = "aliased_leaves"
    if ineligible is not None:
        _capture_fallback(ineligible)
        _disarm(obs)
        return False

    if cycle_len > 1 and pos < cycle_len - 1:
        # accumulate-only microstep: replay forward + backward (+ grad
        # accumulate) as ONE captured program right now. Nothing defers; a
        # failure simply returns False and the normal flush + sweep runs.
        return _run_accum_microstep(seg, root, seg_sig, tape_key, leaves,
                                    slots, pos, obs)

    # defer: detach the pending segment (later ops open a fresh one) and
    # hand every leaf a placeholder grad whose read resolves — or aborts —
    # the captured step
    _tls.segment = None
    stub_seg = _Segment()
    rec = _DeferredStep()
    rec.segment = seg
    rec.stub_seg = stub_seg
    rec.root = root
    rec.seg_sig = seg_sig
    rec.tape_key = tape_key
    rec.leaves = leaves
    rec.leaf_slots = slots
    rec.leaf_grads = []
    rec.expected_opt_fp = armed_opt
    rec.grad_prev_vals = None
    if pos > 0:
        # final microstep of an accumulation cycle: the captured update
        # consumes the k-1 partial sums. Keep each leaf's EXISTING grad
        # tensor (eager semantics mutate it in place) but swap its value
        # for a placeholder ref so any read before optimizer.step() aborts;
        # the previous partial sums ride along for the program inputs and
        # for the abort path's restore.
        rec.grad_prev_vals = [t.grad._value for t in leaves]
        for i, t in enumerate(leaves):
            v = t._value
            ref = LazyRef(stub_seg, i, 0, tuple(v.shape), v.dtype)
            gt = t.grad
            gt._value = ref
            rec.leaf_grads.append((t, gt, ref))
    else:
        for i, t in enumerate(leaves):
            v = t._value
            ref = LazyRef(stub_seg, i, 0, tuple(v.shape), v.dtype)
            gt = _new_tensor(ref, stop_gradient=True)
            t.grad = gt
            rec.leaf_grads.append((t, gt, ref))
    _tls.capture_deferred = rec
    return True


def _accum_step_fn(plan, n_ext, leaf_slots, root_op, root_out,
                   seed_shape, seed_dtype, with_grad_in):
    """Raw accumulate-only microstep program: forward replay + whole-program
    vjp over every tape leaf (+ add into the incoming partial grad sums).
    Same gradient contract as the full captured step (_plan_capture_forward
    stop-gradients every non-diff input position), and the accumulate order
    matches the eager sweep exactly: prev + new."""
    fwd = _plan_capture_forward(plan)
    leaf_slot_set = set(leaf_slots)
    rest_slots = [s for s in range(n_ext) if s not in leaf_slot_set]

    def accum_fn(leaf_vals, grad_in, rest_vals):
        ext = [None] * n_ext
        for s, v in zip(rest_slots, rest_vals):
            ext[s] = v

        def loss_of(lv):
            e = list(ext)
            for s, v in zip(leaf_slots, lv):
                e[s] = v
            results = fwd(e)
            return results[root_op][root_out], results

        _loss, vjp, results = jax.vjp(loss_of, tuple(leaf_vals), has_aux=True)
        (g,) = vjp(jnp.ones(seed_shape, seed_dtype))
        if with_grad_in:
            g = tuple(a + b for a, b in zip(grad_in, g))
        return results, tuple(g)

    return accum_fn, rest_slots


def _run_accum_microstep(seg, root, seg_sig, tape_key, leaves, slots, pos,
                         obs) -> bool:
    """Build/replay the captured accumulate-only program for one
    armed microstep; True when it resolved the backward (grads concrete).

    The incoming partial-sum grad buffers are NOT donated: the graceful
    fallback contract (a real fault resolves the microstep on the normal
    flush + sweep path) must still be able to read them — only the k-th
    microstep's update program donates params and optimizer state."""
    from . import dispatch

    with_grad_in = pos > 0
    key = (seg_sig, tape_key, "accum", with_grad_in)
    try:
        entry = dispatch._lru_get(_capture_cache, key)
    except TypeError:
        return False
    rv = root._value
    lkey = _ladder_key(seg_sig)
    akey = f"accum:{_sig_id(seg_sig)}"
    try:
        built_fn = None
        if entry is None:
            accum_fn, rest_slots = _accum_step_fn(
                _seg_plan(seg), len(seg.ext_vals), tuple(slots),
                rv._op_index, rv._out_index, rv._shape, rv._dtype,
                with_grad_in,
            )
            entry = (jax.jit(accum_fn), rest_slots)
            built_fn = accum_fn
            dispatch._counters["capture_accum_builds"] += 1
            dispatch._lru_put(
                _capture_cache, key, entry,
                evict_counter="capture_evictions",
                cap=int(flags.flag("eager_capture_cache_size")),
            )
            fresh = True
        else:
            fresh = False
        jfn, rest_slots = entry
        ext = seg.ext_vals
        args = (
            tuple(ext[s] for s in slots),
            tuple(leaves[i].grad._value for i in range(len(leaves)))
            if with_grad_in else (),
            tuple(ext[s] for s in rest_slots),
        )
        if built_fn is not None:
            # attribution cost registry: the accumulate-only microstep
            # program registers at build time (spec-only thunk; the plan
            # closure pins no user data)
            specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype), args
            )
            _register_program(
                akey, "accum",
                jaxpr_thunk=(lambda _fn=built_fn, _s=specs:
                             jax.make_jaxpr(_fn)(*_s)),
            )
        t0 = time.perf_counter()
        out = dispatch._rexec(
            "captured", lambda: jfn(*args), fresh=fresh, ladder_key=lkey,
        )
        dt = _add_time("compile_time_ms" if fresh else "replay_time_ms", t0)
        if not fresh:
            _note_program(akey, "accum", dt)
    except BaseException as e:
        if not isinstance(e, Exception):
            raise
        # any build/compile/runtime error: counted, then the normal flush +
        # tape-backward path resolves this microstep with identical numerics
        _capture_fallback("accum_error")
        _disarm(obs)
        return False
    results, g_out = out
    dispatch._count_program("captured")
    dispatch._counters["capture_accum_replays"] += 1
    dispatch._emit("capture", site="captured", phase="accum_replay",
                   pos=pos)

    # the captured program subsumes the segment flush (same write-back as
    # _run_captured, minus vjp closures — a second backward raises)
    seg.flushed = True
    if getattr(_tls, "segment", None) is seg:
        _tls.segment = None
    for op, outs in zip(seg.ops, results):
        for (ref, t), val in zip(op.outs, outs):
            ref._concrete = val
            if t._value is ref:
                t._value = val
        if op.record:
            op.node.out_avals = [(tuple(v.shape), v.dtype) for v in outs]
    seg.ops = []
    from .tensor import Tensor

    for t, g in zip(leaves, g_out):
        if with_grad_in:
            # eager parity: the sweep mutates the existing grad tensor in
            # place (t.grad._value = prev + new) — same object identity
            t.grad._value = g
        else:
            t.grad = Tensor(g, stop_gradient=True)
    obs.pos = pos + 1
    return True


def _abort_capture(reason: str, fallback: bool = True):
    """Resolve a deferred captured-step backward on the normal 3-program
    path: flush the segment (which populates the tape's vjp closures), run
    the real backward, and fill the placeholder grads. Numerics match the
    never-captured path exactly; the event is counted as a capture
    fallback and the controller re-observes from scratch.

    `fallback=False` is the async-compile pending resolution: the step
    resolves the same safe way, but it is NOT a capture fallback — the
    controller stays armed so the next occurrence joins the background
    build (counted separately as capture_build_pending_steps)."""
    from . import dispatch

    rec = getattr(_tls, "capture_deferred", None)
    if rec is None:
        return
    _tls.capture_deferred = None
    rec.stub_seg.flushed = True
    obs = getattr(_tls, "observer", None)
    if fallback:
        _capture_fallback(reason)
        if obs is not None:
            _disarm(obs)
            obs.events, obs.dirty = [], False
    elif obs is not None:
        obs.events, obs.dirty = [], False
        obs.pos = 0  # the cycle completed on the 3-program path
    # Reproduce the eager ordering exactly: the backward writes grads FIRST
    # (a fresh tensor for a plain step; in-place accumulation into the
    # restored k-1 partial sum for an accumulation cycle), any later user
    # write/clear of t.grad then replaced it. So: run the sweep over the
    # restored grad state, give the placeholder its computed value (whoever
    # saved p.grad at backward() time sees the real gradient), and put back
    # the user's replacement if there was one.
    saved = [(t, gt, ref, t.grad) for t, gt, ref in rec.leaf_grads]
    if rec.grad_prev_vals is None:
        for t, _gt, _ref, _cur in saved:
            t.grad = None
    else:
        # final accumulation microstep: restore the partial sums so the
        # sweep accumulates into them (t.grad._value = prev + new), exactly
        # what the eager path would have produced
        for (t, gt, _ref, _cur), prev in zip(saved, rec.grad_prev_vals):
            gt._value = prev
            t.grad = gt
    if not rec.segment.flushed:
        _flush(rec.segment, "capture_abort")
    root = rec.root
    seed = jnp.ones_like(materialize(root._value))
    if not dispatch._try_compiled_tape_backward(root, seed):
        dispatch.run_backward([root])
    for t, gt, ref, cur in saved:
        real = t.grad
        val = (
            real._value if real is not None
            else jnp.zeros(ref._shape, ref._dtype)
        )
        ref._concrete = val
        gt._value = val
        # keep the object identity handed out at backward() time, unless
        # the user replaced/cleared t.grad after the deferral
        t.grad = gt if cur is gt else cur


def _plan_capture_forward(plan, stop_gradients=True):
    """Pure replay of a segment plan for whole-step capture.

    The tape's gradient contract is reproduced structurally: gradient flows
    ONLY through recorded ops' differentiable input positions (exactly the
    positions the per-op path takes jax.vjp over); every other array input
    is wrapped in lax.stop_gradient, so jax.vjp over this whole replay
    equals the composition of the per-op vjps the tape would have applied.

    ``stop_gradients=False`` replays the same plan WITHOUT the gradient
    shaping — value-level identical (stop_gradient is an identity on
    values), used as program 1 of the 3-program reference composition the
    equivalence prover certifies the capture against."""

    def fwd(ext):
        results = []
        for fn, kw, bindings, diff_idx, record in plan:
            vals = []
            for j, (kind, a, b) in enumerate(bindings):
                if kind == _EXT:
                    v = ext[a]
                elif kind == _RES:
                    v = results[a][b]
                else:
                    vals.append(a)  # python literal — no gradient path
                    continue
                if stop_gradients and (not record or j not in diff_idx):
                    v = jax.lax.stop_gradient(v)
                vals.append(v)
            out = fn(*vals, **kw)
            results.append(list(out) if isinstance(out, (tuple, list)) else [out])
        return results

    return fwd


def _build_captured_step(rec: _DeferredStep, opt) -> _CaptureEntry:
    """Trace + jit the whole step — forward plan, loss vjp, grad clip,
    optimizer update — as ONE program with params and optimizer state
    donated."""
    from ..nn.clip import capture_clip_fn

    seg = rec.segment
    leaves = rec.leaves
    clip = getattr(opt, "_grad_clip", None)
    clip_fn = capture_clip_fn(clip)
    if clip is not None and clip_fn is None:
        # custom clip subclass: semantics the pure fold cannot cover
        raise _CaptureIneligible("grad_clip_custom")
    leaf_pos = {id(t): i for i, t in enumerate(leaves)}
    params = [
        p for p in opt._param_list()
        if not p.stop_gradient and p.grad is not None
    ]
    for p in params:
        if id(p) not in leaf_pos:
            # a param carries a grad the deferred tape did not produce
            # (stale grad from an earlier step): updating it from inside
            # the capture would diverge from the eager path
            raise _CaptureIneligible("stale_or_external_grad")
    param_idx = [leaf_pos[id(p)] for p in params]
    pset = set(param_idx)
    extra_idx = [i for i in range(len(leaves)) if i not in pset]
    param_slots = [rec.leaf_slots[i] for i in param_idx]
    extra_slots = [rec.leaf_slots[i] for i in extra_idx]
    n_ext = len(seg.ext_vals)
    leaf_slot_set = set(param_slots) | set(extra_slots)
    rest_slots = [s for s in range(n_ext) if s not in leaf_slot_set]

    plan = _seg_plan(seg)
    fwd = _plan_capture_forward(plan)
    rv = rec.root._value
    root_op, root_out = rv._op_index, rv._out_index
    seed_shape, seed_dtype = rv._shape, rv._dtype

    # the ONE shared definition of the traced optimizer math — identical to
    # what Optimizer._apply_fused jits, so captured and 3-program steps
    # cannot drift apart (it pins no optimizer instance)
    from ..optimizer.optimizer import make_fused_update
    from ..resilience import rescue as _rescue

    rescue_on = _rescue.active()
    tele_on = _telemetry_on()
    apply_update = make_fused_update(opt, params, sentinel=rescue_on,
                                     telemetry=tele_on)
    has_grad_in = rec.grad_prev_vals is not None

    def make_step_fn(planned_loss=None):
        def step_fn(p_vals, sts, lr, extra_vals, rest_vals, gp_in, gx_in):
            ext = [None] * n_ext
            for s, v in zip(rest_slots, rest_vals):
                ext[s] = v

            if planned_loss is not None:
                # planner-guided remat: the loss path replays as the sliced
                # jax.checkpoint stages the RematPlan chose (same eqns, same
                # order — bitwise-equal values, recomputed in the backward)
                def loss_of(dp, dx):
                    return planned_loss(dp, dx, tuple(rest_vals))
            else:
                def loss_of(dp, dx):
                    e = list(ext)
                    for s, v in zip(param_slots, dp):
                        e[s] = v
                    for s, v in zip(extra_slots, dx):
                        e[s] = v
                    results = fwd(e)
                    return results[root_op][root_out], results

            loss_val, vjp, results = jax.vjp(
                loss_of, tuple(p_vals), tuple(extra_vals), has_aux=True
            )
            del loss_val  # the loss is results[root_op][root_out]
            gp, gx = vjp(jnp.ones(seed_shape, seed_dtype))
            if has_grad_in:
                # accumulation: fold this microstep's grads into the k-1-step
                # partial sums, prev + new — the eager sweep's accumulate order
                gp = tuple(a + b for a, b in zip(gp_in, gp))
                gx = tuple(a + b for a, b in zip(gx_in, gx))
            # grad clipping (built-in configs only): the SAME pure function the
            # eager Optimizer.step() applies between backward and the fused
            # update (nn/clip.py _pure), over the param grads in param-list
            # order — global-norm reduction order and all. The update (and the
            # non-finite sentinel, when on) sees the CLIPPED grads; the grads
            # written back to p.grad stay unclipped, exactly like the eager
            # path, which never writes the clipped values back.
            upd_g = tuple(clip_fn(list(gp))) if clip_fn is not None else gp
            # numeric-rescue sentinel and fused telemetry (paddle.resilience /
            # paddle.profiler.attribution): extra OUTPUTS of the SAME program —
            # the sentinel scalar where-gates the update in-program, the
            # telemetry vector stacks per-param grad/param/update norms — so
            # both add zero program launches and never perturb the update math
            upd = apply_update(p_vals, upd_g, lr, sts)
            new_p, new_s = upd[0], upd[1]
            return (results, gp, gx, tuple(new_p), tuple(new_s)) + tuple(upd[2:])

        return step_fn

    # the 3-program reference composition (FLAGS_check_programs=2): what the
    # lazy tier would have executed, assembled from INDEPENDENT builds of the
    # same three programs — (1) the segment flush's forward (the plan replay
    # with no gradient shaping), (2) the tape backward (jax.vjp over the
    # stop_gradient-shaped replay — the per-op-vjp composition contract
    # documented on _plan_capture_forward), (3) the same grad-clip fold and
    # fused optimizer update Optimizer.step() jits. The equivalence prover
    # certifies the captured 1-program step against this BEFORE the first
    # donated replay; never compiled, only traced.
    ref_fwd_plain = _plan_capture_forward(plan, stop_gradients=False)
    ref_clip_fn = capture_clip_fn(clip)
    ref_apply = make_fused_update(opt, params, sentinel=rescue_on,
                                  telemetry=tele_on)

    def ref_step_fn(p_vals, sts, lr, extra_vals, rest_vals, gp_in, gx_in):
        ext = [None] * n_ext
        for s, v in zip(rest_slots, rest_vals):
            ext[s] = v
        e1 = list(ext)
        for s, v in zip(param_slots, p_vals):
            e1[s] = v
        for s, v in zip(extra_slots, extra_vals):
            e1[s] = v
        results = ref_fwd_plain(e1)  # program 1: the flush's forward

        def loss_of(dp, dx):
            e = list(ext)
            for s, v in zip(param_slots, dp):
                e[s] = v
            for s, v in zip(extra_slots, dx):
                e[s] = v
            return fwd(e)[root_op][root_out]

        _loss, vjp = jax.vjp(loss_of, tuple(p_vals), tuple(extra_vals))
        gp, gx = vjp(jnp.ones(seed_shape, seed_dtype))  # program 2: backward
        if has_grad_in:
            gp = tuple(a + b for a, b in zip(gp_in, gp))
            gx = tuple(a + b for a, b in zip(gx_in, gx))
        upd_g = tuple(ref_clip_fn(list(gp))) if ref_clip_fn is not None else gp
        upd = ref_apply(p_vals, upd_g, lr, sts)  # program 3: fused update
        return (results, gp, gx, tuple(upd[0]), tuple(upd[1])) + tuple(upd[2:])

    entry = _CaptureEntry()
    entry.ref_fn = ref_step_fn
    entry.certificate = None
    entry.rescue = rescue_on
    entry.telemetry = tele_on
    # donate params + optimizer state: XLA reuses their HBM buffers for the
    # updated values (the compile_train_step discipline, earned by plain
    # eager code). Batch data / extra leaves are NOT donated — they are
    # caller-owned and reused across steps. FLAGS_eager_capture_donate=0
    # opts out (keeps the 1-program step, drops in-place reuse) for code
    # that holds aliases of param/state buffers across steps.
    donate = (0, 1) if flags.flag("eager_capture_donate") else ()
    entry.arg_specs = None  # recorded at first replay (sharded: at build)
    entry.donated = bool(donate)
    entry.param_idx = param_idx
    entry.extra_idx = extra_idx
    entry.param_slots = param_slots
    entry.extra_slots = extra_slots
    entry.rest_slots = rest_slots
    entry.warmed = False
    entry.pending = None
    entry.mem_plan = None
    entry.mesh = None
    entry.in_specs = None
    entry.verdicts = None

    # mesh-aware capture (FLAGS_eager_capture_sharded): params carrying
    # multi-device NamedShardings get the whole step jitted as the same one
    # SPMD program ShardedTrainStep compiles — declared in/out shardings
    # from parallel.sharding param/state specs, donation gated on the
    # per-shard proof below
    mesh = _capture_mesh(rec)
    in_shardings = out_shardings = None
    if mesh is not None:
        if _mesh_axes(mesh).get("pp", 1) > 1:
            # the pipeline schedule is a shard_map region, and jax 0.4.x
            # cannot differentiate through shard_map with auto axes (the
            # scalar-residual partial-eval bug documented in _jax_compat):
            # refuse structurally instead of dying mid-trace
            from .._jax_compat import shardmap_autodiff_limitation

            raise _CaptureIneligible(
                shardmap_autodiff_limitation() or "pipelined_mesh")
        entry.mesh = mesh
        cap_p, cap_s, cargs = _capture_args(rec, opt, entry)
        entry.arg_specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype), cargs)
        from ..parallel.sharding import capture_step_shardings

        p_sh, st_sh = capture_step_shardings(cap_p, cap_s, mesh)
        # lr / batch / rest / grad-in positions stay unconstrained (None):
        # a committed dp-sharded batch keeps its layout, an uncommitted one
        # stays free — the same caller-placed-batch contract as
        # ShardedTrainStep, so matched specs give bitwise-equal reductions
        in_shardings = (tuple(p_sh), tuple(st_sh)) + (None,) * 5
        # updated params/state pinned to the INPUT layout: donation aliases
        # per-shard and the next replay's spec fingerprint is stable. The
        # param grads gp are pinned to the param shardings too — jit's
        # donation aliasing greedily pairs donated inputs with ANY
        # same-logical-shape output, and an unpinned gp whose propagated
        # layout differs from the param's fails the XLA per-shard aliasing
        # size check at runtime
        out_shardings = (
            (None, tuple(p_sh), None, tuple(p_sh), tuple(st_sh))
            + (None,) * (int(rescue_on) + int(tele_on)))
        flat_sh = jax.tree_util.tree_leaves((tuple(p_sh), tuple(st_sh)))
        n_flat = len(jax.tree_util.tree_leaves(entry.arg_specs))
        entry.in_specs = ([s.spec for s in flat_sh]
                          + [None] * (n_flat - len(flat_sh)))

    planned_loss = None
    if _mem_plan_on():
        # planner-guided remat (FLAGS_memory_plan=auto): slice this step's
        # loss replay into jax.checkpoint stages chosen against
        # FLAGS_memory_budget_mb. Every op output of the capture escapes to
        # the host write-back (the _flush contract), so the planner usually
        # proves there is nothing profitable to cut and returns an identity
        # plan — honesty over wishful savings. A failed BUILD aborts the
        # capture through the ladder as a counted reason (the CUDA Graphs
        # bail-out contract), never a half-applied plan.
        try:
            entry.mem_plan, planned_loss = _build_capture_plan(
                rec, opt, entry, make_step_fn, fwd,
                n_ext, param_slots, extra_slots, rest_slots,
                root_op, root_out)
        except Exception as e:
            from ..analysis import plan as _plan_mod

            _plan_mod.record_failure("capture", e)
            raise _CaptureIneligible("memory_plan_failed")
    step_fn = make_step_fn(planned_loss)
    entry.step_fn = step_fn
    if mesh is not None and donate:
        # per-shard donation gate: donation stays on ONLY when the
        # analysis.sharding donation_safety pass proves EVERY donated flat
        # position at per-shard shapes; anything unproven demotes this
        # build to non-donated replay — a counted reason
        # (capture_donation_fallbacks), not a capture fallback: the step
        # still replays as 1 program, only in-place reuse is given up
        donate = _prove_sharded_donation(entry, mesh, donate)
        entry.donated = bool(donate)
    if mesh is not None:
        if donate:
            # jax 0.4.x donation sharp edge: the donation matcher compares a
            # donated input's PER-SHARD shape against an unpinned output's
            # GLOBAL shape, so e.g. a [16,4] weight sharded to [8,4] aliases
            # a [8,4] logits output and XLA's runtime per-shard size check
            # then faults the replay. Pin EVERY output before donating:
            # probe-compile non-donated (propagation chooses the unpinned
            # outputs' layouts), then rebuild with the inferred shardings —
            # the second compile propagates identically, aliasing now pairs
            # per-shard against per-shard
            probe = jax.jit(
                step_fn, in_shardings=in_shardings,
                out_shardings=out_shardings,
            ).lower(*entry.arg_specs).compile()
            out_shardings = probe.output_shardings
        entry.exe = jax.jit(step_fn, in_shardings=in_shardings,
                            out_shardings=out_shardings,
                            donate_argnums=donate)
    else:
        entry.exe = jax.jit(step_fn, donate_argnums=donate)
    return entry


def _prove_sharded_donation(entry: _CaptureEntry, mesh, donate):
    """Build-time per-shard donation proof of a mesh-aware capture: trace
    the candidate step (no compile), run the analysis.sharding
    donation_safety pass over the _ShardInliner-derived context, and keep
    ``donate`` only when every donated position's verdict is proven. The
    verdicts land on the entry for graph_lint / statusz; a tracing failure
    counts as unproven — donation is a proof-carrying optimization here,
    never a default."""
    from . import dispatch

    try:
        roles, donated_idx = _capture_arg_roles(entry)
        closed = jax.make_jaxpr(entry.step_fn)(*entry.arg_specs)
        from ..analysis import memory as _amem
        from ..analysis import sharding as _ashard

        ctx = _ashard.shard_context(
            closed, roles, mesh=mesh, in_specs=entry.in_specs,
            donated=donated_idx, source="captured-sharded")
        entry.verdicts = _amem.donation_verdicts(ctx)
        proven = bool(entry.verdicts) and all(
            v["proven"] for v in entry.verdicts)
    except Exception:
        entry.verdicts = None
        proven = False
    if proven:
        return donate
    dispatch._counters["capture_donation_fallbacks"] += 1
    dispatch._emit("capture", site="captured", phase="donation_fallback",
                   mesh=_mesh_tag(mesh))
    return ()


def _build_capture_plan(rec, opt, entry, make_step_fn, fwd, n_ext,
                        param_slots, extra_slots, rest_slots,
                        root_op, root_out):
    """Build (and maybe bind) a RematPlan for one capture build. Returns
    ``(plan, planned_loss)`` where planned_loss is None when the plan has no
    cuts. The measure oracle re-traces the FULL candidate step (forward,
    vjp, clip, fused update, donation) and reads the planner's peak — the
    recorded before/after figures are exact est_peak_hbm_mb values, not a
    side model."""
    from .. import analysis
    from ..analysis import memory as _memory
    from ..analysis import plan as _plan_mod

    _p, _s, cargs = _capture_args(rec, opt, entry)
    specs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype), cargs)
    entry.arg_specs = specs
    p_specs, _s_specs, _lr, extra_specs, rest_specs, _gp, _gx = specs
    res_tree = [None]

    def loss_pure(dp, dx, rest_vals):
        # the capture's loss path with every array input explicit, flat
        # outputs (loss first, then every op output — they all escape)
        ext = [None] * n_ext
        for s, v in zip(rest_slots, rest_vals):
            ext[s] = v
        for s, v in zip(param_slots, dp):
            ext[s] = v
        for s, v in zip(extra_slots, dx):
            ext[s] = v
        results = fwd(ext)
        flat, tree = jax.tree_util.tree_flatten(results)
        res_tree[0] = tree
        return (results[root_op][root_out], *flat)

    loss_closed = jax.make_jaxpr(loss_pure)(
        tuple(p_specs), tuple(extra_specs), tuple(rest_specs))

    def bind_loss(flat_fn):
        def planned_loss(dp, dx, rest_vals):
            flat, _ = jax.tree_util.tree_flatten(
                (tuple(dp), tuple(dx), tuple(rest_vals)))
            outs = flat_fn(*flat)
            results = jax.tree_util.tree_unflatten(res_tree[0], outs[1:])
            return outs[0], results
        return planned_loss

    roles, donated = _capture_arg_roles(entry)

    def measure(flat_fn):
        pl = bind_loss(flat_fn) if flat_fn is not None else None
        closed = jax.make_jaxpr(make_step_fn(pl))(*specs)
        if entry.mesh is not None:
            # mesh-aware capture: the plan is chosen against PER-DEVICE
            # peak — the _ShardInliner-derived context sizes every buffer
            # at its shard shape, so FLAGS_memory_budget_mb means one
            # chip's HBM on a mesh, not the global footprint
            from ..analysis.sharding import shard_context

            ctx = shard_context(closed, roles, mesh=entry.mesh,
                                in_specs=entry.in_specs, donated=donated,
                                source="captured-step")
        else:
            ctx = analysis.Context(closed, roles, "captured-step",
                                   donated=donated)
        return _memory.plan_memory(ctx).peak_bytes

    budget = int(float(flags.flag("memory_budget_mb")) * (1 << 20))
    plan = _plan_mod.build_remat_plan(
        loss_closed, budget_bytes=budget, measure=measure, source="capture")
    if plan.has_cuts:
        return plan, bind_loss(plan.bind())
    return plan, None


def _aot_compile(exe, specs):
    """Background-thread half of an async capture build: trace + XLA-compile
    the jitted step over abstract avals (jax AOT). Returns the Compiled
    executable; donation is part of the lowering, so the later replay on the
    main thread consumes its buffers exactly like a plain jit call."""
    import warnings

    with warnings.catch_warnings():
        # backends without real donation (CPU) warn at compile time
        warnings.filterwarnings("ignore", message=".*onated buffer.*")
        return exe.lower(*specs).compile()


def _capture_args(rec: _DeferredStep, opt, entry: _CaptureEntry):
    """The concrete argument tuple of one captured-step replay (also used at
    async-build submission time to derive the AOT lowering avals)."""
    seg = rec.segment
    leaves = rec.leaves
    params = [leaves[i] for i in entry.param_idx]
    ext = seg.ext_vals
    sched = getattr(opt, "_offload_sched", None)
    if sched is not None:
        # host-offload: parked accumulator groups must be device arrays
        # before they feed the captured executable (the wait is booked as
        # the scheduler's blocked time)
        sched.ensure_resident(opt, params)
    states = []
    for p in params:
        st = opt._accumulators.get(id(p))
        if st is None:
            st = opt._create_state(p)
        states.append(st)
    lr = jnp.asarray(opt.get_lr(), dtype=jnp.float32)
    if rec.grad_prev_vals is None:
        gp_in, gx_in = (), ()
    else:
        gp_in = tuple(rec.grad_prev_vals[i] for i in entry.param_idx)
        gx_in = tuple(rec.grad_prev_vals[i] for i in entry.extra_idx)
    return params, states, (
        tuple(ext[s] for s in entry.param_slots),
        tuple(states),
        lr,
        tuple(ext[s] for s in entry.extra_slots),
        tuple(ext[s] for s in entry.rest_slots),
        gp_in,
        gx_in,
    )


def _capture_arg_roles(entry: _CaptureEntry):
    """(invar roles, donated flat invar indices) of the captured step
    program traced from entry.arg_specs — donate_argnums=(0, 1) donates the
    leaves of the param and optimizer-state pytrees, which flatten first."""
    leaves = jax.tree_util.tree_leaves
    p_specs, s_specs, _lr, extra, rest, gp_in, gx_in = entry.arg_specs
    n_p, n_s = len(leaves(p_specs)), len(leaves(s_specs))
    roles = (
        [("param", f"param{i}") for i in range(n_p)]
        + [("buffer", f"opt_state{i}") for i in range(n_s)]
        + [("arg", "lr")]
        + [("feed", f"batch{i}") for i in range(len(leaves(extra)))]
        + [("arg", f"ext{i}") for i in range(len(leaves(rest)))]
        + [("arg", f"grad_in{i}")
           for i in range(len(leaves(gp_in)) + len(leaves(gx_in)))]
    )
    donated = tuple(range(n_p + n_s)) if entry.donated else ()
    return roles, donated


def captured_step_program():
    """(closed jaxpr, donated invar indices, invar roles) of the most
    recently replayed captured whole-step executable on this thread, or
    None when no capture has replayed yet (or its cache entry has been
    evicted and collected). Trace-only (no compile) — feeds the
    paddle_tpu.analysis.memory planner, bench.py's memory trajectory, and
    paddle.profiler.measure_programs."""
    ref = getattr(_tls, "last_capture_entry", None)
    entry = ref() if ref is not None else None
    if entry is None or entry.arg_specs is None:
        return None
    closed = jax.make_jaxpr(entry.step_fn)(*entry.arg_specs)
    roles, donated = _capture_arg_roles(entry)
    return closed, donated, roles


def captured_step_shard_info():
    """``(mesh, flat per-invar PartitionSpecs, mesh axes dict)`` of the most
    recently replayed SHARDED captured step on this thread, or None (no
    sharded replay yet, or the cache entry was evicted and collected).
    Pairs with :func:`captured_step_program` —
    ``analysis.sharding.captured_step_context`` rebuilds the per-shard
    analyzer context from the two."""
    ref = getattr(_tls, "last_capture_entry", None)
    entry = ref() if ref is not None else None
    if entry is None or entry.mesh is None or entry.arg_specs is None:
        return None
    return entry.mesh, list(entry.in_specs or []), _mesh_axes(entry.mesh)


def captured_step_donation_verdicts():
    """Per-position donation_safety verdicts recorded at the last replayed
    capture's build (``analysis.memory.donation_verdicts`` records —
    position / role / proven / diagnostics), or None when the last replay
    was single-chip or nothing has replayed. ``graph_lint --mesh`` prints
    these per position in its JSON record."""
    ref = getattr(_tls, "last_capture_entry", None)
    entry = ref() if ref is not None else None
    return None if entry is None else entry.verdicts


class _CapturedStepHandle:
    """Routable stand-in for this thread's last replayed captured step:
    ``graph_lint --mesh`` and ``analysis.sharding.check_sharded_step``
    dispatch on ``_captured_step`` and rebuild the per-shard context from
    the capture registry — the handle itself pins nothing."""

    _captured_step = True


def captured_step_handle() -> _CapturedStepHandle:
    return _CapturedStepHandle()


def _check_captured_donation(entry: _CaptureEntry, params, states):
    # the static traced-program pass runs once per capture build (warmed is
    # set only after a successful replay, so a raising verdict re-proves)
    from ..analysis import memory as _memory

    roles, donated = _capture_arg_roles(entry)
    _memory.donation_gate(
        params, states,
        lambda: jax.make_jaxpr(entry.step_fn)(*entry.arg_specs),
        roles, donated, "captured-step",
        static_diags=[] if entry.warmed else None,
    )


def _certify_capture_equivalence(entry: _CaptureEntry):
    """FLAGS_check_programs=2 parity proof: structurally certify the
    captured 1-program step ≡ the 3-program composition (and, sharded, the
    donated executable's program against its non-donated probe trace — the
    same step_fn, so the one certificate covers both) BEFORE the first
    donated replay. Outcomes:

      certified  — counted; the certificate lands on the entry (statusz)
      divergent  — ProgramVerificationError with the structured
                   first-divergence diagnostic; the caller resolves the
                   step on the safe 3-program path, then surfaces it
      unprovable — a tracing/canonicalization failure is NOT a proof of
                   divergence: fall through the counted ladder
                   (_CaptureIneligible) instead of crashing the step
    """
    from . import dispatch
    from ..analysis import ProgramVerificationError
    from ..analysis import equivalence as _eq

    dispatch._counters["capture_equivalence_checks"] += 1
    try:
        cap = jax.make_jaxpr(entry.step_fn)(*entry.arg_specs)
        ref = jax.make_jaxpr(entry.ref_fn)(*entry.arg_specs)
        cert = _eq.prove_equivalent(
            cap, ref, label_a="captured-step",
            label_b="3-program-composition", source="captured-step")
    except Exception as e:
        dispatch._counters["capture_equivalence_unprovable"] += 1
        dispatch._emit("capture", site="captured", phase="equivalence",
                       result="unprovable", error=type(e).__name__)
        raise _CaptureIneligible("equivalence_unprovable")
    entry.certificate = cert
    if not cert.equivalent:
        dispatch._counters["capture_equivalence_divergences"] += 1
        dispatch._emit("capture", site="captured", phase="equivalence",
                       result="divergent", mesh=_mesh_tag(entry.mesh))
        raise ProgramVerificationError(
            "captured step is not provably equivalent to the 3-program "
            f"composition: {cert.summary()}",
            [d for d in [cert.divergence] if d is not None])
    dispatch._counters["capture_equivalence_certified"] += 1
    dispatch._emit("capture", site="captured", phase="equivalence",
                   result="certified", mesh=_mesh_tag(entry.mesh),
                   ops=cert.n_ops[0], outputs=cert.outputs_compared)


def captured_step_certificate():
    """The EquivalenceCertificate of the calling thread's last captured
    step, or None (no capture, or FLAGS_check_programs<2 at build)."""
    ref = getattr(_tls, "last_capture_entry", None)
    entry = ref() if ref is not None else None
    return entry.certificate if entry is not None else None


def _run_captured(rec: _DeferredStep, opt, entry: _CaptureEntry) -> bool:
    from . import dispatch

    seg = rec.segment
    leaves = rec.leaves
    ext = seg.ext_vals
    for i, s in zip(entry.param_idx, entry.param_slots):
        if leaves[i]._value is not ext[s]:
            raise _CaptureIneligible("param_rebound")
    for t, gt, ref in rec.leaf_grads:
        if t.grad is not gt or gt._value is not ref:
            # the user wrote/cleared a .grad between backward() and step():
            # the eager path would feed THAT value to the update — abort so
            # the normal path does exactly that
            raise _CaptureIneligible("grad_replaced")
    params, states, args = _capture_args(rec, opt, entry)
    if entry.arg_specs is None:
        entry.arg_specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype), args
        )
    if entry.donated and int(flags.flag("check_programs")):
        # donation-safety gate (analysis.memory): statically verify the
        # captured program's donated positions and gc-scan the to-be-donated
        # buffers for live external Tensor aliases (state_dict()/detach()
        # held across steps) BEFORE XLA invalidates them. Raises
        # ProgramVerificationError at FLAGS_check_programs>=2 — the caller
        # resolves the deferred step on the safe 3-program path first.
        _check_captured_donation(entry, params, states)
    if not entry.warmed and int(flags.flag("check_programs")) >= 2 \
            and entry.ref_fn is not None:
        # proof-carrying parity: certify captured ≡ 3-program composition
        # before anything is donated or replayed
        _certify_capture_equivalence(entry)
    lkey = _ladder_key(rec.seg_sig)
    # with donation on, a REAL fault from inside exe may fire after XLA
    # consumed the param/state buffers — replaying the same args would feed
    # deleted buffers, so such faults skip in-place retry and resolve via
    # the 3-program fallback (injected faults raise pre-launch and retry)
    unsafe = entry.donated
    tag = _mesh_tag(entry.mesh)
    ckey = f"captured:{_sig_id(rec.seg_sig)}" + (f"@{tag}" if tag else "")
    t0 = time.perf_counter()
    if entry.warmed:
        out = dispatch._rexec(
            "captured", lambda: entry.exe(*args), ladder_key=lkey,
            retry_unsafe=unsafe,
        )
        _note_program(ckey, "captured", _add_time("replay_time_ms", t0))
    else:
        import warnings

        def _first_run():
            with warnings.catch_warnings():
                # first call compiles (unless the async pipeline already
                # AOT-compiled it off-thread); backends without real buffer
                # donation (CPU) warn that donated buffers were unused —
                # benign here
                warnings.filterwarnings("ignore", message=".*onated buffer.*")
                return entry.exe(*args)

        out = dispatch._rexec("captured", _first_run, fresh=True,
                              ladder_key=lkey, retry_unsafe=unsafe)
        _add_time("compile_time_ms", t0)
        entry.warmed = True
        # attribution cost registry: the captured step registers its
        # static profile at build time. Weak thunks (the registry must
        # never outlive the capture cache — same discipline as
        # captured_step_program): the jaxpr trace and the XLA
        # cost_analysis both run lazily at the first program_costs read.
        import weakref as _weakref

        eref = _weakref.ref(entry)

        def _cap_jaxpr(_r=eref):
            e = _r()
            if e is None or e.arg_specs is None:
                return None
            return jax.make_jaxpr(e.step_fn)(*e.arg_specs)

        def _cap_cost(_r=eref):
            e = _r()
            if e is None or e.arg_specs is None:
                return None
            ca = getattr(e.exe, "cost_analysis", None)
            if ca is not None:
                try:
                    return ca()
                except Exception:
                    pass
            try:
                return e.exe.lower(*e.arg_specs).cost_analysis()
            except Exception:
                return None

        _roles, _donated = _capture_arg_roles(entry)
        _register_program(ckey, "captured", jaxpr_thunk=_cap_jaxpr,
                          cost_thunk=_cap_cost, donated=len(_donated))
    results, gp, gx, new_p, new_s = out[:5]
    _extra = list(out[5:])
    bad = _extra.pop(0) if entry.rescue else None
    tele = _extra.pop(0) if entry.telemetry else None

    _tls.capture_deferred = None
    rec.stub_seg.flushed = True
    # captured_step_program() surface: a WEAK ref, so the introspection
    # hook never outlives the capture cache (the step fn closes over the
    # plan and optimizer math — pinning it would keep a dropped model's
    # buffers reachable for the thread's lifetime)
    import weakref

    _tls.last_capture_entry = weakref.ref(entry)
    dispatch._count_program("captured")
    dispatch._counters["capture_replays"] += 1
    if entry.mesh is not None:
        dispatch._counters["capture_sharded_replays"] += 1
    # per-host capture tier for /statusz + fleet obs: what the LAST replay
    # on this thread actually ran as
    _tls.capture_tier = {
        "tier": "captured-sharded" if entry.mesh is not None else "captured",
        "mesh": tag,
        "donated": bool(entry.donated),
    }
    dispatch._emit("capture", site="captured", phase="replay",
                   donated=entry.donated, mesh=tag)

    # the captured program subsumes the segment flush: write every op
    # output back exactly like _flush does (minus the vjp closures, which
    # the capture consumed — a second backward raises, same as always)
    seg.flushed = True
    for op, outs in zip(seg.ops, results):
        for (ref, t), val in zip(op.outs, outs):
            ref._concrete = val
            if t._value is ref:
                t._value = val
        if op.record:
            op.node.out_avals = [(tuple(v.shape), v.dtype) for v in outs]
    seg.ops = []
    # donated param buffers are dead: drop the segment's references
    seg.ext_vals = []
    seg.ext_ids = {}

    for i, g in zip(list(entry.param_idx) + list(entry.extra_idx),
                    list(gp) + list(gx)):
        t, gt, ref = rec.leaf_grads[i]
        ref._concrete = g
        gt._value = g
    for p, v, ns in zip(params, new_p, new_s):
        p._value = v
        opt._accumulators[id(p)] = ns
    obs = getattr(_tls, "observer", None)
    if obs is not None:
        obs.events, obs.dirty = [], False  # stays armed for the next step
        obs.pos = 0  # an accumulation cycle completed; next one starts fresh
    if tele is not None:
        # fused telemetry host-read BEFORE the rescue policy runs, so a
        # rescue postmortem's tail already carries the spike event
        try:
            from ..profiler import attribution as _attribution

            _attribution.record_telemetry(
                _attribution.group_names(params), tele)
        except Exception:
            pass
    if bad is not None:
        from ..resilience import rescue as _rescue

        # host-reads the fused sentinel and applies the configured policy
        # (skip already happened in-program; lr_backoff/abort act here)
        _rescue.handle_sentinel(opt, bad)
    return True


def step_capture_step(optimizer) -> bool:
    """Optimizer.step() entry hook — the capture controller's step boundary.

    With no deferred backward pending this is the ordinary lazy-dispatch
    materialization point (flush, reason 'optimizer_step') plus signature
    observation. With a deferred backward pending, the whole step replays
    (or first compiles) as ONE donated XLA program and True is returned so
    Optimizer.step() skips the per-part path; any mismatch aborts to the
    normal path and returns False."""
    rec = getattr(_tls, "capture_deferred", None)
    if rec is None:
        flush_if_pending("optimizer_step")
        if _capture_on():
            _step_boundary(optimizer)
        return False

    def fallback(reason: str) -> bool:
        _abort_capture(reason)
        flush_if_pending("optimizer_step")
        return False

    if not _capture_on():
        # the flag was turned off between backward() and step(): honor it —
        # the deferred step resolves on the normal path, nothing is donated
        return fallback("capture_disabled")
    from ..resilience import faults as _faults

    plan = _faults.active_plan()
    if plan is not None and plan.would_fire(
        "nan", "grads", _faults.current_step()
    ):
        # nan:grads poisons a MATERIALIZED gradient, which the captured
        # 1-program replay never produces — resolve this step on the
        # 3-program path so the injection (and its in-program rescue)
        # actually fire instead of passing vacuously
        return fallback("nan_injected")
    from . import dispatch

    try:
        opt_fp = _opt_fingerprint(optimizer)
    except Exception:
        opt_fp = None
    if opt_fp is None or opt_fp != rec.expected_opt_fp:
        return fallback("optimizer_mismatch")
    from ..resilience import rescue as _rescue

    key = (rec.seg_sig, rec.tape_key, opt_fp,
           bool(flags.flag("eager_capture_donate")),
           rec.grad_prev_vals is not None,  # accumulation: grad-in program
           _rescue.active(),  # the sentinel changes the traced program
           _telemetry_on(),  # ... and so does the fused telemetry vector
           # planner-guided remat: the plan derives deterministically from
           # (signature, budget), so mode + budget fingerprint the plan
           # into the step key — a budget change recompiles, not replays
           (str(flags.flag("memory_plan")), float(flags.flag("memory_budget_mb")))
           if _mem_plan_on() else None,
           # mesh/spec fingerprint (mesh-aware capture): a respec or
           # topology change compiles a fresh executable; None single-chip
           _mesh_fingerprint(_capture_mesh(rec), rec))
    try:
        entry = dispatch._lru_get(_capture_cache, key)
    except TypeError:
        # unhashable step key (exotic custom-optimizer hypers) — the step
        # is not cacheable as a capture; run it on the normal path
        return fallback("unhashable_key")
    try:
        if entry is None:
            def _build_and_submit():
                # trace-free build (jax.jit is lazy); with the async
                # pipeline on, the expensive trace + XLA compile moves to
                # the background thread as an AOT lower().compile() over
                # the arg avals — real buffers never cross the thread
                # boundary, so donation stays a replay-time-only effect
                e = _build_captured_step(rec, optimizer)
                if not _async.enabled():
                    return e, None
                _p, _s, cargs = _capture_args(rec, optimizer, e)
                e.arg_specs = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype),
                    cargs,
                )
                exe, specs = e.exe, e.arg_specs
                fut = _async.submit(lambda: _aot_compile(exe, specs))
                e.pending = fut  # None when the queue is saturated
                return e, fut

            entry, fut = dispatch._rexec(
                "captured", _build_and_submit,
                fresh=True, ladder_key=_ladder_key(rec.seg_sig),
            )
            dispatch._counters["capture_builds"] += 1
            if entry.mesh is not None:
                dispatch._counters["capture_sharded_builds"] += 1
            dispatch._emit("capture", site="captured", phase="build",
                           background=fut is not None,
                           mesh=_mesh_tag(entry.mesh))
            dispatch._lru_put(
                _capture_cache, key, entry,
                evict_counter="capture_evictions",
                cap=int(flags.flag("eager_capture_cache_size")),
            )
            if fut is not None:
                # resolve THIS step on the 3-program path while the
                # executable compiles off-thread — not a capture fallback:
                # the controller stays armed and the next occurrence of
                # this signature joins the finished compile
                dispatch._counters["capture_async_builds"] += 1
                dispatch._counters["capture_build_pending_steps"] += 1
                dispatch._emit("capture", site="captured",
                               phase="build_pending")
                _abort_capture("build_pending", fallback=False)
                flush_if_pending("optimizer_step")
                return False
        elif entry.pending is not None:
            fut = entry.pending
            if not fut.done():
                dispatch._counters["capture_build_pending_steps"] += 1
                dispatch._emit("capture", site="captured",
                               phase="build_pending")
                _abort_capture("build_pending", fallback=False)
                flush_if_pending("optimizer_step")
                return False
            entry.pending = None
            try:
                entry.exe = fut.result()  # the AOT-compiled executable
            except Exception:
                # compile-thread failure: drop the entry so a later cycle
                # rebuilds from scratch, then surface the error with its
                # original traceback through the capture_error contract
                _capture_cache.pop(key, None)
                raise
            dispatch._counters["async_compile_joins"] += 1
            dispatch._emit("async_join", site="captured")
        return _run_captured(rec, optimizer, entry)
    except _CaptureIneligible as e:
        return fallback(e.reason)
    except FloatingPointError:
        # numeric_rescue=abort fired AFTER the captured step resolved (the
        # rescued update was already suppressed in-program) — propagate the
        # verdict, don't re-run the step on the fallback path
        raise
    except Exception as e:
        from ..analysis import ProgramVerificationError

        if isinstance(e, ProgramVerificationError):
            # verification failed at FLAGS_check_programs>=2: resolve the
            # deferred step on the safe 3-program path (numerics and
            # placeholder grads stay correct), then surface the verdict —
            # this is the static trip wire that fires BEFORE XLA's runtime
            # use-after-donate error (or CPU's silent non-donation). Label
            # the fallback by what actually failed, so the fallback-reason
            # histogram doesn't blame donation for a budget overrun.
            from ..analysis import Severity

            donation = any(
                d.pass_name == "donation_safety"
                and d.severity >= Severity.ERROR
                for d in e.diagnostics
            )
            fallback("donation_unsafe" if donation else "verification_failed")
            raise
        # any trace/compile/runtime error from the captured executable must
        # honor the fallback contract — the step completes on the normal
        # 3-program path instead of crashing optimizer.step() (and the
        # deferred placeholder grads must not outlive the failure)
        return fallback("capture_error")


# ---------------------------------------------------------------------------
# Decode-mode capture (paddle.serving)
#
# The whole-step controller above captures TRAINING steps by observing the
# eager event stream. Inference has no backward/optimizer to observe — a
# serving engine knows its step boundaries exactly — so decode-mode capture
# is the direct half of the same contract (the CUDA-Graphs capture/replay
# idiom from PAPERS.md): a pure step function, keyed by its bucket
# signature, jitted ONCE with the paged KV block pool donated, replayed from
# an LRU cache. Per-op dispatch inside the traced function already falls
# back to the per-op path on tracer args (lazy_apply's tracer bail-out), so
# the SAME paddle-ops function serves all three execution tiers:
#
#   captured  jit(fn, donate_argnums=pools)  — 1 donated program per step
#   lazy      jit(fn)                        — 1 program, inputs retained
#                                              (the retry-safe middle rung)
#   per-op    fn(*args) eagerly              — the ladder floor
#
# Build/replay/fallback/eviction counts land in
# paddle.profiler.dispatch_counters() under the serve_capture_* keys.
# ---------------------------------------------------------------------------
_serve_cache: "OrderedDict[Tuple, _ServeProgram]" = OrderedDict()


class _ServeProgram:
    """One captured serving program (a prefill or decode bucket signature)."""

    __slots__ = ("key", "fn", "donate_argnums", "_exe_donate", "_exe_plain",
                 "_built_donate", "_built_plain", "certificate", "__weakref__")

    def __init__(self, key, fn, donate_argnums):
        self.key = key
        self.fn = fn
        self.donate_argnums = tuple(donate_argnums)
        self._exe_donate = None
        self._exe_plain = None
        self._built_donate = False
        self._built_plain = False
        # EquivalenceCertificate binding the donated rung to the plain
        # retry rung (FLAGS_check_programs=2), or None
        self.certificate = None

    def _certify_rungs(self, args):
        """Proof-carrying parity for the serve ladder: before the donated
        rung consumes its first pool, certify its trace structurally
        equivalent to the non-donated retry rung's. Both rungs jit the
        same ``fn`` today, so this locks the ladder invariant (a fault on
        the donated tier replays on a PROVABLY identical program) against
        the rungs ever being forked. Divergence raises
        ProgramVerificationError while the pools are still intact;
        an unprovable trace is recorded and skipped."""
        from . import dispatch
        from ..analysis import ProgramVerificationError
        from ..analysis.equivalence import prove_equivalent

        dispatch._counters["serve_equivalence_checks"] += 1
        try:
            specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype),
                tuple(args),
            )
            cert = prove_equivalent(
                jax.make_jaxpr(self.fn)(*specs),
                jax.make_jaxpr(self.fn)(*specs),
                label_a="serve-donated", label_b="serve-plain",
                source=f"serve:{self.key}",
            )
        except ProgramVerificationError:
            raise
        except Exception as e:
            dispatch._emit("serve_capture", site="captured",
                           phase="equivalence", key=str(self.key),
                           result="unprovable", why=type(e).__name__)
            return
        if not cert.equivalent:
            dispatch._counters["serve_equivalence_divergences"] += 1
            dispatch._emit("serve_capture", site="captured",
                           phase="equivalence", key=str(self.key),
                           result="divergent")
            raise ProgramVerificationError(
                "donated serve rung is not provably equivalent to the "
                "plain retry rung: " + cert.summary(),
                [cert.divergence] if cert.divergence is not None else [])
        self.certificate = cert
        dispatch._counters["serve_equivalence_certified"] += 1
        dispatch._emit("serve_capture", site="captured", phase="equivalence",
                       key=str(self.key), result="certified",
                       ops=cert.n_ops[0], outputs=cert.outputs_compared)

    def built(self, donate: bool = True) -> bool:
        return self._built_donate if donate else self._built_plain

    def run(self, args, donate: bool = True):
        """Replay the captured program (building it on first use).

        ``donate=True`` consumes the buffers at ``donate_argnums`` in place
        (the captured tier); ``donate=False`` is the retry-safe middle rung
        — same single program, inputs retained."""
        import warnings as _warnings

        from . import dispatch

        if donate and self.donate_argnums:
            if self._exe_donate is None:
                self._exe_donate = jax.jit(
                    self.fn, donate_argnums=self.donate_argnums
                )
            exe, fresh = self._exe_donate, not self._built_donate
        else:
            if self._exe_plain is None:
                self._exe_plain = jax.jit(self.fn)
            exe, fresh = self._exe_plain, not self._built_plain
        akey = "serve:" + ":".join(str(x) for x in self.key)
        if fresh and donate and self.donate_argnums \
                and int(flags.flag("check_programs")) >= 2:
            self._certify_rungs(args)
        t0 = time.perf_counter()
        if fresh:
            # first call = trace + XLA compile; backends without real
            # donation (CPU) warn at compile time — same suppression as the
            # training capture's _aot_compile
            with _warnings.catch_warnings():
                _warnings.filterwarnings("ignore", message=".*onated buffer.*")
                out = exe(*args)
            if donate and self.donate_argnums:
                self._built_donate = True
            else:
                self._built_plain = True
            dispatch._counters["serve_capture_builds"] += 1
            dispatch._emit("serve_capture", site="captured", phase="build",
                           key=str(self.key), donated=bool(
                               donate and self.donate_argnums))
            _add_time("compile_time_ms", t0)
            # attribution cost registry: one entry per serving bucket
            # signature. Weak thunk — the step fn closes over the model,
            # and the registry must never outlive the serve cache.
            import weakref as _weakref

            pref = _weakref.ref(self)
            try:
                specs = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype),
                    tuple(args),
                )

                def _serve_jaxpr(_r=pref, _s=specs):
                    p = _r()
                    if p is None:
                        return None
                    return jax.make_jaxpr(p.fn)(*_s)

                _register_program(
                    akey, "serve", jaxpr_thunk=_serve_jaxpr,
                    donated=len(self.donate_argnums)
                    if (donate and self.donate_argnums) else 0,
                )
            except Exception:
                pass
        else:
            out = exe(*args)
            dispatch._counters["serve_capture_replays"] += 1
            _note_program(akey, "serve", _add_time("replay_time_ms", t0))
        return out


def serve_program(key: Tuple, fn: Callable, donate_argnums=()) -> _ServeProgram:
    """The decode-mode capture cache: one ``_ServeProgram`` per bucket
    signature, LRU-bounded by FLAGS_serving_capture_cache_size. A re-used
    key returns the cached handle (its compiled executables intact), so a
    steady-state decode loop replays without recompiling — verified by the
    serve_capture_builds counter staying flat."""
    from . import dispatch

    prog = _serve_cache.get(key)
    if prog is not None:
        _serve_cache.move_to_end(key)
        return prog
    prog = _ServeProgram(key, fn, donate_argnums)
    _serve_cache[key] = prog
    cap = int(flags.flag("serving_capture_cache_size"))
    while cap > 0 and len(_serve_cache) > cap:
        _serve_cache.popitem(last=False)
        dispatch._counters["serve_capture_evictions"] += 1
    return prog


def reset_serve_programs(owner=None):
    """Drop captured serving programs: all of them (test isolation), or —
    with ``owner`` set — only the ones whose key belongs to that engine uid
    (Engine.close(): a dead engine's step-function closures hold the model
    and would otherwise sit in the cache until LRU pressure evicts them)."""
    if owner is None:
        _serve_cache.clear()
        return
    for key in [k for k in _serve_cache
                if len(k) > 1 and k[1] == owner]:
        del _serve_cache[key]


def serve_capture_state() -> Dict[str, Any]:
    """Snapshot of the decode-mode capture cache (bench.py's serving record
    and tests read this)."""
    return {
        "cached_programs": len(_serve_cache),
        "built_programs": sum(
            1 for p in _serve_cache.values()
            if p._built_donate or p._built_plain
        ),
    }


def step_signature_id() -> Optional[int]:
    """Small stable id of the ARMED whole-step capture signature on this
    thread, or None when no signature is armed. The perf-regression
    sentinel keys its train-step baseline on this, so a workload change
    that re-arms capture starts a fresh baseline instead of tripping
    against the old step's timing."""
    obs = getattr(_tls, "observer", None)
    if obs is None or obs.armed is None:
        return None
    try:
        return hash(obs.armed) & 0xFFFF
    except TypeError:
        return None


def step_capture_state() -> Dict[str, Any]:
    """Snapshot of this thread's whole-step capture controller (for
    bench.py's capture-state line and paddle.profiler.measure_programs)."""
    obs = getattr(_tls, "observer", None)
    tier_info = getattr(_tls, "capture_tier", None) or {}
    return {
        "enabled": _capture_on(),
        "armed": bool(obs is not None and obs.armed is not None),
        "stable_steps": 0 if obs is None else obs.stable,
        "deferred": getattr(_tls, "capture_deferred", None) is not None,
        "cached_steps": len(_capture_cache),
        # accumulation-cycle state: period k (1 = plain step) and the
        # position inside the current cycle
        "cycle_len": 1 if obs is None else obs.cycle_len,
        "cycle_pos": 0 if obs is None else obs.pos,
        # async host pipeline: background compiles still in flight
        "pending_compiles": _async.pending_jobs(),
        # mesh-aware capture: the tier the LAST replay on this thread ran
        # as ('captured-sharded' on a multi-device mesh), its mesh tag,
        # and whether that replay was donated — /statusz and the fleet obs
        # snapshot render these per host
        "tier": tier_info.get("tier"),
        "mesh": tier_info.get("mesh"),
        "donated": bool(tier_info.get("donated", False)),
    }
