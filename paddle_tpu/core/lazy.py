"""Deferred (lazy) eager dispatch: batch per-op launches into fused segments.

The per-op eager path (dispatch.apply) launches one XLA program per op, so
an eager LeNet train step costs ~13 device-program round-trips — and
PROFILE_EAGER.md shows the program *count*, not host Python, is the ceiling
on eager throughput through the relay. This module is the classic
LazyTensor-style fix proven by torch-xla (XLATensor + pending IR graph,
torch_xla/csrc/tensor.cpp) and by the reference's own to_static tracing:

  - with FLAGS_eager_lazy_dispatch on, `apply()` does not execute: the op is
    appended to a per-thread pending *segment* and the caller gets a Tensor
    backed by a `LazyRef` (shape/dtype known via jax.eval_shape, value
    pending);
  - materialization points — host reads (numpy/item/float/bool), backward,
    explicit paddle_tpu.device.synchronize(), uncacheable/jit=False ops, a
    mid-segment AMP region — flush the whole pending segment as ONE jitted
    program;
  - the compiled segment is cached by *segment signature* (sequence of op
    cache-tokens + static kwargs + input bindings + external input avals),
    so a steady-state eager train step replays a cached fused executable:
    1 forward segment + 1 compiled-tape backward + 1 fused optimizer update.

Autograd composes unchanged: recorded ops get their GradNode at defer time
(so later ops snapshot correct Edges), and the segment program computes each
recorded op's jax.vjp *inside the fused trace* — at flush the pytree vjp
closures come back as concrete residuals and are slotted into the pending
GradNodes, which then behave exactly like per-op-path nodes (including the
compiled-tape backward and create_graph re-derivation).
"""
from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import flags

__all__ = [
    "LazyRef",
    "flush_if_pending",
    "materialize",
    "pending_op_count",
    "pending_segment_jaxpr",
]

# sentinel returned by lazy_apply when the op must take the per-op path
_FALLBACK = object()

_tls = threading.local()

# binding kinds inside a segment: op input comes from an external array, a
# previous op's output, or an embedded python-scalar literal
_EXT, _RES, _LIT = 0, 1, 2


def _np_dtype(dt):
    """np.dtype when possible; jax extended dtypes (PRNG keys, float8 wrap
    types) pass through as-is — they are hashable and aval-comparable."""
    try:
        return np.dtype(dt)
    except TypeError:
        return dt


class LazyRef:
    """Pending value of one output of one deferred op.

    Carries the inferred aval so shape/dtype-dependent control flow does NOT
    flush; any other attribute access (or numpy/jax conversion) materializes
    by flushing the owning segment. After the flush `_concrete` holds the
    real array and all access delegates to it.
    """

    __slots__ = (
        "_segment",
        "_op_index",
        "_out_index",
        "_shape",
        "_dtype",
        "_concrete",
        "__weakref__",
    )

    def __init__(self, segment, op_index, out_index, shape, dtype):
        self._segment = segment
        self._op_index = op_index
        self._out_index = out_index
        self._shape = tuple(shape)
        self._dtype = _np_dtype(dtype)
        self._concrete = None

    # -- aval surface (no flush) -------------------------------------------
    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        return int(np.prod(self._shape)) if self._shape else 1

    # -- materialization ----------------------------------------------------
    def materialize(self):
        if self._concrete is None:
            _flush(self._segment, "sync")
            if self._concrete is None:
                # the owning segment's flush failed earlier (compile or
                # runtime error): surface the root cause on every read
                # instead of silently yielding None
                raise RuntimeError(
                    "lazy-dispatch segment flush failed; this tensor's value "
                    "is unavailable"
                ) from self._segment.error
        return self._concrete

    def __getattr__(self, name):
        # anything beyond the aval surface needs the real array
        return getattr(self.materialize(), name)

    def __jax_array__(self):
        return self.materialize()

    def __array__(self, dtype=None):
        arr = np.asarray(jax.device_get(self.materialize()))
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        state = "pending" if self._concrete is None else "materialized"
        return f"<LazyRef {state} shape={self._shape} dtype={self._dtype}>"


def _delegating(name):
    def method(self, *args, **kwargs):
        return getattr(self.materialize(), name)(*args, **kwargs)

    method.__name__ = name
    return method


# operators bypass instance __getattr__ — install explicit delegates so a
# LazyRef that leaks into raw jnp/python arithmetic still behaves like its
# (materialized) array instead of raising
for _name in (
    "__add__", "__radd__", "__sub__", "__rsub__", "__mul__", "__rmul__",
    "__truediv__", "__rtruediv__", "__floordiv__", "__rfloordiv__",
    "__mod__", "__rmod__", "__pow__", "__rpow__", "__matmul__",
    "__rmatmul__", "__neg__", "__pos__", "__abs__", "__getitem__",
    "__lt__", "__le__", "__gt__", "__ge__", "__eq__", "__ne__",
    "__float__", "__int__", "__bool__", "__len__", "__iter__",
):
    setattr(LazyRef, _name, _delegating(_name))
LazyRef.__hash__ = object.__hash__  # __eq__ delegate must not kill identity hash


def materialize(v):
    """Concrete value of `v` (flushes the pending segment for LazyRefs)."""
    return v.materialize() if type(v) is LazyRef else v


class _SegOp:
    """One deferred op inside a pending segment."""

    __slots__ = ("fn", "kw", "bindings", "diff_idx", "record", "node", "outs")

    def __init__(self, fn, kw, bindings, diff_idx, record, node):
        self.fn = fn
        self.kw = kw
        self.bindings = bindings
        self.diff_idx = diff_idx
        self.record = record
        self.node = node
        self.outs = []  # [(LazyRef, Tensor)] — filled by lazy_apply


class _Segment:
    """Per-thread pending op trace, flushed as one jitted program."""

    __slots__ = (
        "ops", "ext_vals", "ext_ids", "ext_specs", "sig_parts", "flushed",
        "error",
    )

    def __init__(self):
        self.ops: List[_SegOp] = []
        self.ext_vals: List[Any] = []
        self.ext_ids: Dict[int, int] = {}
        self.ext_specs: List[Tuple] = []
        self.sig_parts: List[Tuple] = []
        self.flushed = False
        self.error: Optional[BaseException] = None


def _current_segment() -> _Segment:
    seg = getattr(_tls, "segment", None)
    if seg is None or seg.flushed:
        seg = _Segment()
        _tls.segment = seg
    return seg


def pending_op_count() -> int:
    seg = getattr(_tls, "segment", None)
    return 0 if seg is None or seg.flushed else len(seg.ops)


def flush_if_pending(reason: str = "explicit_sync"):
    """Flush this thread's pending segment (no-op when nothing is pending)."""
    seg = getattr(_tls, "segment", None)
    if seg is not None and not seg.flushed and seg.ops:
        _flush(seg, reason)


# ---------------------------------------------------------------------------
# Output-aval inference, cached by (op token, statics, input specs): one
# host-side jax.eval_shape per new op configuration, dict lookups after.
# ---------------------------------------------------------------------------
_aval_cache: "OrderedDict[Tuple, Tuple]" = OrderedDict()


def _infer_out_specs(fn, kw, arg_specs):
    args = []
    for spec in arg_specs:
        if spec[0] == "arr":
            args.append(jax.ShapeDtypeStruct(spec[1], spec[2]))
        else:
            args.append(spec[1])
    out = jax.eval_shape(functools.partial(fn, **kw), *args)
    if isinstance(out, (tuple, list)):
        flat, is_seq = list(out), True
    else:
        flat, is_seq = [out], False
    return [(tuple(o.shape), _np_dtype(o.dtype)) for o in flat], is_seq


# ---------------------------------------------------------------------------
# Segment compile cache: signature -> jitted segment program (LRU-bounded)
# ---------------------------------------------------------------------------
_segment_cache: "OrderedDict[Tuple, Callable]" = OrderedDict()


def _segment_fn(plan):
    """Raw (unjitted) segment program over the external-input list.

    plan: [(fn, kw, bindings, diff_idx, record)] — deliberately stripped
    of _SegOp/GradNode/Tensor refs so the cached closure pins no user data."""

    def seg_fn(ext):
        results = []
        vjps = []
        for fn, kw, bindings, diff_idx, record in plan:
            vals = []
            for kind, a, b in bindings:
                if kind == _EXT:
                    vals.append(ext[a])
                elif kind == _RES:
                    vals.append(results[a][b])
                else:
                    vals.append(a)
            if record:

                def partial(*dv, _fn=fn, _kw=kw, _vals=tuple(vals), _di=diff_idx):
                    full = list(_vals)
                    for i, v in zip(_di, dv):
                        full[i] = v
                    res = _fn(*full, **_kw)
                    return tuple(res) if isinstance(res, list) else res

                out, vjp = jax.vjp(partial, *[vals[i] for i in diff_idx])
                vjps.append(vjp)
            else:
                out = fn(*vals, **kw)
            results.append(list(out) if isinstance(out, (tuple, list)) else [out])
        return results, vjps

    return seg_fn


def _build_segment_fn(plan):
    return jax.jit(_segment_fn(plan))


def _seg_plan(seg: _Segment):
    return [(op.fn, op.kw, op.bindings, op.diff_idx, op.record) for op in seg.ops]


def _segment_jaxpr(plan, ext_specs):
    """Closed jaxpr of the fused segment program (for the verifier).

    Preserves the recorded weak_type flags: weak scalars promote
    differently, and the verified jaxpr must match the jaxpr the segment
    actually compiles (a weak f64 literal is benign; a strong one is the
    upcast the dtype pass hunts)."""
    specs = [
        jax.ShapeDtypeStruct(
            shape, dtype, weak_type=bool(rest[0]) if rest else False
        )
        for shape, dtype, *rest in ext_specs
    ]
    return jax.make_jaxpr(_segment_fn(plan))(specs)


def pending_segment_jaxpr():
    """Trace this thread's pending segment WITHOUT flushing it; None when
    nothing is pending. Feeds paddle_tpu.analysis.check_pending_segment."""
    seg = getattr(_tls, "segment", None)
    if seg is None or seg.flushed or not seg.ops:
        return None
    return _segment_jaxpr(_seg_plan(seg), seg.ext_specs)


def _flush(seg: _Segment, reason: str):
    from . import dispatch

    if seg.flushed:
        return
    seg.flushed = True
    if getattr(_tls, "segment", None) is seg:
        _tls.segment = None
    if not seg.ops:
        return

    sig = (tuple(seg.sig_parts), tuple(seg.ext_specs))
    jfn = dispatch._lru_get(_segment_cache, sig)
    fresh = jfn is None
    if fresh:
        dispatch._counters["segment_cache_misses"] += 1
        plan = _seg_plan(seg)
        jfn = _build_segment_fn(plan)
    else:
        dispatch._counters["segment_cache_hits"] += 1

    try:
        if fresh and int(flags.flag("check_programs")):
            # FLAGS_check_programs: verify the fused segment before its
            # first compile (cached replays were already verified). A
            # level-2 raise lands in the except path below, so reads of
            # this segment's tensors re-raise the verification error.
            from .. import analysis

            analysis.enforce(
                analysis.check(
                    _segment_jaxpr(plan, seg.ext_specs),
                    source="lazy-segment",
                ),
                where=f"lazy-segment flush ({reason})",
            )
        results, vjps = jfn(seg.ext_vals)
    except BaseException as e:
        # record the root cause: every later materialize() of this segment's
        # refs re-raises it instead of silently yielding None. A program
        # that never ran successfully is never cached.
        seg.error = e
        seg.ops = []
        raise
    if fresh:
        dispatch._lru_put(
            _segment_cache, sig, jfn,
            evict_counter="segment_cache_evictions",
            cap=int(flags.flag("eager_segment_cache_size")),
        )
    dispatch._count_program("segment")
    dispatch._counters["segments_flushed"] += 1
    reasons = dispatch._counters["flush_reasons"]
    reasons[reason] = reasons.get(reason, 0) + 1

    vi = 0
    for op, outs in zip(seg.ops, results):
        for (ref, t), val in zip(op.outs, outs):
            ref._concrete = val
            if t._value is ref:
                t._value = val
        if op.record:
            node = op.node
            node.vjp_fn = vjps[vi]
            vi += 1
            node.jit_vjp = True
            # replace predicted avals with the real ones (weak-type exactness)
            node.out_avals = [(tuple(v.shape), v.dtype) for v in outs]
    seg.ops = []  # drop op/node/tensor refs — the segment is spent


# ---------------------------------------------------------------------------
# The deferral entry point, called from dispatch.apply when the flag is on
# ---------------------------------------------------------------------------
def lazy_apply(
    fn: Callable,
    args: Tuple,
    kw_items: Tuple,
    *,
    op_name: Optional[str],
    differentiable: bool,
    jit: bool,
    cache_token,
):
    """Defer `fn` onto the pending segment; `_FALLBACK` sends the caller to
    the per-op path (after flushing, so program order is preserved)."""
    from . import dispatch
    from .tensor import Tensor

    # bail-outs: ops the segment trace cannot host take the per-op path.
    # jit=False ops have data-dependent output shapes; closure-captured fns
    # have no stable cache token; explicit cache_token ops (to_static
    # closures) manage their own compile caches; AMP casting and the debug
    # flags read per-call state the segment signature doesn't cover.
    if not jit:
        flush_if_pending("fallback_nojit")
        return _FALLBACK
    if cache_token is not None:
        flush_if_pending("fallback_token")
        return _FALLBACK
    token = dispatch._cache_token(fn)
    if token is None:
        flush_if_pending("fallback_uncacheable")
        return _FALLBACK
    if flags.flag("check_nan_inf") or flags.flag("benchmark"):
        flush_if_pending("fallback_debug")
        return _FALLBACK
    amp = dispatch._amp_module()
    if amp.amp_active():
        flush_if_pending("fallback_amp")
        return _FALLBACK
    try:
        hash(kw_items)
    except TypeError:
        flush_if_pending("fallback_unhashable")
        return _FALLBACK

    # unwrap + classify args; tracer-backed values mean we are inside
    # someone's jit trace (to_static / recompute) — defer nothing there
    vals: List[Any] = []
    diff_idx: List[int] = []
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            v = a._value
            if isinstance(v, jax.core.Tracer):
                return _FALLBACK
            vals.append(v)
            if not a.stop_gradient and (
                getattr(v, "dtype", None) in dispatch._FLOAT_DTYPES
            ):
                diff_idx.append(i)
        else:
            if isinstance(a, jax.core.Tracer):
                return _FALLBACK
            vals.append(a)

    seg = _current_segment()

    # pass 1 — classify without mutating the segment, so any fallback below
    # leaves no stray external inputs in the signature
    pre: List[Tuple] = []
    arg_specs: List[Tuple] = []
    for v in vals:
        if type(v) is LazyRef:
            if v._concrete is not None:
                v = v._concrete
            elif v._segment is not seg:
                # pending ref from a stale/foreign segment: materialize it
                _flush(v._segment, "cross_segment")
                v = v._concrete
            else:
                pre.append((_RES, v._op_index, v._out_index))
                arg_specs.append(("arr", v._shape, v._dtype))
                continue
        if isinstance(v, (jax.Array, np.ndarray)):
            pre.append((_EXT, v, 0))
            arg_specs.append(
                ("arr", tuple(v.shape), _np_dtype(v.dtype),
                 bool(getattr(v, "weak_type", False)))
            )
        else:
            try:
                hash(v)
            except TypeError:
                flush_if_pending("fallback_unhashable")
                return _FALLBACK
            pre.append((_LIT, v, 0))
            arg_specs.append(("lit", v))

    record = (
        differentiable and bool(diff_idx) and dispatch._grad_state().grad_enabled
    )

    # output avals (cached eval_shape); failure → op is not traceable as-is
    kw = dict(kw_items)
    aval_key = (token, kw_items, tuple(arg_specs), record)
    hit = dispatch._lru_get(_aval_cache, aval_key)
    if hit is not None:
        out_specs, is_seq = hit
    else:
        try:
            out_specs, is_seq = _infer_out_specs(fn, kw, arg_specs)
        except Exception:
            flush_if_pending("fallback_infer")
            return _FALLBACK
        # capped alongside the per-op compile caches (host-only metadata, no
        # jit wrappers, so no eviction counter)
        dispatch._lru_put(_aval_cache, aval_key, (out_specs, is_seq))

    # pass 2 — commit: intern external inputs, build final bindings
    bindings = []
    for kind, a, b in pre:
        if kind == _EXT:
            k = seg.ext_ids.get(id(a))
            if k is None:
                k = len(seg.ext_vals)
                seg.ext_vals.append(a)
                seg.ext_ids[id(a)] = k
                seg.ext_specs.append(
                    (tuple(a.shape), _np_dtype(a.dtype),
                     bool(getattr(a, "weak_type", False)))
                )
            bindings.append((_EXT, k, 0))
        else:
            bindings.append((kind, a, b))
    bindings = tuple(bindings)
    diff_t = tuple(diff_idx)

    node = None
    if record:
        node = dispatch.GradNode(
            None,
            [args[i] for i in diff_idx],
            list(out_specs),
            op_name or getattr(fn, "__name__", "op"),
            out_is_seq=is_seq,
        )

        # pure primal for create_graph double-grad re-derivation; non-diff
        # captures resolve at call time (post-flush they are concrete)
        def primal_fn(*dv, _fn=fn, _kw=kw, _vals=tuple(vals), _di=diff_t):
            full = [materialize(x) for x in _vals]
            for i, v in zip(_di, dv):
                full[i] = v
            res = _fn(*full, **_kw)
            return tuple(res) if isinstance(res, list) else res

        node.primal_fn = primal_fn

    op_index = len(seg.ops)
    op = _SegOp(fn, kw, bindings, diff_t, record, node)
    outs = []
    for i, (shape, dtype) in enumerate(out_specs):
        ref = LazyRef(seg, op_index, i, shape, dtype)
        # per-op parity: only RECORDED float outputs are differentiable;
        # non-recorded ops (no_grad, differentiable=False, int inputs) wrap
        # with stop_gradient=True exactly like _wrap_outputs does
        sg = True if not record else dtype not in dispatch._FLOAT_DTYPES
        t = _new_tensor(ref, stop_gradient=sg)
        if record and not t.stop_gradient:
            t._grad_node = node
            t._out_index = i
        op.outs.append((ref, t))
        outs.append(t)
    seg.ops.append(op)
    seg.sig_parts.append((token, kw_items, bindings, record, diff_t))
    dispatch._counters["lazy_ops_deferred"] += 1

    if len(seg.ops) >= int(flags.flag("eager_segment_max_ops")):
        _flush(seg, "segment_limit")

    return outs if is_seq else outs[0]


def _new_tensor(value, stop_gradient):
    from .tensor import Tensor

    t = Tensor.__new__(Tensor)
    t._value = value
    t.stop_gradient = stop_gradient
    t.grad = None
    t._grad_node = None
    t._out_index = 0
    t._backward_hooks = []
    t._inplace_version = 0
    t.name = ""
    t.persistable = False
    t.is_parameter = False
    return t
