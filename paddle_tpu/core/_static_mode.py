"""Static-graph mode toggle (paddle.enable_static/disable_static).

Reference: python/paddle/fluid/framework.py _dygraph_guard machinery. In the
TPU framework "static mode" means the Program/Executor compatibility facade
(paddle_tpu.static) is active; eager is the default.
"""
_enabled = [False]


def enable():
    _enabled[0] = True


def disable():
    _enabled[0] = False


def enabled() -> bool:
    return _enabled[0]
