"""RNG state management.

TPU-native analogue of phi::Generator (reference: paddle/phi/core/generator.h:23,
python/paddle/fluid/generator.py, paddle.seed in python/paddle/framework/random.py).
Paddle keeps a mutable per-device Philox state; JAX is functional, so the
Generator owns a root PRNG key and splits a fresh subkey per draw. Under a
`to_static`/jit trace, random ops must not bake a constant key — a trace-time
key provider can be pushed (see `rng_scope`) so compiled programs thread keys
explicitly; the TP-aware RNGStatesTracker (reference:
fleet/meta_parallel/parallel_layers/random.py) builds on the same scope.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import numpy as np


class Generator:
    """Stateful key source: each get_key() returns a fresh fold of the root key."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with self._lock:
            self._seed = int(seed)
            # key creation is LAZY: building a jax key touches the device
            # backend, and importing the framework must not initialize XLA
            # (jax.distributed.initialize has to run first in multi-process
            # jobs — reference: init_parallel_env before any device work)
            self._key = None
            self._counter = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        with self._lock:
            self._seed, self._counter = state
            self._key = None

    def get_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            self._counter += 1
            return jax.random.fold_in(self._key, self._counter)


default_generator = Generator(0)


def seed(value: int) -> Generator:
    """paddle.seed — reseed the global generator."""
    return default_generator.manual_seed(value)


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


# ---------------------------------------------------------------------------
# Trace-time key injection: inside jit tracing, random ops pull keys from the
# innermost rng scope instead of the global stateful generator.
# ---------------------------------------------------------------------------
_scope = threading.local()


class _KeyFeed:
    def __init__(self, key):
        self._key = key
        self._n = 0

    def next_key(self):
        self._n += 1
        return jax.random.fold_in(self._key, self._n)


@contextlib.contextmanager
def rng_scope(key):
    """Thread an explicit PRNG key through all random ops in this scope."""
    stack = getattr(_scope, "stack", None)
    if stack is None:
        stack = _scope.stack = []
    stack.append(_KeyFeed(key))
    try:
        yield
    finally:
        stack.pop()


def next_key(generator: Optional[Generator] = None):
    """Key for one random draw: scope key if active, else the (global) generator."""
    stack = getattr(_scope, "stack", None)
    if stack:
        return stack[-1].next_key()
    return (generator or default_generator).get_key()


def np_rng() -> np.random.Generator:
    """Host-side numpy RNG derived from the global seed (for dataloader etc.)."""
    return np.random.default_rng(default_generator.initial_seed())
