"""Device / Place abstraction.

TPU-native analogue of Paddle's Place hierarchy (reference:
paddle/phi/common/place.h:23-185 — AllocationType, CPUPlace:109, GPUPlace:117)
and framework::InitDevices (paddle/fluid/platform/init.cc). On TPU there is no
vendor-SDK zoo: JAX/PJRT owns device enumeration, so a Place is a typed handle
to a `jax.Device` plus the `paddle.set_device` / `get_device` API
(reference: python/paddle/device/__init__.py).
"""
from __future__ import annotations

import threading

import jax


class Place:
    """Typed device identity. Wraps a jax.Device."""

    device_type = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    @property
    def jax_device(self):
        devs = _devices_of_type(self.device_type)
        if not devs:
            raise RuntimeError(f"no {self.device_type} devices visible to JAX")
        return devs[self._device_id % len(devs)]

    def __repr__(self):
        return f"Place({self.device_type}:{self._device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.device_type, self._device_id))


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    """The native accelerator Place (replaces reference GPUPlace/CUDAPlace)."""

    device_type = "tpu"


class CUDAPinnedPlace(CPUPlace):
    """Compatibility alias — on TPU pinned host memory is just host memory."""


class CUDAPlace(TPUPlace):
    """Compatibility alias (reference: phi/common/place.h:117 GPUPlace):
    scripts written for the reference's accelerator land on this build's
    accelerator. Device-id semantics carry over unchanged."""


class NPUPlace(TPUPlace):
    """Compatibility alias (reference: place.h:146 NPUPlace)."""


class XPUPlace(TPUPlace):
    """Compatibility alias (reference: place.h XPUPlace)."""


class MLUPlace(TPUPlace):
    """Compatibility alias (reference: place.h MLUPlace)."""


class IPUPlace(TPUPlace):
    """Compatibility alias (reference: place.h IPUPlace)."""


class CustomPlace(TPUPlace):
    """Compatibility alias (reference: place.h:185 CustomPlace)."""

    def __init__(self, device_type="tpu", device_id=0):
        super().__init__(device_id)


def _devices_of_type(kind: str):
    try:
        if kind == "cpu":
            return jax.devices("cpu")
        # the TPU backend may register as 'tpu' or an experimental tunnel
        # platform; fall back to the default backend's devices.
        for plat in ("tpu", "axon"):
            try:
                devs = jax.devices(plat)
                if devs:
                    return devs
            except RuntimeError:
                continue
        devs = jax.devices()
        return [d for d in devs if d.platform != "cpu"] or devs
    except RuntimeError:
        return []


_state = threading.local()


def _default_place() -> Place:
    accel = _devices_of_type("tpu")
    if accel and accel[0].platform != "cpu":
        return TPUPlace(0)
    return CPUPlace(0)


def set_device(device) -> Place:
    """paddle.set_device — accepts 'cpu', 'tpu', 'tpu:0', or a Place."""
    if isinstance(device, Place):
        place = device
    else:
        s = str(device).lower()
        # accept reference spellings and map them onto the accelerator
        s = s.replace("gpu", "tpu").replace("xpu", "tpu").replace("npu", "tpu")
        if ":" in s:
            kind, _, idx = s.partition(":")
            idx = int(idx)
        else:
            kind, idx = s, 0
        if kind == "cpu":
            place = CPUPlace(idx)
        elif kind == "tpu":
            place = TPUPlace(idx)
        else:
            raise ValueError(f"unknown device {device!r}")
    _state.place = place
    return place


def get_device() -> str:
    p = _expected_place()
    return f"{p.device_type}:{p.get_device_id()}"


def _expected_place() -> Place:
    p = getattr(_state, "place", None)
    if p is None:
        p = _default_place()
        _state.place = p
    return p


def _set_expected_place(place: Place):
    _state.place = place


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return bool(_devices_of_type("tpu"))


def device_count() -> int:
    return len(_devices_of_type(_expected_place().device_type))
