"""Optimizer base + the standard optimizers.

Reference analogue: python/paddle/optimizer/optimizer.py:50 (base Optimizer,
minimize:1120, step:1185) and the per-optimizer phi kernels
(paddle/phi/kernels/{sgd,adam,adamw,momentum,...}_kernel.h).

Design: every optimizer defines a *pure* per-parameter update rule
`_update(p, g, lr, state) -> (new_p, new_state)` (arrays in, arrays out).
Eager `step()` applies it through one fused jitted call per parameter; the
compiled training-step path (paddle_tpu.jit) calls the same rule inside the
whole-program trace, so eager and jit share optimizer math exactly.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags as _flags
from ..core import lazy as _lazy
from ..core.dispatch import _count_program, no_grad
from ..core.tensor import Tensor
from .lr import LRScheduler

_jit_update_cache: Dict = {}


def make_fused_update(opt, params, sentinel=False, telemetry=False):
    """Pure multi-tensor update applier `(p_vals, g_vals, lr, states) ->
    (new_ps, new_states)` over `opt`'s rule for `params`.

    The ONE definition of the traced optimizer math shared by the eager
    fused step (`_apply_fused`) and the whole-step capture trace
    (core/lazy.py `_build_captured_step`): same rule, same static global +
    per-param hyper merge, same grad-dtype cast. The rule is bound to a
    bare shim carrying just `_weight_decay` — NOT the live optimizer — so
    callers can cache the (jitted) closure without pinning the instance
    and its accumulators.

    With `sentinel=True` (FLAGS_numeric_rescue, paddle.resilience) the
    applier returns a third output — `any(~isfinite(g))` over every grad —
    and where-gates the whole update on it: a non-finite step returns the
    ORIGINAL params and state. The scan and the gate are folded into the
    same traced program, so rescue adds zero program launches.

    With `telemetry=True` (FLAGS_telemetry, paddle.profiler.attribution)
    the applier appends one MORE output — a stacked `(n_params, 3)` f32
    vector of per-parameter sums of squares: grad², param², and
    (new_p − p)² — the fused-numerics telemetry the attribution layer
    reduces to per-group grad-norm / param-norm / update-ratio on the
    host. Same mechanism as the sentinel: extra outputs of the SAME
    traced program, zero extra launches, and the update chain itself is
    untouched, so step numerics stay bitwise-identical to telemetry-off.
    Output order is always (new_ps, new_states[, bad][, telemetry]).

    With FLAGS_pallas_fused_update (on TPU, or under the interpret flag),
    eligible parameters route through the hand-written Pallas kernel
    (ops/pallas/fused_update.py): the whole elementwise update chain — and
    the sentinel gate — runs as one VMEM pass per buffer. Ineligible
    params (unsupported rule, dtype, or tile size) keep the lax rule in
    the SAME traced program, so the callers' 1/3-program arithmetic never
    changes. The enablement is part of both compile-cache keys
    (_apply_fused's and the capture controller's), so flipping the flag
    retraces instead of replaying a stale program."""
    from ..ops.pallas import fused_update as _pfu

    rule = type(opt)._update
    hypers = [dict(opt._hyper(), **opt._per_param_hyper(p)) for p in params]
    ctx = object.__new__(type(opt))
    ctx._weight_decay = opt._weight_decay
    kind = _pfu.rule_kind(type(opt)) if _pfu.enabled() else None

    def apply_update(p_vals, g_vals, lr, states):
        bad = None
        if sentinel:
            bad = jnp.asarray(False)
            for gv in g_vals:
                bad = bad | jnp.any(~jnp.isfinite(gv))
        new_ps, new_sts = [], []
        tele_rows = []
        for pv, gv, st, hy in zip(p_vals, g_vals, states, hypers):
            if gv.dtype != pv.dtype:
                gv = gv.astype(pv.dtype)
            if kind is not None and _pfu.supported(kind, pv, gv, st):
                # sentinel gating happens IN-KERNEL (bad rides in SMEM) —
                # these outputs must not be re-gated below
                np_, nst = _pfu.param_update(
                    kind, pv, gv, lr, st, hy,
                    wd=ctx._weight_decay, bad=bad,
                )
            else:
                np_, nst = rule(ctx, pv, gv, lr, st, **hy)
                if bad is not None:
                    np_ = jnp.where(bad, pv, np_)
                    nst = jax.tree_util.tree_map(
                        lambda o, n: jnp.where(bad, o, n), st, nst
                    )
            if telemetry:
                # fused numerics telemetry: per-param sums of squares of
                # the (post-cast) grad, the param, and the APPLIED update
                # (post-gate, so a rescued step reports a zero update) —
                # independent extra outputs, the update chain is untouched
                f32 = jnp.float32
                tele_rows.append(jnp.stack([
                    jnp.sum(jnp.square(gv.astype(f32))),
                    jnp.sum(jnp.square(pv.astype(f32))),
                    jnp.sum(jnp.square((np_ - pv).astype(f32))),
                ]))
            new_ps.append(np_)
            new_sts.append(nst)
        out = (new_ps, new_sts)
        if sentinel:
            out = out + (bad,)
        if telemetry:
            out = out + (jnp.stack(tele_rows),)
        return out

    return apply_update


class Optimizer:
    _update_has_state = True

    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        name=None,
        multi_precision=False,
    ):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else None
        self._weight_decay = self._parse_wd(weight_decay)
        self._grad_clip = grad_clip
        # per-parameter optimizer state: id(param) -> dict[str, jax.Array]
        self._accumulators: Dict[int, Dict[str, jax.Array]] = {}
        self._step_count = 0

    @staticmethod
    def _parse_wd(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, float):
            return weight_decay
        # L2Decay regularizer object
        coeff = getattr(weight_decay, "_coeff", None)
        return float(coeff) if coeff is not None else float(weight_decay)

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError(
                "optimizer's learning rate is an LRScheduler; call scheduler.step()"
            )
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # -- state rules (override per optimizer) --------------------------------
    def _create_state(self, p: Tensor) -> Dict[str, jax.Array]:
        return {}

    def _update(self, p, g, lr, state, **hyper):
        raise NotImplementedError

    def _hyper(self) -> Dict:
        """Static hyper-parameters baked into the jitted update."""
        return {}

    def _per_param_hyper(self, p: Tensor) -> Dict:
        """Static per-parameter hyper overrides (e.g. no-decay params) —
        consumed by the compiled whole-step path (paddle_tpu.jit)."""
        return {}

    # -- main API ------------------------------------------------------------
    @no_grad()
    def step(self):
        """reference: optimizer.py:1185 step. The reference launches one phi
        optimizer kernel per param; here ALL param updates run as ONE cached
        jitted XLA program (the merged_adam/multi_tensor path the reference
        gates behind use_multi_tensor), so eager training pays a single
        dispatch per step instead of one per parameter."""
        # whole-step capture boundary (FLAGS_eager_step_capture): a deferred
        # backward resolves here as ONE donated XLA program covering forward
        # + backward + this update. Otherwise this is the ordinary lazy-
        # dispatch materialization point — grads (and lazily-created params)
        # are flushed concrete before the fused jitted update reads them —
        # plus step-signature observation for the capture controller.
        from ..resilience import runtime as _rrt

        # host-offload boundary (optimizer/offload.py): start the H2D
        # prefetch of parked accumulator groups now, overlapped behind the
        # step's own dispatch; step_end() below books the measured figures
        # and enqueues the next D2H sweep
        sched = getattr(self, "_offload_sched", None)
        if sched is not None:
            sched.step_begin()
        try:
            if _lazy.step_capture_step(self):
                self._step_count += 1
                return
            params_grads = [
                (p, p.grad)
                for p in self._param_list()
                if not p.stop_gradient and p.grad is not None
            ]
            if self._grad_clip is not None:
                params_grads = self._grad_clip(params_grads)
            self._step_count += 1
            if params_grads:
                self._apply_fused(params_grads)
        finally:
            if sched is not None:
                sched.step_end()
            # resilience step boundary: advances the fault-injection step
            # counter and the degradation ladder's cooldown clocks
            _rrt.on_step_end()

    def _apply_fused(self, params_grads):
        from ..core import dispatch as _dispatch
        from ..resilience import faults as _faults
        from ..resilience import rescue as _rescue
        from ..resilience import runtime as _rrt

        params = [p for p, _ in params_grads]
        g_vals = [
            (_lazy.materialize(g._value) if isinstance(g, Tensor) else g)
            for _, g in params_grads
        ]
        # chaos harness: a `nan:grads` clause poisons the first gradient
        # this step (the numeric-rescue sentinel must catch it in-program)
        plan = _faults.active_plan()
        if plan is not None and g_vals and plan.nan_fires(
            "grads", _faults.current_step()
        ):
            _dispatch._counters["injected_faults"] += 1
            g_vals = list(g_vals)
            g_vals[0] = jnp.full_like(g_vals[0], jnp.nan)
        sentinel = _rescue.active()
        from ..profiler import attribution as _attribution

        telemetry = _attribution.telemetry_active()
        sched = getattr(self, "_offload_sched", None)
        if sched is not None:
            # join the prefetch: any accumulator still parked on the host
            # comes back NOW, and the wait is booked as blocked time (the
            # overhead figure the scheduler tunes against)
            sched.ensure_resident(self, params)
        states = []
        for p in params:
            st = self._accumulators.get(id(p))
            if st is None:
                st = self._create_state(p)
                self._accumulators[id(p)] = st
            states.append(st)
        # key covers everything the traced update reads besides its arrays:
        # rule identity, global + per-param statics, and array shapes/dtypes
        # (jit would retrace on those anyway; keying here keeps one wrapper
        # per configuration instead of leaking one per optimizer instance).
        # The key is memoized per (param identity, shapes/dtypes) — rebuilding
        # it each step costs more than the whole host-side dispatch.
        per_hypers = tuple(
            tuple(sorted(self._per_param_hyper(p).items())) for p in params
        )
        # the Pallas fused-update enablement changes the traced program —
        # it must key the cache so flipping the flag retraces
        pallas = (
            bool(_flags.flag("pallas_fused_update")),
            bool(_flags.flag("pallas_update_interpret")),
        )
        sig = (
            tuple(sorted(self._hyper().items())),
            per_hypers,
            self._weight_decay,
            sentinel,
            telemetry,
            pallas,
            tuple(
                (id(p), p._value.shape, p._value.dtype, g.dtype)
                for p, g in zip(params, g_vals)
            ),
        )
        memo = getattr(self, "_fused_key_memo", None)
        if memo is not None and memo[0] == sig:
            key = memo[1]
        else:
            key = (
                type(self),
                tuple(sorted(self._hyper().items())),
                per_hypers,
                self._weight_decay,
                sentinel,
                telemetry,
                pallas,
                tuple(
                    (p._value.shape, str(p._value.dtype), str(g.dtype))
                    for p, g in zip(params, g_vals)
                ),
            )
            self._fused_key_memo = (sig, key)
        fn = _jit_update_cache.get(key)
        if fn is None:
            # make_fused_update binds a bare weight-decay shim, NOT `self`:
            # this cache is global and capturing the instance would pin its
            # accumulators (potentially hundreds of MB of moments) forever
            fn = jax.jit(make_fused_update(self, params, sentinel=sentinel,
                                           telemetry=telemetry))
            _jit_update_cache[key] = fn
        p_vals = [p._value for p in params]
        lr = jnp.asarray(self.get_lr(), dtype=jnp.float32)
        out = _rrt.execute("optimizer", lambda: fn(p_vals, g_vals, lr, states))
        new_ps, new_sts = out[0], out[1]
        extra = list(out[2:])
        bad = extra.pop(0) if sentinel else None
        tele = extra.pop(0) if telemetry else None
        _count_program("optimizer")
        for p, npv, nst in zip(params, new_ps, new_sts):
            p._value = npv
            self._accumulators[id(p)] = nst
        if tele is not None:
            # fused telemetry host-read BEFORE the rescue policy, so a
            # rescue postmortem's tail already carries the spike event
            _attribution.record_telemetry(
                _attribution.group_names(params), tele)
        if bad is not None:
            # host-read of the fused sentinel (same program's output —
            # no extra launch); applies skip / lr_backoff / abort
            _rescue.handle_sentinel(self, bad)

    def _param_list(self) -> List[Tensor]:
        if self._parameters is None:
            raise ValueError(
                "optimizer was created without a parameter list (static-graph "
                "mode is driven through minimize())"
            )
        return self._parameters

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        """reference: optimizer.py:1120 — backward + apply."""
        loss.backward()
        self.step()
        return None, None

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        for p in self._param_list():
            p.clear_grad()

    clear_gradients = clear_grad

    # -- checkpoint ----------------------------------------------------------
    def state_dict(self):
        # a compiled (pipelined) step may hold authoritative stacked moments;
        # let it write them back into _accumulators first
        sync = getattr(self, "_lazy_state_sync", None)
        if sync is not None:
            sync()
        out = {"_step_count": self._step_count}
        params = self._param_list()
        for i, p in enumerate(params):
            st = self._accumulators.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"{p.name or i}.{k}"] = Tensor(v)
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("_step_count", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
        params = self._param_list()
        for i, p in enumerate(params):
            prefix = f"{p.name or i}."
            st = {}
            for k, v in state_dict.items():
                if isinstance(k, str) and k.startswith(prefix):
                    val = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                    st[k[len(prefix):]] = val
            if st:
                cur = self._accumulators.get(id(p)) or self._create_state(p)
                cur.update(st)
                self._accumulators[id(p)] = cur

    set_dict = set_state_dict

    def _apply_weight_decay_l2(self, g, p):
        if self._weight_decay:
            return g + self._weight_decay * p
        return g


class SGD(Optimizer):
    """reference: phi/kernels/sgd_kernel.h."""

    def _update(self, p, g, lr, state):
        g = self._apply_weight_decay_l2(g, p)
        return p - lr.astype(p.dtype) * g, state


class Momentum(Optimizer):
    """reference: phi momentum_kernel; use_nesterov supported."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _hyper(self):
        return {"mu": self._momentum, "nesterov": self._nesterov}

    def _create_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update(self, p, g, lr, state, *, mu, nesterov):
        g = self._apply_weight_decay_l2(g, p)
        v = mu * state["velocity"] + g
        if nesterov:
            step = g + mu * v
        else:
            step = v
        return p - lr.astype(p.dtype) * step, {"velocity": v}


class Adam(Optimizer):
    """reference: phi adam_kernel; bias-corrected like the reference
    (beta1/beta2 pow accumulators)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _hyper(self):
        return {"b1": self._beta1, "b2": self._beta2, "eps": self._epsilon}

    def _create_state(self, p):
        return {
            "moment1": jnp.zeros_like(p._value),
            "moment2": jnp.zeros_like(p._value),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, p, g, lr, state, *, b1, b2, eps):
        g = self._apply_weight_decay_l2(g, p)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        lr_t = (lr * jnp.sqrt(1 - b2p) / (1 - b1p)).astype(p.dtype)
        new_p = p - lr_t * m / (jnp.sqrt(v) + eps)
        return new_p, {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p,
        }


class AdamW(Adam):
    """reference: phi adamw_kernel — decoupled weight decay."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        self._wd_coeff = float(weight_decay) if not hasattr(weight_decay, "_coeff") else float(weight_decay._coeff)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _hyper(self):
        return {"b1": self._beta1, "b2": self._beta2, "eps": self._epsilon,
                "wd": self._wd_coeff}

    @no_grad()
    def _update(self, p, g, lr, state, *, b1, b2, eps, wd):
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        lr_t = (lr * jnp.sqrt(1 - b2p) / (1 - b1p)).astype(p.dtype)
        new_p = p * (1.0 - (lr * wd).astype(p.dtype)) - lr_t * m / (jnp.sqrt(v) + eps)
        return new_p, {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p,
        }

    def _per_param_hyper(self, p):
        # single decay-exclusion path, merged identically by the eager
        # _apply_one and the compiled train step
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(
            p.name
        ):
            return {"wd": 0.0}
        return {}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _hyper(self):
        return {"b1": self._beta1, "b2": self._beta2, "eps": self._epsilon}

    def _create_state(self, p):
        return {
            "moment": jnp.zeros_like(p._value),
            "inf_norm": jnp.zeros_like(p._value),
            "beta1_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, p, g, lr, state, *, b1, b2, eps):
        g = self._apply_weight_decay_l2(g, p)
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        b1p = state["beta1_pow"] * b1
        new_p = p - (lr / (1 - b1p)).astype(p.dtype) * m / (u + eps)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _hyper(self):
        return {"eps": self._epsilon}

    def _create_state(self, p):
        return {"moment": jnp.full_like(p._value, self._init_acc)}

    def _update(self, p, g, lr, state, *, eps):
        g = self._apply_weight_decay_l2(g, p)
        acc = state["moment"] + jnp.square(g)
        return p - lr.astype(p.dtype) * g / (jnp.sqrt(acc) + eps), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _hyper(self):
        return {"eps": self._epsilon, "rho": self._rho}

    def _create_state(self, p):
        return {
            "avg_squared_grad": jnp.zeros_like(p._value),
            "avg_squared_update": jnp.zeros_like(p._value),
        }

    def _update(self, p, g, lr, state, *, eps, rho):
        g = self._apply_weight_decay_l2(g, p)
        asg = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        update = (
            jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(asg + eps) * g
        )
        asu = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(update)
        return p - lr.astype(p.dtype) * update, {
            "avg_squared_grad": asg, "avg_squared_update": asu,
        }


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _hyper(self):
        return {"rho": self._rho, "eps": self._epsilon,
                "mu": self._momentum, "centered": self._centered}

    def _create_state(self, p):
        return {
            "mean_square": jnp.zeros_like(p._value),
            "mean_grad": jnp.zeros_like(p._value),
            "momentum": jnp.zeros_like(p._value),
        }

    def _update(self, p, g, lr, state, *, rho, eps, mu, centered):
        g = self._apply_weight_decay_l2(g, p)
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(g)
        if centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + eps)
        mom = mu * state["momentum"] + lr.astype(p.dtype) * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op + LambOptimizer meta-optimizer."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _hyper(self):
        return {"b1": self._beta1, "b2": self._beta2, "eps": self._epsilon,
                "wd": self._wd}

    def _create_state(self, p):
        return {
            "moment1": jnp.zeros_like(p._value),
            "moment2": jnp.zeros_like(p._value),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, p, g, lr, state, *, b1, b2, eps, wd):
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where(
            (w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0
        ).astype(p.dtype)
        return p - lr.astype(p.dtype) * trust * r, {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p,
        }


class Lars(Optimizer):
    """LARS — layer-wise adaptive rate scaling for large-batch SGD.

    reference: operators/optimizers/lars_momentum_op.cc + the
    LarsOptimizer meta-optimizer (fleet/meta_optimizers/lars_optimizer.py):
    local_lr = lr * coeff * ||w|| / (||g|| + lambda*||w|| + eps), momentum
    applied on the rescaled gradient."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, exclude_from_weight_decay=None,
                 epsilon=0.0, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._wd = lars_weight_decay
        self._eps = epsilon
        # name fragments excluded from weight decay (reference: lars
        # meta-optimizer's exclude_from_weight_decay list — biases/norms)
        self._exclude = list(exclude_from_weight_decay or [])

    def _hyper(self):
        return {"mu": self._momentum, "coeff": self._coeff, "wd": self._wd,
                "eps": self._eps}

    def _per_param_hyper(self, p):
        name = getattr(p, "name", "") or ""
        if any(frag in name for frag in self._exclude):
            return {"wd": 0.0}
        return {}

    def _create_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update(self, p, g, lr, state, *, mu, coeff, wd, eps):
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            coeff * w_norm / (g_norm + wd * w_norm + eps),
            1.0,
        ).astype(p.dtype)
        step = g + wd * p
        v = mu * state["velocity"] + (lr.astype(p.dtype) * local_lr) * step
        return p - v, {"velocity": v}
