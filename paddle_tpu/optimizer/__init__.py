"""paddle.optimizer — optimizers + lr schedulers.

Reference analogue: python/paddle/optimizer/ (5.9k LoC).
"""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Lars,
    Momentum,
    Optimizer,
    RMSProp,
)
from . import offload  # noqa: F401  (host offload of cold optimizer state)
