"""Host offload of cold optimizer state (paddle.optimizer.offload).

The liveness planner (paddle_tpu.analysis.plan.cold_state_indices) proves
what every Adam-family trainer already knows: the moment accumulators are
*cold* — their only reads and writes happen inside the trailing fused
update, so their HBM buffers sit dead through the whole forward + backward.
This scheduler parks those buffers in host memory between step boundaries
and prefetches them back before the update that consumes them:

    step N ends   -> D2H copies enqueued on the worker thread (overlapped
                     behind whatever the host does next — data loading,
                     the next forward's dispatch)
    step N+1 begins (Optimizer.step entry) -> H2D prefetch enqueued
    update reads accumulators -> ensure_resident() joins the prefetch;
                     any wait is *measured* as blocked time

Cadence discipline is CheckFreq's (PAPERS.md), the same loop PR 8 runs for
snapshot persistence: measured transfer EMAs against an overhead budget.
When the blocked-time share of a step exceeds ``FLAGS_offload_overhead_pct``
the scheduler halves the offloaded set (largest groups stay — they buy the
most HBM per transfer); when it stays well under, the set regrows. Restore
is exact because offload rides the existing two-phase checkpoint commit:
``state_dict()`` runs the optimizer's ``_lazy_state_sync`` hook, which this
module chains to make every stashed group resident first — a snapshot never
sees a half-transferred moment, and ``set_state_dict`` simply overwrites
the stash entries with restored device arrays.

Scope: the eager fused step and the whole-step capture (their accumulator
reads go through ``ensure_resident``). ``jit.compile_train_step`` pins its
optimizer state as donated device arrays for the program's lifetime — a
step that keeps state in HBM by construction has nothing to offload.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["enable", "disable", "scheduler_of", "state"]

_MB = float(1 << 20)


class _HostValue:
    """One accumulator array parked in host memory. Stored *inside* the
    optimizer's accumulator dict in place of the device array, so every
    code path that replaces accumulator entries (set_state_dict, elastic
    reshard) naturally overwrites the stash instead of leaking it."""

    __slots__ = ("host", "shape", "dtype")

    def __init__(self, host: np.ndarray, shape, dtype):
        self.host = host
        self.shape = shape
        self.dtype = dtype

    def device(self):
        return jnp.asarray(self.host)


class _OffloadScheduler:
    """Per-optimizer offload state machine. All mutation of accumulator
    dicts happens under ``_lock``; the worker thread only ever swaps an
    entry it can still identify (value identity checked under the lock), so
    a concurrent restore/reshard that replaced the entry wins."""

    def __init__(self, opt, *, overhead_pct: Optional[float] = None,
                 min_bytes: int = 1 << 16):
        from ..core import flags as _flags

        self._opt_ref = weakref.ref(opt)
        self.overhead_pct = (
            float(_flags.flag("offload_overhead_pct"))
            if overhead_pct is None else float(overhead_pct))
        self.min_bytes = int(min_bytes)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._inflight: set = set()  # (id(state_dict), key) being transferred
        self._jobs: List[Tuple] = []
        self._stop = False
        # (id(state_dict), key) -> (state_dict, key, nbytes); insertion holds
        # a strong ref to the dict only while the group is selected
        self._groups: Dict[Tuple[int, str], Tuple[dict, str, int]] = {}
        self._selected: Optional[List[Tuple[int, str]]] = None
        self._max_groups: Optional[int] = None  # tuning knob (None = all)
        self._cold_source = "heuristic"
        # measured EMAs (ms): device->host, host->device, blocked-at-update
        self.d2h_ema_ms = 0.0
        self.h2d_ema_ms = 0.0
        self.blocked_ema_ms = 0.0
        self.step_ema_ms = 0.0
        self.overhead_pct_ema = 0.0
        self.d2h_count = 0
        self.h2d_count = 0
        self.shrinks = 0
        self.regrows = 0
        self.steps = 0
        self._t_step_begin: Optional[float] = None
        self._worker = threading.Thread(
            target=self._run, name="paddle-offload", daemon=True)
        self._worker.start()

    # -- worker ------------------------------------------------------------
    def _run(self):
        while True:
            with self._lock:
                while not self._jobs and not self._stop:
                    self._cv.wait(timeout=0.1)
                if self._stop and not self._jobs:
                    return
                job = self._jobs.pop(0)
            try:
                self._do_job(job)
            except Exception:
                with self._lock:
                    self._inflight.discard(job[:2])
                    self._cv.notify_all()

    def _do_job(self, job):
        did, key, st, direction = job
        t0 = time.perf_counter()
        if direction == "d2h":
            with self._lock:
                val = st.get(key)
            if val is not None and not isinstance(val, _HostValue):
                host = np.asarray(val)
                hv = _HostValue(host, tuple(val.shape), val.dtype)
                with self._lock:
                    if st.get(key) is val:  # nobody replaced it meanwhile
                        st[key] = hv
                dt = (time.perf_counter() - t0) * 1000.0
                self.d2h_ema_ms = _ema(self.d2h_ema_ms, dt)
                self.d2h_count += 1
        else:  # h2d prefetch
            with self._lock:
                val = st.get(key)
            if isinstance(val, _HostValue):
                dev = val.device()
                dev.block_until_ready()
                with self._lock:
                    if st.get(key) is val:
                        st[key] = dev
                dt = (time.perf_counter() - t0) * 1000.0
                self.h2d_ema_ms = _ema(self.h2d_ema_ms, dt)
                self.h2d_count += 1
        with self._lock:
            self._inflight.discard((did, key))
            self._cv.notify_all()

    def _enqueue(self, st: dict, key: str, direction: str):
        did = id(st)
        with self._lock:
            if (did, key) in self._inflight:
                return
            self._inflight.add((did, key))
            self._jobs.append((did, key, st, direction))
            self._cv.notify_all()

    # -- group selection ---------------------------------------------------
    def _select_groups(self, opt):
        """Choose the accumulator entries to offload: planner-marked cold
        state over the last captured step program when one exists, else the
        shape heuristic (any non-scalar accumulator >= min_bytes — for the
        Adam family exactly the moment tensors, not the beta powers)."""
        params = [p for p in opt._param_list() if not p.stop_gradient]
        entries: List[Tuple[dict, str, int]] = []
        for p in params:
            st = opt._accumulators.get(id(p))
            if not st:
                continue
            for k in sorted(st):
                v = st[k]
                shape = getattr(v, "shape", ())
                nbytes = int(getattr(v, "nbytes", 0) or 0)
                if len(tuple(shape)) >= 1 and nbytes >= self.min_bytes:
                    entries.append((st, k, nbytes))
        cold = self._planner_cold_keys(opt, params)
        if cold is not None:
            entries = [e for e in entries if (id(e[0]), e[1]) in cold]
            self._cold_source = "planner"
        self._groups = {(id(st), k): (st, k, nb) for st, k, nb in entries}
        # largest first: each transfer has fixed overhead, big groups buy
        # the most HBM per ms of transfer
        order = sorted(self._groups, key=lambda g: -self._groups[g][2])
        self._selected = order
        if self._max_groups is not None:
            self._selected = order[:self._max_groups]

    def _planner_cold_keys(self, opt, params):
        """(id(state_dict), key) pairs the remat planner proves cold over
        the last captured step program, or None when no capture replayed
        yet (the caller falls back to the shape heuristic)."""
        try:
            from ..core import lazy as _lazy
            from ..analysis import plan as _plan

            prog = _lazy.captured_step_program()
            if prog is None:
                return None
            closed, _donated, roles = prog
            cold = _plan.cold_state_indices(closed, roles)
            if not cold:
                return None
            cold_idx = {
                int(name[len("opt_state"):])
                for _i, name in cold if name.startswith("opt_state")
            }
            # opt_state leaves flatten params-outer, sorted-keys-inner —
            # the same order _capture_args builds the states tuple
            keys = set()
            flat = 0
            for p in params:
                st = opt._accumulators.get(id(p)) or {}
                for k in sorted(st):
                    if flat in cold_idx:
                        keys.add((id(st), k))
                    flat += 1
            return keys or None
        except Exception:
            return None

    # -- step-boundary hooks (Optimizer.step) ------------------------------
    def step_begin(self):
        """Optimizer.step() entry: start prefetching every offloaded group
        back to the device, overlapped behind the step's own dispatch."""
        self._t_step_begin = time.perf_counter()
        with self._lock:
            groups = list(self._selected or ())
        for g in groups:
            ent = self._groups.get(g)
            if ent is None:
                continue
            st, k, _nb = ent
            if isinstance(st.get(k), _HostValue):
                self._enqueue(st, k, "h2d")

    def step_end(self):
        """Optimizer.step() exit: book the step's measured figures, retune
        the offloaded set against the overhead budget, and enqueue the D2H
        copies for the groups that stay offloaded."""
        opt = self._opt_ref()
        if opt is None:
            return
        now = time.perf_counter()
        if self._t_step_begin is not None:
            step_ms = (now - self._t_step_begin) * 1000.0
            self.step_ema_ms = _ema(self.step_ema_ms, step_ms)
        self.steps += 1
        if self._selected is None or (
                self._cold_source == "heuristic" and self.steps <= 8):
            # early steps re-run selection: the first captured-step replay
            # usually lands a few steps in, upgrading the cold-group choice
            # from the shape heuristic to the planner's liveness proof
            self._select_groups(opt)
        self._retune()
        with self._lock:
            groups = list(self._selected or ())
        for g in groups:
            ent = self._groups.get(g)
            if ent is None:
                continue
            st, k, _nb = ent
            v = st.get(k)
            if v is not None and not isinstance(v, _HostValue):
                self._enqueue(st, k, "d2h")
        self._publish()

    def _retune(self):
        """CheckFreq discipline: measured overhead vs the budget. Blocked
        EMA over step EMA is the truthful cost — transfers that finished
        behind the step are free no matter how many bytes moved."""
        if self.step_ema_ms <= 0.0 or self._selected is None:
            return
        pct = 100.0 * self.blocked_ema_ms / self.step_ema_ms
        self.overhead_pct_ema = pct
        n_all = len(self._groups)
        n_sel = len(self._selected)
        if pct > self.overhead_pct and n_sel > 0:
            self._max_groups = max(0, n_sel // 2)
            self.shrinks += 1
            # decay the blocked EMA so one spike doesn't pin the set at the
            # shrunken size forever; the next overrun shrinks again
            self.blocked_ema_ms *= 0.5
            self._reselect()
        elif pct < 0.25 * self.overhead_pct and n_sel < n_all:
            self._max_groups = min(n_all, max(1, n_sel * 2))
            self.regrows += 1
            self._reselect()

    def _reselect(self):
        order = sorted(self._groups, key=lambda g: -self._groups[g][2])
        keep = order if self._max_groups is None else order[:self._max_groups]
        with self._lock:
            dropped = [g for g in (self._selected or ()) if g not in set(keep)]
            self._selected = keep
        # groups leaving the offload set come home for good
        for g in dropped:
            ent = self._groups.get(g)
            if ent is not None and isinstance(ent[0].get(ent[1]), _HostValue):
                self._enqueue(ent[0], ent[1], "h2d")

    # -- consumer-side hooks ------------------------------------------------
    def ensure_resident(self, opt, params) -> float:
        """Make every accumulator of ``params`` a device array again,
        joining in-flight transfers first. Returns (and books) the blocked
        milliseconds — the scheduler's honest overhead figure."""
        t0 = time.perf_counter()
        waited = False
        dicts = []
        for p in params:
            st = opt._accumulators.get(id(p))
            if st:
                dicts.append(st)
        with self._lock:
            pending = {(id(st), k) for st in dicts for k in st}
            while self._inflight & pending:
                waited = True
                self._cv.wait(timeout=0.1)
        for st in dicts:
            for k in list(st):
                v = st.get(k)
                if isinstance(v, _HostValue):
                    waited = True
                    dev = v.device()
                    with self._lock:
                        if st.get(k) is v:
                            st[k] = dev
        blocked_ms = (time.perf_counter() - t0) * 1000.0 if waited else 0.0
        self.blocked_ema_ms = _ema(self.blocked_ema_ms, blocked_ms)
        return blocked_ms

    def sync(self):
        """Drain the worker and bring EVERY stashed group resident — the
        two-phase checkpoint commit and state_dict() run through this, so a
        snapshot always sees whole device arrays."""
        opt = self._opt_ref()
        with self._lock:
            while self._inflight:
                self._cv.wait(timeout=0.1)
        if opt is None:
            return
        for p in opt._param_list():
            st = opt._accumulators.get(id(p))
            if not st:
                continue
            for k in list(st):
                v = st.get(k)
                if isinstance(v, _HostValue):
                    with self._lock:
                        if st.get(k) is v:
                            st[k] = v.device()

    def stop(self):
        self.sync()
        with self._lock:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout=5.0)

    # -- observability ------------------------------------------------------
    def offloaded_bytes(self) -> int:
        total = 0
        with self._lock:
            sel = list(self._selected or ())
        for g in sel:
            ent = self._groups.get(g)
            if ent is not None and isinstance(ent[0].get(ent[1]), _HostValue):
                total += ent[2]
        return total

    def snapshot(self) -> Dict[str, Any]:
        return {
            "groups_total": len(self._groups),
            "groups_selected": len(self._selected or ()),
            "cold_source": self._cold_source,
            "offloaded_mb": round(self.offloaded_bytes() / _MB, 3),
            "d2h_ema_ms": round(self.d2h_ema_ms, 3),
            "h2d_ema_ms": round(self.h2d_ema_ms, 3),
            "blocked_ema_ms": round(self.blocked_ema_ms, 3),
            "step_ema_ms": round(self.step_ema_ms, 3),
            "overhead_pct_ema": round(self.overhead_pct_ema, 3),
            "overhead_budget_pct": self.overhead_pct,
            "d2h_count": self.d2h_count,
            "h2d_count": self.h2d_count,
            "shrinks": self.shrinks,
            "regrows": self.regrows,
            "steps": self.steps,
        }

    def _publish(self):
        try:
            from ..core import dispatch

            dispatch._emit("offload", site="optimizer", phase="step",
                           groups=len(self._selected or ()),
                           offloaded_mb=round(self.offloaded_bytes() / _MB, 3),
                           overhead_pct=round(self.overhead_pct_ema, 3))
        except Exception:
            pass
        try:
            from ..profiler import metrics as _metrics

            reg = _metrics.default_registry()
            reg.gauge("memory_plan_offload_groups",
                      doc="accumulator groups currently selected for host "
                          "offload").set(len(self._selected or ()))
            reg.gauge("memory_plan_offload_mb",
                      doc="bytes of optimizer state parked on the host, MB"
                      ).set(self.offloaded_bytes() / _MB)
            reg.gauge("memory_plan_offload_overhead_pct",
                      doc="measured blocked time as % of step time (EMA); "
                          "budget is FLAGS_offload_overhead_pct"
                      ).set(self.overhead_pct_ema)
        except Exception:
            pass


def _ema(cur: float, new: float, alpha: float = 0.2) -> float:
    return new if cur == 0.0 else (1.0 - alpha) * cur + alpha * new


# ---------------------------------------------------------------------------
# Public API + registry (the /statusz section reads state())
# ---------------------------------------------------------------------------
_registry: "weakref.WeakValueDictionary[int, _OffloadScheduler]" = (
    weakref.WeakValueDictionary())
_reg_lock = threading.Lock()


def enable(optimizer, *, overhead_pct: Optional[float] = None,
           min_bytes: int = 1 << 16) -> _OffloadScheduler:
    """Attach a host-offload scheduler to ``optimizer``. Idempotent: a
    second call returns the existing scheduler. ``overhead_pct`` overrides
    FLAGS_offload_overhead_pct for this optimizer; ``min_bytes`` is the
    smallest accumulator worth a round trip (beta-power scalars never
    qualify)."""
    sched = getattr(optimizer, "_offload_sched", None)
    if sched is not None:
        return sched
    sched = _OffloadScheduler(optimizer, overhead_pct=overhead_pct,
                              min_bytes=min_bytes)
    optimizer._offload_sched = sched
    # chain the checkpoint sync hook: state_dict() / TrainingState.refresh
    # call _lazy_state_sync before reading accumulators — offload joins the
    # same commit point so snapshots are exact (two-phase commit intact)
    prev = getattr(optimizer, "_lazy_state_sync", None)

    def _sync_chain(_prev=prev, _s=weakref.ref(sched)):
        if _prev is not None:
            _prev()
        s = _s()
        if s is not None:
            s.sync()

    optimizer._lazy_state_sync = _sync_chain
    sched._prev_sync = prev  # for disable()
    with _reg_lock:
        _registry[id(optimizer)] = sched
    return sched


def disable(optimizer) -> None:
    """Detach and stop the scheduler; every stashed group is brought back
    to the device first, so training continues exactly where it was."""
    sched = getattr(optimizer, "_offload_sched", None)
    if sched is None:
        return
    sched.stop()
    optimizer._offload_sched = None
    optimizer._lazy_state_sync = getattr(sched, "_prev_sync", None)
    with _reg_lock:
        _registry.pop(id(optimizer), None)


def scheduler_of(optimizer) -> Optional[_OffloadScheduler]:
    return getattr(optimizer, "_offload_sched", None)


def state() -> List[Dict[str, Any]]:
    """Snapshots of every live scheduler (the /statusz 'memory plan &
    offload' section)."""
    with _reg_lock:
        scheds = list(_registry.values())
    return [s.snapshot() for s in scheds]
