"""paddle.autograd — user-facing autograd API.

Reference analogue: python/paddle/autograd/ (PyLayer at py_layer.py:202,
paddle.grad in fluid/dygraph/base.py, functional vjp/jvp in functional.py).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.dispatch import GradNode, enable_grad, is_grad_enabled, no_grad  # noqa: F401
from ..core.tensor import Tensor

__all__ = [
    "grad",
    "backward",
    "PyLayer",
    "PyLayerContext",
    "no_grad",
    "enable_grad",
    "vjp",
    "jvp",
    "Jacobian",
    "Hessian",
    "jacobian",
    "hessian",
    "functional",
]


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph: Optional[bool] = None,
    create_graph: bool = False,
    only_inputs: bool = True,
    allow_unused: bool = False,
    no_grad_vars=None,
    name=None,
):
    """paddle.grad (reference: fluid/dygraph/base.py grad) — returns grads of
    `outputs` w.r.t. `inputs` without touching .grad.

    With create_graph=True the backward computation is itself recorded on the
    tape (see dispatch.run_backward), so the returned grads can be
    differentiated again — the reference's double-grad op path.
    """
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    single = isinstance(inputs, Tensor)
    inputs = [inputs] if single else list(inputs)
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    got = dispatch.run_backward(
        outputs,
        grad_outputs,
        retain_graph=bool(retain_graph),
        inputs=inputs,
        create_graph=create_graph,
    )
    results = []
    for t in inputs:
        g = got.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "one of the input tensors received no gradient "
                    "(pass allow_unused=True to return None for it)"
                )
            results.append(None)
        elif isinstance(g, Tensor):
            results.append(g if create_graph else Tensor(g._value, stop_gradient=True))
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results[0] if single else results


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward."""
    tensors = [tensors] if isinstance(tensors, Tensor) else list(tensors)
    dispatch.run_backward(tensors, grad_tensors, retain_graph=retain_graph)


class PyLayerContext:
    """reference: python/paddle/autograd/py_layer.py PyLayerContext."""

    def __init__(self):
        self._saved = ()
        self.non_differentiable = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def mark_non_differentiable(self, *tensors):
        self.non_differentiable = tensors


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op with user forward/backward.

    Reference: python/paddle/autograd/py_layer.py:202. The tape integration
    records a GradNode whose vjp calls the user's static `backward`.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        is_seq = isinstance(outputs, (tuple, list))
        out_list = list(outputs) if is_seq else [outputs]

        # paddle contract (py_layer.py backward docs): user backward returns
        # one grad per *tensor* input of forward, in declaration order; the
        # engine ignores grads for stop_gradient inputs.
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        any_trainable = any(not a.stop_gradient for a in tensor_inputs)
        if not is_grad_enabled() or not any_trainable:
            return outputs

        out_avals = [
            (tuple(o._value.shape), o._value.dtype) for o in out_list
        ]

        def vjp_fn(cotangents):
            if not isinstance(cotangents, tuple):
                cotangents = (cotangents,)
            grads = cls.backward(
                ctx, *[Tensor(c, stop_gradient=True) for c in cotangents]
            )
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            grads = [g._value if isinstance(g, Tensor) else g for g in grads]
            if len(grads) != len(tensor_inputs):
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(grads)} grads for "
                    f"{len(tensor_inputs)} tensor inputs"
                )
            return tuple(grads)

        node = GradNode(vjp_fn, tensor_inputs, out_avals, cls.__name__)
        nd = set(map(id, ctx.non_differentiable))
        wired = []
        for i, o in enumerate(out_list):
            t = o
            if id(o) not in nd and jnp.issubdtype(o._value.dtype, jnp.floating):
                t.stop_gradient = False
                t._grad_node = node
                t._out_index = i
            wired.append(t)
        return wired if is_seq else wired[0]


from . import functional  # noqa: E402
from .functional import Hessian, Jacobian, hessian, jacobian, jvp, vjp  # noqa: E402,F401
