"""paddle.autograd.functional — functional higher-order autodiff.

Reference analogue: python/paddle/autograd/functional.py (vjp/jvp at module
top, Jacobian/Hessian lazy-matrix classes). TPU-native design: instead of
replaying registered double-grad ops, each API wraps the user function into a
pure jax function over raw arrays and leans on jax's composable transforms
(jax.vjp / jax.jvp / jax.jacrev / jax.jacfwd / jax.hessian) — every result is
exact to machine precision, and the whole computation stages into one XLA
program.
"""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "jacobian", "hessian"]


def _unwrap(xs):
    if isinstance(xs, (tuple, list)):
        return [x._value if isinstance(x, Tensor) else jnp.asarray(x) for x in xs]
    return [xs._value if isinstance(xs, Tensor) else jnp.asarray(xs)]


def _pure(func: Callable):
    """Lift a Tensor->Tensor user function to a pure array function."""

    def f(*arrs):
        outs = func(*[Tensor(a, stop_gradient=True) for a in arrs])
        if isinstance(outs, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in outs)
        return outs._value if isinstance(outs, Tensor) else outs

    return f


def _wrap(v):
    if isinstance(v, (tuple, list)):
        return [Tensor(x, stop_gradient=True) for x in v]
    return Tensor(v, stop_gradient=True)


def vjp(func, xs, v=None):
    """Vector-Jacobian product: returns (func(xs), vjp_result).

    Reference: python/paddle/autograd/functional.py vjp.
    """
    vals = _unwrap(xs)
    out, vjp_fn = jax.vjp(_pure(func), *vals)
    if v is None:
        v_val = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_val = v._value if isinstance(v, Tensor) else (
            tuple(_unwrap(v)) if isinstance(v, (tuple, list)) else jnp.asarray(v)
        )
    grads = vjp_fn(v_val)
    gs = [Tensor(g, stop_gradient=True) for g in grads]
    out_t = _wrap(list(out)) if isinstance(out, tuple) else _wrap(out)
    return out_t, (gs if isinstance(xs, (tuple, list)) else gs[0])


def jvp(func, xs, v=None):
    """Jacobian-vector product: returns (func(xs), jvp_result)."""
    vals = _unwrap(xs)
    if v is None:
        tangents = [jnp.ones_like(x) for x in vals]
    else:
        tangents = _unwrap(v)
    out, jv = jax.jvp(_pure(func), tuple(vals), tuple(tangents))
    out_t = _wrap(list(out)) if isinstance(out, tuple) else _wrap(out)
    jv_t = _wrap(list(jv)) if isinstance(jv, tuple) else _wrap(jv)
    return out_t, jv_t


class Jacobian:
    """Lazy Jacobian matrix of func at xs (reference functional.py Jacobian).

    The full Jacobian is computed once (jax.jacrev, one staged XLA program)
    on first element access; indexing views it as the reference does: a 2D
    matrix of shape [out_numel, in_numel] (single input, single output).
    """

    def __init__(self, func, xs, is_batched: bool = False):
        self._func = func
        self._xs = xs
        self._is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        vals = _unwrap(self._xs)
        multi_in = isinstance(self._xs, (tuple, list))
        jac = jax.jacrev(_pure(self._func), argnums=tuple(range(len(vals))))(*vals)

        def flat2d(j, out_shape, in_shape, batched):
            if batched:
                b = j.shape[0]
                o = int(jnp.prod(jnp.array(out_shape[1:]))) if len(out_shape) > 1 else 1
                i = int(jnp.prod(jnp.array(in_shape[1:]))) if len(in_shape) > 1 else 1
                # batched layout [B, out_numel, in_numel]; jacrev gives
                # [*out_shape, *in_shape] — take the diagonal over batch
                j = j.reshape(out_shape + in_shape)
                idx = jnp.arange(b)
                j = j.reshape((b, o, b, i))[idx, :, idx, :]
                return j
            o = int(jnp.prod(jnp.array(out_shape))) if out_shape else 1
            i = int(jnp.prod(jnp.array(in_shape))) if in_shape else 1
            return j.reshape((o, i))

        out = jax.eval_shape(_pure(self._func), *vals)
        out_shape = tuple(out.shape) if not isinstance(out, tuple) else None
        if out_shape is None:
            raise NotImplementedError("Jacobian over multi-output functions")
        mats = [
            flat2d(j, out_shape, tuple(v.shape), self._is_batched)
            for j, v in zip(jac, vals)
        ]
        self._mat = jnp.concatenate(mats, axis=-1) if multi_in else mats[0]
        return self._mat

    @property
    def shape(self):
        return tuple(self._compute().shape)

    def __getitem__(self, idx):
        return Tensor(self._compute()[idx], stop_gradient=True)

    def numpy(self):
        import numpy as np

        return np.asarray(self._compute())


class Hessian:
    """Lazy Hessian matrix of a scalar-output func at xs."""

    def __init__(self, func, xs, is_batched: bool = False):
        if is_batched:
            raise NotImplementedError("batched Hessian")
        self._func = func
        self._xs = xs
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        vals = _unwrap(self._xs)
        multi_in = isinstance(self._xs, (tuple, list))

        def scalar_f(*arrs):
            out = _pure(self._func)(*arrs)
            if isinstance(out, tuple):
                raise ValueError("Hessian requires a single scalar output")
            return jnp.reshape(out, ())

        if multi_in:
            flat_sizes = [int(v.size) for v in vals]
            shapes = [tuple(v.shape) for v in vals]

            def packed_f(flat):
                parts, o = [], 0
                for s, sh in zip(flat_sizes, shapes):
                    parts.append(flat[o : o + s].reshape(sh))
                    o += s
                return scalar_f(*parts)

            flat0 = jnp.concatenate([v.reshape(-1) for v in vals])
            self._mat = jax.hessian(packed_f)(flat0)
        else:
            n = int(vals[0].size)
            h = jax.hessian(scalar_f)(vals[0])
            self._mat = h.reshape((n, n))
        return self._mat

    @property
    def shape(self):
        return tuple(self._compute().shape)

    def __getitem__(self, idx):
        return Tensor(self._compute()[idx], stop_gradient=True)

    def numpy(self):
        import numpy as np

        return np.asarray(self._compute())


def jacobian(func, xs, create_graph: bool = False, allow_unused: bool = False):
    """Dense Jacobian as Tensor(s) — the reference's legacy functional.jacobian."""
    if create_graph:
        raise NotImplementedError(
            "jacobian(create_graph=True): use paddle.grad(..., create_graph=True) "
            "per row, or differentiate through Jacobian via paddle.incubate.autograd"
        )
    j = Jacobian(func, xs)
    return j[:]


def hessian(func, xs, create_graph: bool = False, allow_unused: bool = False):
    if create_graph:
        raise NotImplementedError(
            "hessian(create_graph=True): compose paddle.grad(..., create_graph=True) "
            "sweeps instead"
        )
    h = Hessian(func, xs)
    return h[:]
