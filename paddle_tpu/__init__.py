"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built on JAX/XLA/Pallas/pjit.

Layer map (TPU-native; see SURVEY.md for the reference's):
  core/        L0-L2: Place, dtype, flags, RNG, Tensor (PJRT buffers),
               dispatch (per-op XLA compile cache) + tape autograd engine
  ops/         L3: pure-jax kernels (the PHI-kernel analogue; Pallas in ops/pallas)
  tensor_api   L9: the ~500-function paddle.* tensor API
  nn/          Layer system, functional ops, initializers
  optimizer/   optimizers + lr schedulers (eager step() and pure update core)
  amp/         bf16 auto_cast O1/O2 + GradScaler
  jit/         to_static: whole-program jax.jit tracing (the executor zoo)
  static/      Program/Executor compatibility facade
  io/          Dataset/DataLoader
  distributed/ fleet, collectives over jax.sharding.Mesh, launch
  parallel/    mesh topology, TP/PP/EP/SP engines, sharding (ZeRO)
  vision/ hapi/ metric/ ...  user-facing packages
"""
from __future__ import annotations

from . import version  # noqa: F401
from .version import full_version as __version__  # noqa: F401


def __getattr__(name):
    # lazy: version.commit costs a git subprocess on first access
    if name == "__git_commit__":
        return version.commit
    raise AttributeError(name)

import jax as _jax

# paddle semantics: float64 tensors and int64 default integer dtype are
# first-class (reference exposes full fp64 kernels); jax disables x64 by
# default, so enable it once at import. TPU compute paths use f32/bf16
# explicitly, so this does not affect accelerator performance.
_jax.config.update("jax_enable_x64", True)

from . import core
from .core import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    DType,
    Generator,
    Place,
    TPUPlace,
    Tensor,
    bfloat16,
    bool_,
    complex64,
    complex128,
    device_count,
    enable_grad,
    float16,
    float32,
    float64,
    get_default_dtype,
    get_device,
    get_rng_state,
    int8,
    int16,
    int32,
    int64,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    no_grad,
    seed,
    set_default_dtype,
    set_device,
    set_grad_enabled,
    set_rng_state,
    to_tensor,
    uint8,
)
from .core.flags import get_flags, set_flags  # noqa: F401

# the full tensor function API (paddle.add, paddle.matmul, ...)
from .tensor_api import *  # noqa: F401,F403
from . import tensor_api as _tensor_api

# subpackages — imported when present (built up milestone by milestone; the
# list mirrors the reference's python/paddle/ package tree)
import importlib as _importlib

for _pkg in (
    "analysis",
    "nn",
    "regularizer",
    "sysconfig",
    "callbacks",
    "optimizer",
    "autograd",
    "amp",
    "io",
    "jit",
    "static",
    "linalg",
    "metric",
    "vision",
    "framework",
    "distributed",
    "incubate",
    "profiler",
    "resilience",
    "hapi",
    "text",
    "distribution",
    "sparse",
    "fft",
    "signal",
    "onnx",
    "inference",
    "serving",
    "device",
    "hub",
    "utils",
    "cost_model",
    "quantization",
    "reader",
    "compat",
    "dataset",
):
    try:
        globals()[_pkg] = _importlib.import_module(f".{_pkg}", __name__)
    except ModuleNotFoundError as _e:
        if f"paddle_tpu.{_pkg}" not in str(_e):
            raise  # real import error inside an existing subpackage

from .batch import batch  # noqa: E402,F401

if "autograd" in globals() and hasattr(globals()["autograd"], "grad"):
    grad = globals()["autograd"].grad
if "framework" in globals() and hasattr(globals()["framework"], "io_utils"):
    load = globals()["framework"].io_utils.load
    save = globals()["framework"].io_utils.save
if "hapi" in globals():
    Model = globals()["hapi"].Model
    summary = globals()["hapi"].summary
if "distributed" in globals() and hasattr(globals()["distributed"], "parallel"):
    DataParallel = globals()["distributed"].parallel.DataParallel
if "static" in globals():
    disable_static = globals()["static"].disable_static
    enable_static = globals()["static"].enable_static

in_dynamic_mode = _tensor_api.in_dynamic_mode


def is_grad_enabled():
    return core.is_grad_enabled()


# remaining top-level reference names (python/paddle/__init__.py __all__)
from .core.place import (  # noqa: E402,F401
    CUDAPlace,
    CustomPlace,
    IPUPlace,
    MLUPlace,
    NPUPlace,
    XPUPlace,
)

bool = bool_  # noqa: A001 — paddle.bool is the dtype (reference parity)
dtype = DType
if "nn" in globals():
    ParamAttr = globals()["nn"].ParamAttr
if "hapi" in globals():
    from .hapi.dynamic_flops import flops  # noqa: E402,F401

# the accelerator generator state IS the cuda one on this build
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state
