"""paddle._C_ops — the raw op-call namespace.

Reference analogue: python/paddle/_C_ops.py (re-exports the pybind'd op
entry points; user code and generated layers call `_C_ops.matmul(...)`
directly). Here every lookup forwards to the public op surface — the
`final_state_` prefix the reference's generated code uses is stripped.
"""
from __future__ import annotations


def __getattr__(name):
    from . import nn, tensor_api

    base = name[len("final_state_"):] if name.startswith("final_state_") \
        else name
    for mod in (tensor_api, nn.functional):
        fn = getattr(mod, base, None)
        if fn is not None:
            return fn
    import paddle_tpu as _p

    fn = getattr(_p, base, None)
    if fn is not None and callable(fn):
        return fn
    raise AttributeError(f"paddle._C_ops has no op {name!r}")
