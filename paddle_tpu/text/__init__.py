"""paddle.text — Viterbi decoding + NLP datasets.

Reference analogue: python/paddle/text/ (viterbi_decode.py over the phi
viterbi_decode kernel; datasets/{imdb,imikolov,conll05,movielens,
uci_housing,wmt14,wmt16}.py). Zero-egress environment: dataset classes fall
back to deterministic synthetic corpora with the real field structure
(vision/datasets.py pattern) when no local copy exists.

TPU-native viterbi: the dynamic program is one `lax.scan` over time with a
max/argmax recurrence — static shapes, masked by lengths — then a reverse
scan for backtracking; no per-step host dispatch.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor
from ..io.dataset import Dataset
from ..nn.layer_base import Layer

__all__ = [
    "viterbi_decode", "ViterbiDecoder",
    "Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05", "WMT14", "WMT16",
]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """reference: text/viterbi_decode.py:24 — returns (scores, paths)."""

    def f(emission, trans, lens, include_bos_eos_tag):
        B, T, N = emission.shape
        if include_bos_eos_tag:
            # last row/col = start tag, second-to-last = stop tag
            start_idx, stop_idx = N - 1, N - 2
            init = emission[:, 0] + trans[start_idx][None, :]
        else:
            init = emission[:, 0]

        def step(carry, t):
            alpha, _ = carry
            # scores[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, t, j]
            scores = alpha[:, :, None] + trans[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)              # [B, N]
            best_score = jnp.max(scores, axis=1) + emission[:, t]
            valid = (t < lens)[:, None]
            new_alpha = jnp.where(valid, best_score, alpha)
            bp = jnp.where(valid, best_prev,
                           jnp.arange(N)[None, :].repeat(B, 0))
            return (new_alpha, None), bp

        (alpha, _), bps = jax.lax.scan(
            step, (init, None), jnp.arange(1, T)
        )  # bps [T-1, B, N]
        if include_bos_eos_tag:
            stop_trans = trans[:, N - 2]
            alpha = alpha + stop_trans[None, :]
        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1)  # [B]

        # backtrack from each sequence's last valid position
        def back(carry, t):
            tag = carry
            bp_t = bps[t]                                    # [B, N]
            prev = jnp.take_along_axis(bp_t, tag[:, None], 1)[:, 0]
            active = (t + 1) < lens                          # step t+1 was real
            prev = jnp.where(active, prev, tag)
            return prev, tag

        tag0, rev_tags = jax.lax.scan(
            back, last_tag, jnp.arange(T - 2, -1, -1)
        )
        paths = jnp.concatenate(
            [tag0[None, :], rev_tags[::-1]], axis=0
        ).T  # [B, T]
        # zero out positions beyond each length (reference pads with the path)
        mask = jnp.arange(T)[None, :] < lens[:, None]
        paths = jnp.where(mask, paths, 0)
        return scores, paths.astype(jnp.int64)

    res = apply(
        f, potentials, transition_params,
        (lengths if isinstance(lengths, Tensor) else to_tensor(lengths)).astype("int64"),
        include_bos_eos_tag=include_bos_eos_tag, op_name="viterbi_decode",
    )
    return res[0], res[1]


class ViterbiDecoder(Layer):
    """reference: text/viterbi_decode.py:91."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(
            potentials, self.transitions, lengths, self.include_bos_eos_tag
        )


# ---------------------------------------------------------------------------
# datasets (synthetic fallback, deterministic)
# ---------------------------------------------------------------------------
class _SyntheticTextDataset(Dataset):
    VOCAB = 2048

    def __init__(self, mode, n, seed):
        self.mode = mode
        self._rng = np.random.default_rng(seed if mode == "train" else seed + 1)
        self._n = n

    def __len__(self):
        return self._n


class Imdb(_SyntheticTextDataset):
    """reference: text/datasets/imdb.py — (tokens, polarity label)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        super().__init__(mode, 256, 7)
        lens = self._rng.integers(20, 120, self._n)
        self.docs = [
            self._rng.integers(0, self.VOCAB, L).astype(np.int64) for L in lens
        ]
        self.labels = self._rng.integers(0, 2, self._n).astype(np.int64)
        self.word_idx = {i: i for i in range(self.VOCAB)}

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Imikolov(_SyntheticTextDataset):
    """reference: text/datasets/imikolov.py — n-gram LM tuples."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        super().__init__(mode, 512, 11)
        self.window_size = window_size
        self.data = self._rng.integers(
            0, self.VOCAB, (self._n, window_size)
        ).astype(np.int64)
        self.word_idx = {i: i for i in range(self.VOCAB)}

    def __getitem__(self, i):
        return tuple(self.data[i])


class Movielens(_SyntheticTextDataset):
    """reference: text/datasets/movielens.py."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        super().__init__(mode, 384, 13)
        self.data = [
            (
                self._rng.integers(0, 6040),   # user id
                self._rng.integers(0, 2),      # gender
                self._rng.integers(0, 7),      # age bucket
                self._rng.integers(0, 21),     # job
                self._rng.integers(0, 3952),   # movie id
                self._rng.integers(0, 19),     # category
                float(self._rng.integers(1, 6)),  # score
            )
            for _ in range(self._n)
        ]

    def __getitem__(self, i):
        return self.data[i]


class UCIHousing(_SyntheticTextDataset):
    """reference: text/datasets/uci_housing.py — 13 features → price."""

    def __init__(self, data_file=None, mode="train", download=True):
        super().__init__(mode, 404 if mode == "train" else 102, 17)
        w = np.random.default_rng(3).normal(size=13).astype(np.float32)
        self.x = self._rng.normal(size=(self._n, 13)).astype(np.float32)
        noise = 0.1 * self._rng.normal(size=self._n).astype(np.float32)
        self.y = (self.x @ w + noise).astype(np.float32)[:, None]

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Conll05(_SyntheticTextDataset):
    """reference: text/datasets/conll05.py — SRL fields."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 mode="train", download=True):
        super().__init__(mode, 128, 19)
        self.num_labels = 67
        lens = self._rng.integers(5, 40, self._n)
        self.samples = [
            (
                self._rng.integers(0, self.VOCAB, L).astype(np.int64),  # words
                self._rng.integers(0, self.VOCAB),                      # verb
                self._rng.integers(0, self.num_labels, L).astype(np.int64),
            )
            for L in lens
        ]

    def __getitem__(self, i):
        return self.samples[i]


class _WMT(_SyntheticTextDataset):
    def __init__(self, mode, dict_size, seed):
        super().__init__(mode, 256, seed)
        self.dict_size = dict_size
        lens = self._rng.integers(4, 30, self._n)
        self.pairs = [
            (
                self._rng.integers(0, dict_size, L).astype(np.int64),
                self._rng.integers(0, dict_size, L + self._rng.integers(-2, 3))
                .astype(np.int64),
            )
            for L in lens
        ]

    def __getitem__(self, i):
        src, tgt = self.pairs[i]
        return src, tgt[:-1], tgt[1:]


class WMT14(_WMT):
    """reference: text/datasets/wmt14.py."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        super().__init__(mode, dict_size, 23)


class WMT16(_WMT):
    """reference: text/datasets/wmt16.py."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        super().__init__(mode, src_dict_size, 29)


# reference name alias (python/paddle/text/datasets/conll05.py Conll05st)
Conll05st = Conll05
