"""paddle.linalg namespace.

Reference analogue: python/paddle/linalg.py (re-exports from tensor/linalg.py).
"""
from __future__ import annotations

from .core.dispatch import apply
from .ops import linalg as _la
from .tensor_api import (  # noqa: F401
    bmm,
    cross,
    dist,
    dot,
    matmul,
    mm,
    mv,
    norm,
    t,
    trace,
)


def cholesky(x, upper=False, name=None):
    return apply(_la.cholesky, x, upper=upper)


def inv(x, name=None):
    return apply(_la.inverse, x)


inverse = inv


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(_la.pinv, x, rcond=rcond, hermitian=hermitian)


def det(x, name=None):
    return apply(_la.det, x)


def slogdet(x, name=None):
    return apply(_la.slogdet, x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(_la.matrix_rank, x, tol=tol, hermitian=hermitian, differentiable=False)


def matrix_power(x, n, name=None):
    return apply(_la.matrix_power, x, n=int(n))


def qr(x, mode="reduced", name=None):
    out = apply(_la.qr, x, mode=mode)
    return out[0], out[1]


def svd(x, full_matrices=False, name=None):
    out = apply(_la.svd, x, full_matrices=full_matrices)
    return out[0], out[1], out[2]


def eig(x, name=None):
    out = apply(_la.eig, x, differentiable=False)
    return out[0], out[1]


def eigh(x, UPLO="L", name=None):
    out = apply(_la.eigh, x, UPLO=UPLO)
    return out[0], out[1]


def eigvals(x, name=None):
    return apply(_la.eigvals, x, differentiable=False)


def eigvalsh(x, UPLO="L", name=None):
    return apply(_la.eigvalsh, x, UPLO=UPLO)


def solve(x, y, name=None):
    return apply(_la.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return apply(
        _la.triangular_solve, x, y, upper=upper, transpose=transpose,
        unitriangular=unitriangular,
    )


def cholesky_solve(x, y, upper=False, name=None):
    return apply(_la.cholesky_solve, x, y, upper=upper)


def lstsq(x, y, rcond=None, driver=None, name=None):
    out = apply(_la.lstsq, x, y, rcond=rcond, differentiable=False)
    return tuple(out)


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization; pivots are 1-based sequential row swaps (reference:
    tensor/linalg.py lu — LAPACK getrf convention)."""
    out = apply(_la.lu, x, differentiable=False)
    lu_mat, piv = out[0], out[1] + 1
    if get_infos:
        import numpy as _np

        from .core.tensor import to_tensor

        info = to_tensor(_np.zeros(tuple(lu_mat.shape[:-2]), _np.int32))
        return lu_mat, piv, info
    return lu_mat, piv


def multi_dot(x, name=None):
    return apply(_la.multi_dot, *x)


def cond(x, p=None, name=None):
    return apply(_la.cond, x, p=p, differentiable=False)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(_la.cov, x, rowvar=rowvar, ddof=ddof)


def corrcoef(x, rowvar=True, name=None):
    return apply(_la.corrcoef, x, rowvar=rowvar)


def histogram(x, bins=100, min=0, max=0, name=None):
    return apply(_la.histogram, x, bins=bins, min=min, max=max, differentiable=False)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack jnp.linalg-style LU factorization into (P, L, U) (reference:
    tensor/linalg.py lu_unpack)."""
    import jax.numpy as jnp
    import numpy as np

    from .core.dispatch import apply

    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)

    def _unpack(lu, piv):
        L = jnp.tril(lu[..., :, :k], -1) + jnp.eye(m, k, dtype=lu.dtype)
        U = jnp.triu(lu[..., :k, :])
        # pivots (1-based sequential row swaps) -> permutation matrix
        perm = jnp.broadcast_to(jnp.arange(m), piv.shape[:-1] + (m,))

        def swap(perm, i):
            j = piv[..., i] - 1
            pi = perm[..., i]
            pj = jnp.take_along_axis(perm, j[..., None], axis=-1)[..., 0]
            perm = perm.at[..., i].set(pj)
            return jnp.put_along_axis(perm, j[..., None], pi[..., None],
                                      axis=-1, inplace=False), None

        for i in range(piv.shape[-1]):
            perm, _ = swap(perm, i)
        P = jax.nn.one_hot(perm, m, dtype=lu.dtype)
        # rows permuted: P[perm[i], i] = 1 so that A = P @ L @ U
        return jnp.swapaxes(P, -1, -2), L, U

    import jax

    P, L, U = apply(_unpack, lu_data, lu_pivots, differentiable=False,
                    op_name="lu_unpack")
    # reference flag semantics: un-requested outputs come back as None
    if not unpack_pivots:
        P = None
    if not unpack_ludata:
        L = U = None
    return P, L, U
