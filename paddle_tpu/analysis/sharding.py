"""SPMD sharding analyzer: per-shard analysis IR, collective cost model,
resharding lints.

Reference analogue: the reference's hybrid-parallel stack validates its
collective programs at runtime (reducer bucket checks, pipeline schedule
asserts); GSPMD-style systems instead derive a *static* cost model from the
partitioned program and feed it back into planning (the Alpa/GSPMD
discipline in PAPERS.md). This module does that over the PR 2 analysis IR:

  - ``ShardContext`` — a mesh-scoped :class:`~paddle_tpu.analysis.Context`
    whose inliner rewrites every buffer's aval to its **per-shard** shape.
    Each jaxpr invar becomes a fresh per-shard ``ShardVar`` (same soundness
    rule as the pjit inlining: fresh canonical SSA per instance), specs are
    propagated through elementwise/transpose/reshape/broadcast/reduce/
    dot_general/slice ops, ``sharding_constraint`` equations re-anchor them,
    and ``shard_map`` regions are inlined *through* (their body avals are
    already per-shard). Every downstream pass — ``memory_budget``,
    ``donation_safety``, ``plan_memory`` — then operates on what one chip
    actually holds.
  - an **implied-collective** model for GSPMD programs, which carry no
    explicit collectives in the jaxpr (XLA inserts them at partitioning):
    a ``dot_general`` whose contracted dimension is sharded on axis *a*
    implies a psum of the output over *a* (this is exactly the dp gradient
    all-reduce and the row-parallel TP activation reduce); a reduction over
    a sharded dimension implies the same; a ``sharding_constraint`` that
    un-shards a dimension implies an all-gather, and one that moves a
    dimension between axes implies an all-to-all.
  - ``collective_cost`` (registered pass): classifies every explicit and
    implied collective with per-device bytes-on-wire under a ring-ICI cost
    model (all-reduce moves ``2·(n-1)/n·B``, all-gather ``(n-1)·B_shard``,
    reduce-scatter / all-to-all ``(n-1)/n·B``, ppermute ``B``) and reports
    the per-program comm/compute ratio. The same numbers feed
    ``profiler.attribution`` static profiles (``comm_bytes`` /
    ``collective_count`` — visible in ``/programz`` and ``fleet_top
    --programs``).
  - ``resharding_lint`` (registered pass): implicit-reshard hazards —
    psum∘psum over the same axis, all_gather immediately sliced back to the
    shard, a replicated output where the declared out-spec says sharded,
    and loop-invariant collectives inside scan bodies that could hoist.

Both passes stay silent on programs with no mesh, no ``shard_map`` region,
and no collectives, so the single-device ``FLAGS_check_programs`` suites
add no noise.

Public as ``paddle.static.analysis.sharding``. Entry points:
``check_sharded_step`` (lint a ``ShardedTrainStep`` without compiling it),
``shard_context`` (build a per-shard Context for any traced jaxpr),
``parse_mesh`` (``"dp=2,mp=2"`` → axis dict, the ``graph_lint --mesh``
syntax), and ``plan_memory(ctx, mesh=...)`` in ``analysis.memory`` for the
per-device peak-HBM estimate.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core import flags as _flags
from . import (
    CanonVar,
    ConstAtom,
    Context,
    Diagnostic,
    FlatOp,
    Severity,
    _as_open,
    _resolve,
    _sub_jaxprs,
    register_pass,
)

__all__ = [
    "CollectiveOp",
    "ShardContext",
    "ShardVar",
    "captured_step_context",
    "check_sharded_step",
    "collective_records",
    "collective_stats",
    "parse_mesh",
    "pipelined_step_context",
    "ring_wire_bytes",
    "schedule_of",
    "shard_context",
    "sharded_step_context",
]

# primitives that move bytes between devices; psum2/pbroadcast appear under
# shard_map's check_rep rewrite, the rest are the explicit lax collectives
_COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmax", "pmin", "all_gather", "reduce_scatter",
    "all_to_all", "ppermute", "pbroadcast",
}
# cost-model kind per primitive (pmax/pmin are all-reduces on the wire)
_COLL_KIND = {
    "psum": "psum", "psum2": "psum", "pmax": "psum", "pmin": "psum",
    "all_gather": "all_gather", "reduce_scatter": "reduce_scatter",
    "all_to_all": "all_to_all", "ppermute": "ppermute",
    "pbroadcast": "pbroadcast",
}


def parse_mesh(text) -> Dict[str, int]:
    """``"dp=2,mp=2"`` → ``{"dp": 2, "mp": 2}`` (the graph_lint --mesh and
    test syntax). Also accepts a jax ``Mesh`` or an axis dict unchanged."""
    if isinstance(text, dict):
        return {str(k): int(v) for k, v in text.items()}
    shape = getattr(text, "shape", None)
    if shape is not None and hasattr(shape, "items"):  # jax Mesh
        return {str(k): int(v) for k, v in shape.items()}
    axes: Dict[str, int] = {}
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, val = part.partition("=")
        if not val:
            raise ValueError(
                f"bad mesh spec {text!r}: expected axis=size pairs like "
                "'dp=2,mp=2'"
            )
        axes[name.strip()] = int(val)
    return axes


def ring_wire_bytes(kind: str, payload_bytes: int, group_size: int) -> int:
    """Per-device bytes on wire for one collective under the ring-ICI model
    (bidirectional ring over the mesh axis, the TPU ICI topology): an
    all-reduce is reduce-scatter + all-gather (``2·(n-1)/n·B``), an
    all-gather receives every other shard (``(n-1)·B_shard``),
    reduce-scatter and all-to-all each move ``(n-1)/n`` of the local
    payload, a ppermute forwards the full payload once, and pbroadcast is a
    replication marker with no wire traffic. Pure integer arithmetic —
    golden-testable, no timing."""
    n = int(group_size)
    b = int(payload_bytes)
    if n <= 1 or b <= 0:
        return 0
    if kind == "psum":
        return 2 * b * (n - 1) // n
    if kind == "all_gather":
        return b * (n - 1)
    if kind in ("reduce_scatter", "all_to_all"):
        return b * (n - 1) // n
    if kind == "ppermute":
        return b
    return 0  # pbroadcast / unknown


@dataclasses.dataclass
class CollectiveOp:
    """One classified collective (explicit or implied by the spec model)."""

    kind: str  # psum | all_gather | reduce_scatter | all_to_all | ppermute | pbroadcast
    path: str  # flat-op path it is attached to
    axes: Tuple[str, ...]  # mesh axes it reduces/moves over
    group_size: int  # product of the axis sizes
    payload_bytes: int  # per-device payload entering the collective
    wire_bytes: int  # per-device bytes on wire (ring-ICI), one execution
    count: int = 1  # trip multiplicity (scan bodies)
    implied: bool = False  # True: inserted by GSPMD, not in the jaxpr
    shape: Tuple = ()
    dtype: str = ""

    @property
    def total_wire_bytes(self) -> int:
        return int(self.wire_bytes) * int(self.count)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind, "path": self.path, "axes": list(self.axes),
            "group_size": int(self.group_size),
            "payload_bytes": int(self.payload_bytes),
            "wire_bytes": int(self.wire_bytes), "count": int(self.count),
            "implied": bool(self.implied),
            "shape": [int(d) for d in self.shape], "dtype": self.dtype,
        }


# ---------------------------------------------------------------------------
# Spec arithmetic: a spec is a per-dim tuple of mesh-axis-name tuples
# ---------------------------------------------------------------------------
def _norm_spec(pspec, ndim: int) -> Tuple[Tuple[str, ...], ...]:
    """PartitionSpec / tuple / None → canonical ``((axes...),) * ndim``."""
    entries = list(pspec) if pspec is not None else []
    out: List[Tuple[str, ...]] = []
    for e in entries[:ndim]:
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(a for a in e if a is not None))
        else:
            out.append((e,))
    while len(out) < ndim:
        out.append(())
    return tuple(out)


def _dedupe_spec(spec) -> Tuple[Tuple[str, ...], ...]:
    """A mesh axis may shard at most one dim — keep the first occurrence."""
    seen = set()
    out = []
    for names in spec:
        kept = tuple(a for a in names if a not in seen)
        seen.update(kept)
        out.append(kept)
    return tuple(out)


def _merge_dim(a: Tuple[str, ...], b: Tuple[str, ...]) -> Tuple[str, ...]:
    if a == b or not b:
        return a
    if not a:
        return b
    return a  # conflict: keep the first (conservative)


def _merge_specs(specs: Sequence, ndim: int) -> Tuple[Tuple[str, ...], ...]:
    out = [()] * ndim
    for s in specs:
        s = tuple(s)
        off = ndim - len(s)  # right-align broadcasting inputs
        for d, names in enumerate(s):
            out[off + d] = _merge_dim(out[off + d], names)
    return _dedupe_spec(tuple(out))


def _shard_factor(names: Tuple[str, ...], axes: Dict[str, int]) -> int:
    f = 1
    for a in names:
        f *= int(axes.get(a, 1))
    return f


def _shard_aval(aval, spec, axes):
    """Per-shard aval: each sharded dim divided by its axis-size product.
    A dim the axes do not divide stays global (XLA pads; the estimate must
    stay an upper bound)."""
    shape = tuple(getattr(aval, "shape", ()))
    if not shape or aval is None:
        return aval
    new = list(shape)
    changed = False
    for d, names in enumerate(spec[:len(new)]):
        f = _shard_factor(names, axes)
        if f > 1 and new[d] % f == 0:
            new[d] //= f
            changed = True
    if not changed:
        return aval
    try:
        return aval.update(shape=tuple(new))
    except Exception:
        return jax.core.ShapedArray(tuple(new), aval.dtype)


def _aval_nbytes(aval) -> int:
    if aval is None:
        return 0
    shape = tuple(getattr(aval, "shape", ()))
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    try:
        item = int(np.dtype(dt).itemsize)
    except TypeError:
        item = int(getattr(dt, "itemsize", 8))
    return n * item


class ShardVar(CanonVar):
    """Per-shard canonical SSA value: a CanonVar whose aval is the
    per-device shape, annotated with the propagated partition spec.
    ``explicit`` marks specs pinned by the program itself (an invar
    sharding, a sharding_constraint, a collective) rather than derived by
    propagation — the resharding lint only trusts explicit specs."""

    __slots__ = ("spec", "explicit")

    def __init__(self, aval, spec=(), explicit=False):
        super().__init__(aval)
        self.spec = tuple(spec)
        self.explicit = bool(explicit)

    def __repr__(self):
        return f"ShardVar({self.aval}, spec={self.spec})"


def _spec_of(atom, ndim: int) -> Tuple[Tuple[str, ...], ...]:
    if isinstance(atom, ShardVar):
        return _norm_spec(atom.spec, ndim)
    return ((),) * ndim


# ---------------------------------------------------------------------------
# The mesh-scoped inliner
# ---------------------------------------------------------------------------
def _coll_axes(params) -> Tuple[str, ...]:
    ax = params.get("axes", params.get("axis_name"))
    if ax is None:
        return ()
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


class _ShardInliner:
    """Rewrites a (global-shaped) closed jaxpr into the per-shard flat-op
    IR, recording every explicit and implied collective on the way."""

    def __init__(self, axes: Dict[str, int], collectives: List[CollectiveOp]):
        self.axes = dict(axes)
        self.collectives = collectives
        self.ops: List[FlatOp] = []
        self.producers: Dict[Any, FlatOp] = {}

    # -- collective recording ------------------------------------------------
    def _record(self, kind, path, names, payload, *, count=1, implied=False,
                shape=(), dtype=""):
        names = tuple(a for a in names if a in self.axes or a not in ())
        n = _shard_factor(tuple(names), self.axes)
        self.collectives.append(CollectiveOp(
            kind=kind, path=path, axes=tuple(names), group_size=n,
            payload_bytes=int(payload),
            wire_bytes=ring_wire_bytes(kind, payload, n),
            count=int(count), implied=implied,
            shape=tuple(int(d) for d in shape), dtype=str(dtype),
        ))

    # -- op emission ---------------------------------------------------------
    def _emit(self, name, invars, out_avals, out_specs, params, scope,
              explicit=False):
        outs = [ShardVar(av, sp, explicit=explicit)
                for av, sp in zip(out_avals, out_specs)]
        op = FlatOp(name, invars, outs, params, scope, len(self.ops))
        for ov in outs:
            self.producers[ov] = op
        self.ops.append(op)
        return op, outs

    # -- entry ---------------------------------------------------------------
    def run(self, closed, in_specs):
        open_jaxpr, consts = _as_open(closed)
        invar_atoms = []
        env: Dict[Any, Any] = {}
        specs = list(in_specs or [])
        for i, v in enumerate(open_jaxpr.invars):
            ndim = len(tuple(getattr(v.aval, "shape", ())))
            spec = _norm_spec(specs[i] if i < len(specs) else None, ndim)
            sv = ShardVar(_shard_aval(v.aval, spec, self.axes), spec,
                          explicit=True)
            env[v] = sv
            invar_atoms.append(sv)
        self._walk(open_jaxpr, consts, env, "", 1, manual=False)
        out_atoms = [_resolve(v, env) for v in open_jaxpr.outvars]
        return self.ops, self.producers, out_atoms, invar_atoms

    # -- the walk ------------------------------------------------------------
    def _walk(self, open_jaxpr, consts, env, scope, mult, manual):
        for cv, cval in zip(open_jaxpr.constvars, consts):
            env[cv] = ConstAtom(cval)
        for eqn in open_jaxpr.eqns:
            name = eqn.primitive.name
            ins = [_resolve(v, env) for v in eqn.invars]
            if name == "shard_map":
                self._shard_map(eqn, ins, env, scope, mult)
                continue
            if name in ("scan", "while", "cond", "switch"):
                self._scoped(eqn, ins, env, scope, mult, manual)
                continue
            kind, subs = _sub_jaxprs(eqn)
            if kind == "call":
                sub_open, sub_consts = _as_open(subs[0])
                if len(sub_open.invars) == len(eqn.invars):
                    ienv = dict(zip(sub_open.invars, ins))
                    self._walk(sub_open, sub_consts, ienv, scope, mult,
                               manual)
                    for ov, iov in zip(eqn.outvars, sub_open.outvars):
                        env[ov] = _resolve(iov, ienv)
                    continue
            self._primitive(eqn, ins, env, scope, mult, manual)

    # -- shard_map: mesh-scoped inline-through -------------------------------
    def _shard_map(self, eqn, ins, env, scope, mult):
        body, body_consts = _as_open(eqn.params["jaxpr"])
        mesh = eqn.params.get("mesh")
        shape = getattr(mesh, "shape", None)
        if shape is not None and hasattr(shape, "items"):
            for k, v in shape.items():
                self.axes.setdefault(str(k), int(v))
        in_names = eqn.params.get("in_names") or ()
        out_names = eqn.params.get("out_names") or ()

        def names_spec(names, ndim):
            spec = [()] * ndim
            for d, ax in (names or {}).items():
                if int(d) < ndim:
                    spec[int(d)] = tuple(ax)
            return tuple(spec)

        ienv = {}
        for i, (iv, outer) in enumerate(zip(body.invars, ins)):
            iv_aval = getattr(iv, "aval", None)
            outer_aval = getattr(outer, "aval", None)
            if (outer_aval is not None and iv_aval is not None
                    and tuple(getattr(outer_aval, "shape", ())) ==
                    tuple(getattr(iv_aval, "shape", ()))
                    and not isinstance(outer, jax.core.Literal)):
                # per-shard shapes agree: the body reads the caller's buffer
                # in place — substitute (sound: fresh ShardVars upstream)
                ienv[iv] = outer
            else:
                # layouts differ (outer spec ≠ in_names): XLA reshards at
                # the boundary; a "reshard" view op keeps liveness honest
                ndim = len(tuple(getattr(iv_aval, "shape", ())))
                spec = names_spec(in_names[i] if i < len(in_names) else {},
                                  ndim)
                _, outs = self._emit(
                    "reshard", [outer], [iv_aval], [spec], {}, scope)
                ienv[iv] = outs[0]
        self._walk(body, body_consts, ienv, scope, mult, manual=True)
        for i, (ov, iov) in enumerate(zip(eqn.outvars, body.outvars)):
            inner = _resolve(iov, ienv)
            ndim = len(tuple(getattr(ov.aval, "shape", ())))
            spec = names_spec(out_names[i] if i < len(out_names) else {},
                              ndim)
            per_shard = _shard_aval(ov.aval, spec, self.axes)
            inner_aval = getattr(inner, "aval", None)
            if (inner_aval is not None and tuple(
                    getattr(inner_aval, "shape", ())) ==
                    tuple(getattr(per_shard, "shape", ()))
                    and not isinstance(inner, jax.core.Literal)):
                if isinstance(inner, ShardVar):
                    inner.spec = spec
                    inner.explicit = True
                env[ov] = inner
            else:
                _, outs = self._emit(
                    "reshard", [inner], [per_shard], [spec], {}, scope,
                    explicit=True)
                env[ov] = outs[0]

    # -- scan/while/cond: scope-style with spec-mapped body invars -----------
    def _scoped(self, eqn, ins, env, scope, mult, manual):
        name = eqn.primitive.name
        _, subs = _sub_jaxprs(eqn)
        body_mult = mult
        n_consts = n_carry = 0
        if name == "scan":
            n_consts = int(eqn.params.get("num_consts", 0))
            n_carry = int(eqn.params.get("num_carry", 0))
            body_mult = mult * max(1, int(eqn.params.get("length", 1)))
        for si, sub in enumerate(subs):
            sub_open, sub_consts = _as_open(sub)
            tag = name + (str(si) if len(subs) > 1 else "")
            ienv = {}
            for i, iv in enumerate(sub_open.invars):
                outer = ins[i] if i < len(ins) else None
                ndim = len(tuple(getattr(iv.aval, "shape", ())))
                if name == "scan" and outer is not None:
                    o_ndim = len(tuple(getattr(
                        getattr(outer, "aval", None), "shape", ())) or ())
                    o_spec = _spec_of(outer, o_ndim)
                    spec = (tuple(o_spec[:ndim]) if i < n_consts + n_carry
                            else tuple(o_spec[1:1 + ndim]))  # xs: drop scan dim
                    spec = _norm_spec(spec, ndim)
                else:
                    spec = ((),) * ndim
                ienv[iv] = ShardVar(
                    _shard_aval(iv.aval, spec, self.axes), spec)
            self._walk(sub_open, sub_consts, ienv, env_scope(scope, tag),
                       body_mult, manual)
        # the outer control-flow op itself: carry outputs inherit the carry
        # inputs' specs; stacked ys are conservatively replicated
        out_avals, out_specs = [], []
        for oi, ov in enumerate(eqn.outvars):
            ndim = len(tuple(getattr(ov.aval, "shape", ())))
            if name == "scan" and oi < n_carry:
                carry_in = ins[n_consts + oi] if n_consts + oi < len(ins) \
                    else None
                spec = _spec_of(carry_in, ndim) if carry_in is not None \
                    else ((),) * ndim
            else:
                spec = ((),) * ndim
            out_avals.append(_shard_aval(ov.aval, spec, self.axes))
            out_specs.append(spec)
        _, outs = self._emit(name, ins, out_avals, out_specs, eqn.params,
                             scope)
        for ov, sv in zip(eqn.outvars, outs):
            env[ov] = sv

    # -- plain primitives: spec propagation + implied collectives ------------
    def _primitive(self, eqn, ins, env, scope, mult, manual):
        name = eqn.primitive.name
        path = f"{scope}/eqn[{len(self.ops)}] {name}" if scope \
            else f"eqn[{len(self.ops)}] {name}"
        out_specs = self._propagate(eqn, ins, path, mult, manual)
        out_avals = []
        for ov, spec in zip(eqn.outvars, out_specs):
            if manual:
                out_avals.append(ov.aval)  # body avals are already per-shard
            else:
                out_avals.append(_shard_aval(ov.aval, spec, self.axes))
        explicit = name in ("sharding_constraint",) or name in _COLLECTIVE_PRIMS
        op, outs = self._emit(name, ins, out_avals, out_specs, eqn.params,
                              scope, explicit=explicit)
        if name in _COLLECTIVE_PRIMS:
            payload = sum(_aval_nbytes(getattr(a, "aval", None))
                          for a in ins
                          if not isinstance(a, jax.core.Literal))
            self._record(_COLL_KIND[name], op.path, _coll_axes(eqn.params),
                         payload, count=mult,
                         shape=tuple(getattr(
                             getattr(ins[0], "aval", None), "shape", ())),
                         dtype=str(getattr(
                             getattr(ins[0], "aval", None), "dtype", "")))
        for ov, sv in zip(eqn.outvars, outs):
            env[ov] = sv

    def _propagate(self, eqn, ins, path, mult, manual):
        """Out spec per outvar; records implied collectives for GSPMD
        (non-manual) regions."""
        name = eqn.primitive.name
        n_out = len(eqn.outvars)
        out_shapes = [tuple(getattr(ov.aval, "shape", ()))
                      for ov in eqn.outvars]

        def repl():
            return [((),) * len(s) for s in out_shapes]

        if manual and name not in _COLLECTIVE_PRIMS \
                and name != "sharding_constraint":
            return repl()  # manual regions: explicit collectives only

        if name == "sharding_constraint":
            sh = eqn.params.get("sharding")
            pspec = getattr(sh, "spec", None)
            ndim = len(out_shapes[0])
            new = _norm_spec(pspec, ndim) if pspec is not None \
                else ((),) * ndim
            old = _spec_of(ins[0], ndim)
            if not manual:
                self._constraint_reshard(old, new, eqn.outvars[0].aval,
                                         path, mult)
            return [new]

        if name == "dot_general":
            return [self._dot_general(eqn, ins, path, mult)]

        if name in ("reduce_sum", "reduce_prod", "reduce_max", "reduce_min",
                    "reduce_and", "reduce_or", "reduce_xor",
                    "argmax", "argmin"):
            in_spec = _spec_of(ins[0], len(tuple(getattr(
                getattr(ins[0], "aval", None), "shape", ()))))
            axes_red = tuple(eqn.params.get("axes", ()))
            red_names = tuple(a for d in axes_red
                              for a in (in_spec[d] if d < len(in_spec)
                                        else ()))
            out_spec = tuple(s for d, s in enumerate(in_spec)
                             if d not in axes_red)
            out_spec = _norm_spec(out_spec, len(out_shapes[0]))
            if red_names and name.startswith("reduce_"):
                payload = _aval_nbytes(_shard_aval(
                    eqn.outvars[0].aval, out_spec, self.axes))
                self._record("psum", path, red_names, payload, count=mult,
                             implied=True, shape=out_shapes[0],
                             dtype=str(eqn.outvars[0].aval.dtype))
            return [out_spec] + [((),) * len(s) for s in out_shapes[1:]]

        if name == "transpose":
            perm = tuple(eqn.params.get("permutation", ()))
            in_spec = _spec_of(ins[0], len(perm))
            return [tuple(in_spec[p] for p in perm)]

        if name == "broadcast_in_dim":
            in_shape = tuple(getattr(
                getattr(ins[0], "aval", None), "shape", ()))
            bdims = tuple(eqn.params.get("broadcast_dimensions", ()))
            out = [()] * len(out_shapes[0])
            in_spec = _spec_of(ins[0], len(in_shape))
            for i, d in enumerate(bdims):
                if i < len(in_shape) and in_shape[i] == out_shapes[0][d]:
                    out[d] = in_spec[i]
            return [_dedupe_spec(tuple(out))]

        if name == "reshape":
            in_shape = tuple(getattr(
                getattr(ins[0], "aval", None), "shape", ()))
            in_spec = _spec_of(ins[0], len(in_shape))
            # the walker sees GLOBAL shapes in GSPMD mode, but the op's
            # recorded avals are per-shard — use the eqn's own (global)
            # shapes for the factor matching
            g_in = tuple(getattr(eqn.invars[0].aval, "shape", ()))
            return [_reshape_spec(g_in, out_shapes[0], in_spec, self.axes)]

        if name == "squeeze":
            dims = set(eqn.params.get("dimensions", ()))
            in_spec = _spec_of(ins[0], len(tuple(getattr(
                getattr(ins[0], "aval", None), "shape", ()))))
            return [tuple(s for d, s in enumerate(in_spec) if d not in dims)]

        if name == "slice":
            g_in = tuple(getattr(eqn.invars[0].aval, "shape", ()))
            starts = tuple(eqn.params.get("start_indices", ()))
            limits = tuple(eqn.params.get("limit_indices", ()))
            strides = eqn.params.get("strides") or (1,) * len(g_in)
            in_spec = _spec_of(ins[0], len(g_in))
            out = tuple(
                in_spec[d] if (starts[d] == 0 and limits[d] == g_in[d]
                               and strides[d] == 1) else ()
                for d in range(len(g_in))
            )
            return [out]

        if name == "dynamic_slice":
            g_in = tuple(getattr(eqn.invars[0].aval, "shape", ()))
            sizes = tuple(eqn.params.get("slice_sizes", ()))
            in_spec = _spec_of(ins[0], len(g_in))
            return [tuple(in_spec[d] if sizes[d] == g_in[d] else ()
                          for d in range(len(g_in)))]

        if name in ("dynamic_update_slice", "scatter", "scatter_add",
                    "scatter-add", "scatter_mul", "scatter_min",
                    "scatter_max"):
            nd = len(out_shapes[0])
            return [_spec_of(ins[0], nd)] + [((),) * len(s)
                                             for s in out_shapes[1:]]

        if name == "concatenate":
            dim = int(eqn.params.get("dimension", 0))
            nd = len(out_shapes[0])
            merged = list(_merge_specs(
                [_spec_of(a, nd) for a in ins], nd))
            if dim < len(merged):
                merged[dim] = ()
            return [tuple(merged)]

        if name == "pad":
            cfg = tuple(eqn.params.get("padding_config", ()))
            nd = len(out_shapes[0])
            in_spec = _spec_of(ins[0], nd)
            return [tuple(in_spec[d] if d < len(cfg) and cfg[d] == (0, 0, 0)
                          else () for d in range(nd))]

        if name in ("rev", "copy", "convert_element_type", "stop_gradient",
                    "reduce_precision", "real", "imag"):
            nd = len(out_shapes[0])
            return [_spec_of(ins[0], nd)] + [((),) * len(s)
                                             for s in out_shapes[1:]]

        # generic elementwise: every input is scalar or output-shaped
        if n_out == 1:
            nd = len(out_shapes[0])
            shaped = []
            ok = True
            for a in ins:
                sh = tuple(getattr(getattr(a, "aval", None), "shape", ()))
                if sh == ():
                    continue
                # compare GLOBAL shapes (per-shard avals divide uniformly)
                shaped.append(a)
            g_out = out_shapes[0]
            for a, gv in zip(ins, eqn.invars):
                g_sh = tuple(getattr(getattr(gv, "aval", None), "shape", ()))
                if g_sh not in ((), g_out):
                    ok = False
                    break
            if ok and shaped:
                return [_merge_specs(
                    [_spec_of(a, len(tuple(getattr(
                        getattr(a, "aval", None), "shape", ()))))
                     for a in shaped], nd)]
        return repl()

    def _dot_general(self, eqn, ins, path, mult):
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs_g = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        rhs_g = tuple(getattr(eqn.invars[1].aval, "shape", ()))
        lspec = _spec_of(ins[0], len(lhs_g))
        rspec = _spec_of(ins[1], len(rhs_g))
        # out dims: batch, then lhs free, then rhs free
        out_spec: List[Tuple[str, ...]] = []
        for bl, br in zip(lb, rb):
            out_spec.append(_merge_dim(lspec[bl], rspec[br]))
        for d in range(len(lhs_g)):
            if d not in lc and d not in lb:
                out_spec.append(lspec[d])
        for d in range(len(rhs_g)):
            if d not in rc and d not in rb:
                out_spec.append(rspec[d])
        out_spec = _dedupe_spec(tuple(out_spec))
        # contracted dim sharded on axis a (either operand) → partial sums
        # per shard, GSPMD all-reduces the output over a — THE implied psum
        # (dp grad all-reduce, row-parallel TP activation reduce)
        contracted = tuple(dict.fromkeys(
            [a for d in lc for a in lspec[d]]
            + [a for d in rc for a in rspec[d]]
        ))
        if contracted:
            payload = _aval_nbytes(_shard_aval(
                eqn.outvars[0].aval, out_spec, self.axes))
            self._record("psum", path, contracted, payload, count=mult,
                         implied=True,
                         shape=tuple(getattr(eqn.outvars[0].aval, "shape",
                                             ())),
                         dtype=str(eqn.outvars[0].aval.dtype))
        return out_spec

    def _constraint_reshard(self, old, new, out_aval, path, mult):
        """A sharding_constraint that changes the layout: un-sharding a dim
        is an all-gather, moving it between axes is an all-to-all;
        sharding a replicated dim is a local slice (no wire traffic)."""
        for d, (o, n_) in enumerate(zip(old, new)):
            if o == n_:
                continue
            gathered = tuple(a for a in o if a not in n_)
            if not gathered:
                continue
            payload = _aval_nbytes(_shard_aval(out_aval, old, self.axes))
            kind = "all_to_all" if n_ else "all_gather"
            self._record(kind, path, gathered, payload, count=mult,
                         implied=True,
                         shape=tuple(getattr(out_aval, "shape", ())),
                         dtype=str(getattr(out_aval, "dtype", "")))


def env_scope(scope: str, tag: str) -> str:
    return f"{scope}/{tag}" if scope else tag


def _reshape_spec(in_shape, out_shape, in_spec, axes):
    """Propagate a spec through reshape by greedy composite-group matching:
    within a group (a run of in-dims whose size product equals a run of
    out-dims'), a sharded in-dim carries to the last out-dim its shard
    factor divides (the common batch-split ``[B,..] → [k, B/k, ..]``
    pattern shards the inner dim). Unmatched sharding is dropped
    (replicated — the conservative upper bound)."""
    out = [()] * len(out_shape)
    i = j = 0
    while i < len(in_shape) and j < len(out_shape):
        gi, gj = i + 1, j + 1
        pi, pj = in_shape[i], out_shape[j]
        while pi != pj:
            if pi < pj and gi < len(in_shape):
                pi *= in_shape[gi]
                gi += 1
            elif pj < pi and gj < len(out_shape):
                pj *= out_shape[gj]
                gj += 1
            else:
                return tuple(out)  # ragged (shouldn't happen) — bail
        ins_g = list(range(i, gi))
        outs_g = list(range(j, gj))
        if len(ins_g) == len(outs_g) and all(
                in_shape[a] == out_shape[b]
                for a, b in zip(ins_g, outs_g)):
            for a, b in zip(ins_g, outs_g):
                out[b] = in_spec[a] if a < len(in_spec) else ()
        else:
            names = tuple(a for d in ins_g
                          for a in (in_spec[d] if d < len(in_spec) else ()))
            f = _shard_factor(names, axes)
            if f > 1:
                for b in reversed(outs_g):
                    if out_shape[b] % f == 0:
                        out[b] = names
                        break
        i, j = gi, gj
    return _dedupe_spec(tuple(out))


# ---------------------------------------------------------------------------
# ShardContext: the mesh-scoped Context
# ---------------------------------------------------------------------------
class ShardContext(Context):
    """A :class:`Context` whose IR is per-shard: invars become fresh
    ``ShardVar`` atoms sized to one device's shard, specs are propagated,
    and ``ctx.collectives`` lists every classified collective. All PR 2/4
    passes run on it unchanged — ``plan_memory`` then reports per-device
    peak HBM, and ``donation_safety`` proofs run against per-shard live
    ranges."""

    def __init__(self, closed, roles, source="sharded", *, mesh_axes,
                 in_specs=None, out_specs=None, donated=(),
                 alias_groups=None, alias_refs=None, memory_budget_mb=None,
                 counters=None, budget=None):
        self.mesh_axes = {str(k): int(v)
                          for k, v in parse_mesh(mesh_axes).items()}
        self.in_specs = list(in_specs) if in_specs is not None else None
        self.out_specs = list(out_specs) if out_specs is not None else None
        self.collectives: List[CollectiveOp] = []
        super().__init__(closed, roles, source, counters=counters,
                         budget=budget, donated=donated,
                         alias_groups=alias_groups, alias_refs=alias_refs,
                         memory_budget_mb=memory_budget_mb)

    def _build_ir(self):
        if self.closed is None:
            return [], {}, []
        inliner = _ShardInliner(self.mesh_axes, self.collectives)
        ops, producers, out_atoms, invar_atoms = inliner.run(
            self.closed, self.in_specs)
        self.mesh_axes.update(inliner.axes)  # axes learned from shard_maps
        self.invar_atoms = invar_atoms
        return ops, producers, out_atoms


def shard_context(closed, roles=(), *, mesh, in_specs=None, out_specs=None,
                  donated=(), source="sharded", memory_budget_mb=None,
                  alias_groups=None, alias_refs=None) -> ShardContext:
    """Build a per-shard analysis context for an already-traced (closed)
    jaxpr. ``mesh`` is a jax Mesh, an axis dict, or a ``"dp=2,mp=2"``
    string; ``in_specs`` is one PartitionSpec (or tuple) per flat invar."""
    return ShardContext(
        closed, list(roles), source, mesh_axes=parse_mesh(mesh),
        in_specs=in_specs, out_specs=out_specs, donated=donated,
        memory_budget_mb=memory_budget_mb, alias_groups=alias_groups,
        alias_refs=alias_refs,
    )


# ---------------------------------------------------------------------------
# ShardedTrainStep front-end
# ---------------------------------------------------------------------------
def _norm_batch_specs(batch_specs):
    out = []
    for s in batch_specs or []:
        shape = getattr(s, "shape", None)
        if shape is not None:
            dt = getattr(s, "dtype", "float32")
        else:
            shape, dt = s
        shape = tuple(1 if d in (None, -1) else int(d) for d in shape)
        try:
            dt = np.dtype(dt)
        except TypeError:
            pass
        out.append(jax.ShapeDtypeStruct(shape, dt))
    return out


def sharded_step_context(step, batch_specs, *, memory_budget_mb=None,
                         source=None) -> ShardContext:
    """Trace a ``ShardedTrainStep`` (no XLA compile) and build its
    per-shard context: flat roles/in-specs in jaxpr invar order, every
    param and optimizer-state position marked donated (the step's
    ``donate_argnums=(0, 1)``), and the declared out-specs attached for
    the resharding lint."""
    import jax.numpy as jnp

    mesh = step.mesh
    if mesh is None:
        raise ValueError("sharded_step_context needs a step with a mesh")
    states = step._opt_state
    if states is None:
        states = step._init_state()
    p_sh, st_sh, b_sh, batch_sh = step._shardings(states)
    batch_sds = _norm_batch_specs(batch_specs)
    step_fn, in_sh, out_sh = step._step_parts(len(batch_sds), states)

    def _sds(v):
        v = getattr(v, "_value", v)
        return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)

    p_sds = tuple(_sds(p) for p in step._params)
    st_sds = tuple({k: _sds(v) for k, v in st.items()} for st in states)
    b_sds = tuple(_sds(b) for b in step._buffers)
    key = jax.random.PRNGKey(0)
    key_sds = jax.ShapeDtypeStruct(tuple(key.shape), key.dtype)
    lr_sds = jax.ShapeDtypeStruct((), jnp.float32)
    closed = jax.make_jaxpr(step_fn)(p_sds, st_sds, b_sds, key_sds, lr_sds,
                                     *batch_sds)

    roles: List[Tuple[str, str]] = []
    specs: List[Any] = []
    for i, p in enumerate(step._params):
        roles.append(("param", getattr(p, "name", None) or f"param{i}"))
        specs.append(p_sh[i].spec)
    n_state = 0
    for i, (st, sh) in enumerate(zip(states, st_sh)):
        for k in sorted(st):
            roles.append(("arg", f"opt_state:{i}.{k}"))
            specs.append(sh[k].spec)
            n_state += 1
    for i, b in enumerate(step._buffers):
        roles.append(("buffer", getattr(b, "name", None) or f"buffer{i}"))
        specs.append(b_sh[i].spec)
    roles.append(("arg", "rng_key"))
    specs.append(None)
    roles.append(("arg", "lr"))
    specs.append(None)
    for i, s in enumerate(batch_sds):
        roles.append(("feed", f"batch{i}"))
        specs.append(batch_sh.spec)
    if len(roles) != len(closed.jaxpr.invars):
        raise RuntimeError(
            f"sharded step trace misaligned: {len(roles)} roles vs "
            f"{len(closed.jaxpr.invars)} jaxpr invars"
        )
    donated = tuple(range(len(step._params) + n_state))
    out_specs = [getattr(s, "spec", None)
                 for s in jax.tree_util.tree_leaves(out_sh)]
    mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return ShardContext(
        closed, roles, source or "sharded-step", mesh_axes=mesh_axes,
        in_specs=specs, out_specs=out_specs, donated=donated,
        memory_budget_mb=memory_budget_mb,
    )


def pipelined_step_context(step, batch_specs, *, memory_budget_mb=None,
                           source=None) -> ShardContext:
    """Per-shard context for a ``PipelinedTrainStep`` (the shard_map-manual
    GPipe schedule): stacked block params pp-sharded on dim 0, the
    ppermute/psum collectives of the schedule classified from the body's
    per-shard avals, every param/state position donated
    (``donate_argnums=(0, 1, 2, 3)``).

    Under jax<0.5 the full step cannot be traced — an upstream shard_map
    autodiff bug drops the rank of scalar residuals under partial-eval
    (see ``_jax_compat`` / the ``needs_shardmap_grad`` skips) — so the
    context falls back to the forward GPipe loss program: the identical
    shard_map schedule with the identical ppermute/psum collectives, minus
    the optimizer tail (and hence with nothing donated)."""
    import jax.numpy as jnp

    mesh = step.mesh
    saved = (step._stacked, step._stacked_state, step._repl_state)
    if step._stacked is None:
        step._stacked = step._init_stacked()
    if step._stacked_state is None:
        step._stacked_state = step._init_stacked_state()
    if step._repl_state is None:
        step._repl_state = step._init_repl_state()
    try:
        step_fn, in_sh, out_sh = step._step_parts()

        def _sds(v):
            v = getattr(v, "_value", v)
            return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)

        repl_sds = tuple(_sds(p) for p in step._repl_params)
        stacked_sds = tuple(_sds(v) for v in step._stacked)
        rs_sds = tuple({k: _sds(v) for k, v in st.items()}
                       for st in step._repl_state)
        ss_sds = tuple({k: _sds(v) for k, v in st.items()}
                       for st in step._stacked_state)
        b_sds = tuple(_sds(b) for b in step._buffers)
        key = jax.random.PRNGKey(0)
        key_sds = jax.ShapeDtypeStruct(tuple(key.shape), key.dtype)
        lr_sds = jax.ShapeDtypeStruct((), jnp.float32)
        batch_sds = _norm_batch_specs(batch_specs)
        full_step = True
        try:
            closed = jax.make_jaxpr(step_fn)(
                repl_sds, stacked_sds, rs_sds, ss_sds, b_sds, key_sds,
                lr_sds, *batch_sds)
        except Exception:
            # jax<0.5 shard_map autodiff bug — trace the forward loss
            # program instead (same collectives, no optimizer tail)
            full_step = False
            closed = jax.make_jaxpr(step._loss_program)(
                repl_sds, stacked_sds, b_sds, key_sds, *batch_sds)
    finally:
        step._stacked, step._stacked_state, step._repl_state = saved

    roles: List[Tuple[str, str]] = []
    for i, p in enumerate(step._repl_params):
        roles.append(("param", getattr(p, "name", None) or f"param{i}"))
    for j in range(len(stacked_sds)):
        roles.append(("param", f"stacked{j}"))
    if full_step:
        for i, st in enumerate(rs_sds):
            for k in sorted(st):
                roles.append(("arg", f"repl_state:{i}.{k}"))
        for j, st in enumerate(ss_sds):
            for k in sorted(st):
                roles.append(("arg", f"stacked_state:{j}.{k}"))
    for i, b in enumerate(step._buffers):
        roles.append(("buffer", getattr(b, "name", None) or f"buffer{i}"))
    roles.append(("arg", "rng_key"))
    if full_step:
        roles.append(("arg", "lr"))
    for i in range(len(batch_sds)):
        roles.append(("feed", f"batch{i}"))
    repl_sh, stacked_sh, rs_sh, ss_sh, buf_sh, key_sh, lr_sh, *batch_sh = \
        in_sh
    if full_step:
        n_donated = len(jax.tree_util.tree_leaves(
            (repl_sds, stacked_sds, rs_sds, ss_sds)))
        flat_in_sh = jax.tree_util.tree_leaves(in_sh)
        flat_out_sh = jax.tree_util.tree_leaves(out_sh)
    else:
        n_donated = 0  # forward-only program: nothing to donate
        flat_in_sh = jax.tree_util.tree_leaves(
            (repl_sh, stacked_sh, buf_sh, key_sh, tuple(batch_sh)))
        flat_out_sh = [jax.tree_util.tree_leaves(out_sh)[0]]  # scalar loss
    specs = [getattr(s, "spec", None) for s in flat_in_sh]
    out_specs = [getattr(s, "spec", None) for s in flat_out_sh]
    if len(roles) != len(closed.jaxpr.invars) or \
            len(specs) != len(closed.jaxpr.invars):
        raise RuntimeError(
            f"pipelined step trace misaligned: {len(roles)} roles / "
            f"{len(specs)} specs vs {len(closed.jaxpr.invars)} jaxpr invars"
        )
    mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return ShardContext(
        closed, roles, source or "pipelined-step", mesh_axes=mesh_axes,
        in_specs=specs, out_specs=out_specs,
        donated=tuple(range(n_donated)),
        memory_budget_mb=memory_budget_mb,
    )


def captured_step_context(*, memory_budget_mb=None,
                          source=None) -> ShardContext:
    """Per-shard analysis context for the thread's last replayed SHARDED
    captured whole-step program (``core.lazy`` whole-step capture on a
    mesh). Rebuilds the closed jaxpr and per-invar PartitionSpecs from the
    capture registry — trace-only, no XLA compile. Raises RuntimeError
    when no sharded capture has replayed on this thread yet."""
    from ..core import lazy as _lazy

    prog = _lazy.captured_step_program()
    info = _lazy.captured_step_shard_info()
    if prog is None or info is None:
        raise RuntimeError(
            "no sharded captured step has replayed on this thread; run a "
            "captured training step on a mesh first (FLAGS_eager_step_capture "
            "with NamedSharding params)")
    closed, donated, roles = prog
    mesh, in_specs, axes = info
    return ShardContext(
        closed, list(roles), source or "captured-sharded", mesh_axes=axes,
        in_specs=in_specs, donated=donated,
        memory_budget_mb=memory_budget_mb,
    )


def check_sharded_step(step, batch_specs, *, passes=None,
                       memory_budget_mb=None, source=None
                       ) -> List[Diagnostic]:
    """Run the full analysis suite over a sharded/pipelined train step's
    traced program at per-shard shapes — the multi-chip twin of
    ``analysis.check``. Trace-only: no XLA compile, runs in milliseconds,
    safe as a build-time gate under ``FLAGS_check_programs``. Accepts a
    ``ShardedTrainStep``, a ``PipelinedTrainStep``, or a
    ``lazy.captured_step_handle()`` (batch_specs ignored for the latter —
    the captured program embeds its own batch shapes)."""
    from . import run_passes

    if getattr(step, "_captured_step", False):  # lazy captured-step handle
        ctx = captured_step_context(memory_budget_mb=memory_budget_mb,
                                    source=source)
        return run_passes(ctx, passes)
    if hasattr(step, "_stacked"):  # PipelinedTrainStep (pp schedule)
        ctx = pipelined_step_context(step, batch_specs,
                                     memory_budget_mb=memory_budget_mb,
                                     source=source)
    else:
        ctx = sharded_step_context(step, batch_specs,
                                   memory_budget_mb=memory_budget_mb,
                                   source=source)
    return run_passes(ctx, passes)


# ---------------------------------------------------------------------------
# Collective extraction for plain (non-mesh) contexts + attribution
# ---------------------------------------------------------------------------
def _axis_sizes_from_ops(ops) -> Dict[str, int]:
    axes: Dict[str, int] = {}
    for op in ops:
        if op.name == "shard_map":
            shape = getattr(op.params.get("mesh"), "shape", None)
            if shape is not None and hasattr(shape, "items"):
                for k, v in shape.items():
                    axes.setdefault(str(k), int(v))
    return axes


def collective_records(ctx) -> List[CollectiveOp]:
    """Classified collectives of a context. ShardContext carries them from
    the per-shard inline; for a plain Context the explicit collectives
    inside ``shard_map`` scopes are classified here (their avals are
    already per-shard), with axis sizes read off the shard_map mesh
    params."""
    recs = getattr(ctx, "collectives", None)
    if recs is not None:
        return list(recs)
    ops = getattr(ctx, "ops", None) or []
    axes = _axis_sizes_from_ops(ops)
    out: List[CollectiveOp] = []
    for op in ops:
        if op.name not in _COLLECTIVE_PRIMS:
            continue
        names = _coll_axes(op.params)
        n = _shard_factor(names, axes)
        payload = sum(_aval_nbytes(getattr(a, "aval", None))
                      for a in op.invars
                      if not isinstance(a, jax.core.Literal))
        kind = _COLL_KIND[op.name]
        first = getattr(op.invars[0], "aval", None) if op.invars else None
        out.append(CollectiveOp(
            kind=kind, path=op.path, axes=names, group_size=n,
            payload_bytes=payload,
            wire_bytes=ring_wire_bytes(kind, payload, n),
            shape=tuple(getattr(first, "shape", ())),
            dtype=str(getattr(first, "dtype", "")),
        ))
    return out


def collective_stats(closed) -> Dict[str, int]:
    """``{"comm_bytes", "collective_count"}`` for one closed jaxpr — the
    attribution hook (``profiler.attribution`` static profiles). Explicit
    collectives only (no spec info at this call site); zero-collective
    programs return zeros so single-chip profiles are unchanged."""
    from . import _inline_ops

    ops, _producers, _outs = _inline_ops(closed)
    recs = collective_records(type("C", (), {
        "collectives": None, "ops": ops})())
    return {
        "comm_bytes": int(sum(r.total_wire_bytes for r in recs)),
        "collective_count": int(sum(r.count for r in recs)),
    }


def _flops_of_ops(ops) -> int:
    from ..profiler.attribution import _op_flops

    return int(sum(_op_flops(op) for op in ops))


# ---------------------------------------------------------------------------
# Pass: collective_cost
# ---------------------------------------------------------------------------
def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / float(1 << 20):.1f}MB"
    if n >= 1 << 10:
        return f"{n / 1024.0:.1f}KB"
    return f"{n}B"


@register_pass("collective_cost")
def collective_cost(ctx: Context) -> List[Diagnostic]:
    recs = collective_records(ctx)
    if not recs and getattr(ctx, "mesh_axes", None) is None:
        return []  # single-device program — stay silent
    comm_bytes = sum(r.total_wire_bytes for r in recs)
    count = sum(r.count for r in recs)
    flops = _flops_of_ops(ctx.ops)
    ratio = comm_bytes / float(flops) if flops else 0.0
    by_kind: Dict[str, List[int]] = {}
    for r in recs:
        row = by_kind.setdefault(r.kind, [0, 0])
        row[0] += r.total_wire_bytes
        row[1] += r.count
    kinds = ", ".join(
        f"{k} ×{n} {_fmt_bytes(b)}"
        for k, (b, n) in sorted(by_kind.items(), key=lambda kv: -kv[1][0])
    ) or "none"
    diags = [Diagnostic(
        Severity.INFO, "collective_cost", "program",
        f"{count} collective(s), {_fmt_bytes(comm_bytes)} on wire per "
        f"device per step (ring-ICI); comm/compute "
        f"{ratio:.2e} bytes/flop; by kind: {kinds}",
        data={
            "comm_bytes": int(comm_bytes),
            "collective_count": int(count),
            "flops_est": int(flops),
            "comm_compute_ratio": float(ratio),
            "collectives": [r.to_dict() for r in recs],
        },
    )]
    warn_at = float(_flags.flag("comm_ratio_warn"))
    if warn_at > 0 and ratio > warn_at:
        heavy = max(recs, key=lambda r: r.total_wire_bytes)
        diags.append(Diagnostic(
            Severity.WARNING, "collective_cost", heavy.path,
            f"comm/compute ratio {ratio:.2e} bytes/flop exceeds "
            f"FLAGS_comm_ratio_warn={warn_at:g}: this program is "
            "interconnect-bound under the ring-ICI model "
            f"(heaviest: {heavy.kind} over {list(heavy.axes)}, "
            f"{_fmt_bytes(heavy.total_wire_bytes)})",
            hint="re-balance the mesh (more model-parallel, less data-"
                 "parallel traffic), raise the per-device batch, or check "
                 "resharding_lint for removable round trips",
        ))
    return diags


# ---------------------------------------------------------------------------
# Pass: resharding_lint
# ---------------------------------------------------------------------------
def _scan_hoist_findings(open_jaxpr, path, acc):
    """Loop-invariant collectives: a collective inside a scan body whose
    transitive inputs are all scan CONSTS (or literals) recomputes the same
    cross-device traffic every iteration — hoist it above the loop."""
    for i, eqn in enumerate(open_jaxpr.eqns):
        name = eqn.primitive.name
        here = f"{path}eqn[{i}]"
        if name == "scan":
            body, _ = _as_open(eqn.params["jaxpr"])
            nc = int(eqn.params.get("num_consts", 0))
            pure = set(body.invars[:nc])
            for bi, be in enumerate(body.eqns):
                ins = [v for v in be.invars if isinstance(v, jax.core.Var)]
                if ins and all(v in pure for v in ins):
                    if be.primitive.name in _COLLECTIVE_PRIMS:
                        acc.append((
                            f"{here}/scan/eqn[{bi}] {be.primitive.name}",
                            be,
                            int(eqn.params.get("length", 0)),
                        ))
                    pure.update(be.outvars)
            _scan_hoist_findings(body, f"{here}/scan/", acc)
        else:
            _k, subs = _sub_jaxprs(eqn)
            for si, sub in enumerate(subs):
                sub_open, _c = _as_open(sub)
                tag = name + (str(si) if len(subs) > 1 else "")
                _scan_hoist_findings(sub_open, f"{here}/{tag}/", acc)


@register_pass("resharding_lint")
def resharding_lint(ctx: Context) -> List[Diagnostic]:
    mesh_scoped = getattr(ctx, "mesh_axes", None) is not None
    has_region = any(op.name == "shard_map" for op in ctx.ops) or any(
        op.name in _COLLECTIVE_PRIMS for op in ctx.ops)
    if not mesh_scoped and not has_region:
        return []  # single-device program — stay silent
    diags: List[Diagnostic] = []
    prod = ctx.producers

    if mesh_scoped:
        # psum∘psum / gather-then-slice are redundant_ops findings on plain
        # contexts; the mesh-scoped suite reports them here instead (the
        # redundant_ops pass defers when ctx.mesh_axes is set) so the full
        # suite never double-reports one defect
        for op in ctx.ops:
            if op.name in ("psum", "psum2"):
                p = prod.get(op.invars[0]) if op.invars else None
                if p is not None and p.name in ("psum", "psum2") and \
                        set(_coll_axes(op.params)) == \
                        set(_coll_axes(p.params)):
                    diags.append(Diagnostic(
                        Severity.WARNING, "resharding_lint", op.path,
                        "psum∘psum over the same axis "
                        f"{sorted(_coll_axes(op.params))}: the second "
                        "all-reduce multiplies by the group size and "
                        "doubles the wire traffic",
                        hint="reduce once (or use the two-axis form "
                             "psum(x, ('a','b')) for a single fused "
                             "all-reduce)",
                        shapes=(tuple(getattr(getattr(
                            op.invars[0], "aval", None), "shape", ())),),
                    ))
            elif op.name in ("slice", "dynamic_slice", "squeeze"):
                p = prod.get(op.invars[0]) if op.invars else None
                if p is not None and p.name == "all_gather" and \
                        tuple(getattr(getattr(op.outvars[0], "aval", None),
                                      "shape", ())) == \
                        tuple(getattr(getattr(p.invars[0], "aval", None),
                                      "shape", ())):
                    diags.append(Diagnostic(
                        Severity.WARNING, "resharding_lint", op.path,
                        "all_gather immediately sliced back to the local "
                        "shard: a full-axis round trip that ends where it "
                        "started",
                        hint="drop the gather (the shard is already local) "
                             "or keep the gathered value if other shards "
                             "are actually read",
                        shapes=(tuple(getattr(getattr(
                            p.invars[0], "aval", None), "shape", ())),),
                    ))

    # replicated output where the declared out-spec says sharded — only
    # when the propagated spec is EXPLICIT (constraint/collective-pinned);
    # propagation fallbacks must not false-positive
    out_specs = getattr(ctx, "out_specs", None)
    if mesh_scoped and out_specs:
        for pos, (atom, decl) in enumerate(zip(ctx.out_atoms, out_specs)):
            if not isinstance(atom, ShardVar) or not atom.explicit:
                continue
            ndim = len(tuple(getattr(atom.aval, "shape", ())))
            want = _norm_spec(decl, ndim)
            have = _norm_spec(atom.spec, ndim)
            missing = [d for d in range(ndim) if want[d] and not have[d]]
            if missing and not any(have):
                diags.append(Diagnostic(
                    Severity.WARNING, "resharding_lint", f"output[{pos}]",
                    f"output {pos} is replicated inside the program but its "
                    f"declared out-spec shards dim(s) {missing}: XLA will "
                    "slice at the boundary and every device computed the "
                    "full value first",
                    hint="keep the value sharded through the program (check "
                         "lost sharding constraints) or declare the output "
                         "replicated",
                    shapes=(tuple(getattr(atom.aval, "shape", ())),),
                ))

    # loop-invariant collectives inside scan bodies
    if ctx.jaxpr is not None:
        acc: List = []
        _scan_hoist_findings(ctx.jaxpr, "", acc)
        for path, eqn, length in acc:
            diags.append(Diagnostic(
                Severity.WARNING, "resharding_lint", path,
                f"loop-invariant {eqn.primitive.name} inside a scan body: "
                "its inputs are scan constants, so the same collective "
                f"runs every iteration"
                + (f" (×{length})" if length else ""),
                hint="hoist the collective above the lax.scan / fori loop",
                shapes=(tuple(getattr(eqn.invars[0].aval, "shape", ()))
                        if eqn.invars else (),),
            ))
    return diags


# ---------------------------------------------------------------------------
# Pass: collective_schedule — SPMD divergence
# ---------------------------------------------------------------------------
# In SPMD every rank runs the SAME program, so every rank must reach every
# collective in the SAME order: a collective reachable only under control
# flow predicated on a rank-varying value (the device coordinate) is the
# classic SPMD deadlock — some ranks enter the collective, their peers
# never arrive, and the step hangs instead of erroring.

def schedule_of(ops) -> List[Dict[str, Any]]:
    """Ordered collective schedule of a flat-op list: one record per
    collective, in program order, ``{kind, op, path, axes, group_size,
    payload_bytes, scope}``. This is the artifact two programs must agree
    on to be SPMD-interchangeable; ``graph_lint --diff`` and
    ``equivalence.program_diff`` print schedule deltas from it."""
    axes = _axis_sizes_from_ops(ops)
    out: List[Dict[str, Any]] = []
    for op in ops:
        if op.name not in _COLLECTIVE_PRIMS:
            continue
        names = _coll_axes(op.params)
        payload = sum(_aval_nbytes(getattr(a, "aval", None))
                      for a in op.invars
                      if not isinstance(a, jax.core.Literal))
        out.append({
            "kind": _COLL_KIND[op.name],
            "op": op.name,
            "path": op.path,
            "axes": tuple(names),
            "group_size": _shard_factor(names, axes),
            "payload_bytes": int(payload),
            "scope": op.scope,
        })
    return out


def _jaxpr_has_collective(j, depth=6) -> bool:
    """True when the (closed or open) jaxpr contains a collective anywhere,
    including nested control-flow/call bodies."""
    if depth <= 0:
        return False
    open_j, _consts = _as_open(j)
    for eqn in open_j.eqns:
        if eqn.primitive.name in _COLLECTIVE_PRIMS:
            return True
        for v in eqn.params.values():
            subs = v if isinstance(v, (tuple, list)) else (v,)
            for s in subs:
                if hasattr(s, "eqns") or hasattr(s, "jaxpr"):
                    if _jaxpr_has_collective(s, depth - 1):
                        return True
    return False


def _rank_varying(atom, producers, depth=64) -> bool:
    """True when ``atom`` derives from the device coordinate
    (``axis_index``): a branch predicated on it takes different arms on
    different ranks."""
    stack = [atom]
    steps = 0
    while stack and steps < depth:
        a = stack.pop()
        steps += 1
        if isinstance(a, jax.core.Literal):
            continue
        try:
            op = producers.get(a)
        except TypeError:
            continue
        if op is None:
            continue
        if op.name == "axis_index":
            return True
        stack.extend(op.invars)
    return False


@register_pass("collective_schedule")
def collective_schedule(ctx) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    prod = ctx.producers
    for op in ctx.ops:
        if op.name in ("cond", "switch"):
            branches = op.params.get("branches") or ()
            if not any(_jaxpr_has_collective(b) for b in branches):
                continue
            pred = op.invars[0] if op.invars else None
            if pred is not None and _rank_varying(pred, prod):
                diags.append(Diagnostic(
                    Severity.ERROR, "collective_schedule", op.path,
                    f"collective inside a {op.name} branch whose predicate "
                    "derives from axis_index: ranks taking different arms "
                    "reach different collective schedules — the classic "
                    "SPMD deadlock (some ranks enter the collective, peers "
                    "never arrive)",
                    hint="hoist the collective out of the branch, or make "
                         "the predicate rank-invariant (e.g. reduce it with "
                         "psum/pmax first)",
                ))
        elif op.name == "while":
            bodies = [op.params.get("cond_jaxpr"),
                      op.params.get("body_jaxpr")]
            if not any(b is not None and _jaxpr_has_collective(b)
                       for b in bodies):
                continue
            if any(_rank_varying(a, prod) for a in op.invars
                   if not isinstance(a, jax.core.Literal)):
                diags.append(Diagnostic(
                    Severity.ERROR, "collective_schedule", op.path,
                    "collective inside a while loop whose carry derives "
                    "from axis_index: ranks can run different trip counts, "
                    "so they disagree on how many collectives execute — "
                    "SPMD deadlock",
                    hint="make the trip count rank-invariant (pmax the "
                         "continue predicate) or move the collective out "
                         "of the loop",
                ))
    return diags
