"""paddle_tpu.analysis.equivalence — structural equivalence prover.

Every execution tier the framework grows — per-op → lazy(3-program) →
captured(1-program) → sharded-captured, telemetry on/off, donated vs plain,
planned vs unplanned — carries a *bitwise parity* contract. This module
turns that contract from a test-suite hope into a compile-time artifact: a
**structural proof** that two traced programs compute the same function,
checked before the first donated replay ever runs (the CUDA-Graphs
capture/replay discipline: a replayed program must provably be the path it
replaces).

The proof is canonical value numbering over the inlined flat-op IR
(``analysis._inline_ops``): every atom gets a content key derived from its
producer's primitive name, canonicalized params, and input keys —
alpha-renaming is free (keys never mention variable names), and a declared
allowlist of *bitwise-safe* rewrites is folded into the keys:

  - **commutative operand ordering** — ``add``/``mul``/``max``/… operand
    keys are sorted (IEEE float addition is commutative; only association
    changes results, and association is visible as tree shape);
  - **identity elision** — ``stop_gradient`` / ``copy`` are value-level
    no-ops (the capture inserts ``stop_gradient`` at non-differentiable
    positions; the 3-program flush does not);
  - **literal folding** — compile-time scalar chains fold to their value
    (``scalar_const``), so a literal ``2.0`` matches a ``1.0 + 1.0`` const
    chain and a broadcast-of-scalar;
  - **remat / recompute deduplication** — duplicated subcomputations (a
    ``jax.checkpoint`` replay under ``prevent_cse``, or the 3-program
    composition recomputing the forward inside its backward) hash to the
    SAME keys as the originals, so a planned program proves equal to its
    unplanned twin;
  - **declared extra outputs** — the rescue sentinel and the telemetry
    triple are extra *outputs* of the same program; callers declare how
    many trailing outputs each side may carry beyond the common contract.

Two programs are **certified equivalent** when their (declared-common)
output key sequences match. When they do not, a synchronized backward walk
from the first mismatched output pair produces a structured
*first-divergence* diagnostic: the two op paths, shapes and dtypes where
the programs first disagree.

Consumers:

  - ``core.lazy`` (FLAGS_check_programs=2): certifies the captured
    1-program step against the 3-program composition — and the sharded
    capture against its non-donated probe trace — before the first donated
    replay; an unprovable certificate falls back through the counted
    ``_CaptureIneligible`` ladder.
  - ``jit.CompiledTrainStep``: certifies the remat-planned step against
    its unplanned twin when a memory plan is applied.
  - ``core.lazy._ServeProgram``: certifies the donated and plain serve
    rungs trace the same program.
  - ``tools/graph_lint.py --diff A B``: schedule/structure diff between
    any two lintable targets.
"""
from __future__ import annotations

import dataclasses
import hashlib
import re
from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from . import (
    CanonVar,
    ConstAtom,
    Context,
    Diagnostic,
    Severity,
    _as_open,
    _inline_ops,
    atom_dtype,
    atom_shape,
    register_pass,
    scalar_const,
)

__all__ = [
    "CanonicalProgram",
    "EquivalenceCertificate",
    "canonicalize",
    "prove_equivalent",
    "certify_callables",
    "program_diff",
]


# bitwise-commutative binary primitives (operand ORDER never changes the
# result; association — which is tree shape, not operand order — does and
# is NOT rewritten)
_COMMUTATIVE = {"add", "add_any", "mul", "max", "min", "and", "or", "xor",
                "eq", "ne"}

# value-level identity ops: elided from producer chains
_IDENTITY = {"stop_gradient", "copy"}

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def _val_digest(val) -> str:
    """Content digest of a closed-over constant (shape, dtype, bytes)."""
    try:
        arr = np.asarray(val)
        h = hashlib.sha1(arr.tobytes()).hexdigest()[:16]
        return f"const:{arr.shape}:{arr.dtype}:{h}"
    except Exception:
        return f"const:{_ADDR_RE.sub('0x', repr(val))}"


def _scalar_key(atom, producers) -> Optional[str]:
    """Literal-folding: the canonical key of a compile-time scalar, chasing
    converts/broadcasts and folding constant arithmetic — None when `atom`
    is not a scalar constant."""
    if atom_shape(atom) != ():
        return None
    v = scalar_const(atom, producers)
    if v is None:
        return None
    return f"sc:{atom_dtype(atom)}:{v!r}"


class CanonicalProgram:
    """One side of an equivalence proof: the flat-op IR plus the canonical
    value-number key of every reachable atom."""

    __slots__ = ("closed", "ops", "producers", "out_atoms", "out_keys",
                 "rewrites", "_memo", "_jmemo")

    def __init__(self, closed, _jmemo=None):
        self.closed = closed
        self.ops, self.producers, self.out_atoms = _inline_ops(closed)
        self.rewrites: Counter = Counter()
        self._memo: Dict[int, str] = {}
        self._jmemo: Dict[int, Tuple[Any, str]] = (
            {} if _jmemo is None else _jmemo)
        open_jaxpr, _ = _as_open(closed)
        for i, v in enumerate(open_jaxpr.invars):
            self._memo[id(v)] = f"in:{i}"
        # ops arrive topologically ordered (scoped bodies before their scope
        # op); computing keys in list order keeps this iterative — no
        # recursion depth limit on deep GPT chains
        for op in self.ops:
            if op.scope:
                # scoped bodies (scan/while/cond/shard_map) never appear in
                # top-level producer chains; their content reaches the proof
                # through the scope op's param digest
                continue
            self._op_keys(op)
        self.out_keys = [self.key_of(a) for a in self.out_atoms]

    # -- atom keys ---------------------------------------------------------
    def key_of(self, atom) -> str:
        k = self._memo.get(id(atom))
        if k is not None:
            return k
        sk = _scalar_key(atom, self.producers)
        if sk is not None:
            self.rewrites["literal_folds"] += 1
            self._memo[id(atom)] = sk
            return sk
        if isinstance(atom, jax.core.Literal):
            k = f"lit:{atom_dtype(atom)}:{_val_digest(atom.val)}"
        elif isinstance(atom, ConstAtom):
            k = _val_digest(atom.val)
        else:
            op = self.producers.get(atom)
            if op is None:
                # an unproduced free var (scoped-body invar leaking — should
                # not happen at top level); key by aval only
                k = f"free:{atom_shape(atom)}:{atom_dtype(atom)}"
            else:
                self._op_keys(op)
                k = self._memo[id(atom)]
        self._memo[id(atom)] = k
        return k

    def _op_keys(self, op) -> None:
        """Assign canonical keys to every outvar of `op` (memoized)."""
        if op.outvars and id(op.outvars[0]) in self._memo:
            return
        if op.name in _IDENTITY and len(op.invars) == 1 \
                and len(op.outvars) == 1:
            self.rewrites["identity_elisions"] += 1
            self._memo[id(op.outvars[0])] = self.key_of(op.invars[0])
            return
        ins = [self.key_of(a) for a in op.invars]
        if op.name in _COMMUTATIVE and len(ins) == 2:
            ins = sorted(ins)
        pdig = _params_digest(op.params, self._jmemo)
        base = hashlib.sha1(
            f"{op.name}|{pdig}|{','.join(ins)}".encode()
        ).hexdigest()[:20]
        for k, ov in enumerate(op.outvars):
            sk = _scalar_key(ov, self.producers)
            if sk is not None:
                self.rewrites["literal_folds"] += 1
                self._memo[id(ov)] = sk
            else:
                self._memo[id(ov)] = f"{op.name}:{base}:{k}"

    # -- divergence helpers ------------------------------------------------
    def producer(self, atom):
        """producers.get with unhashable-atom (Literal) guard."""
        if isinstance(atom, (jax.core.Literal, ConstAtom)):
            return None
        try:
            return self.producers.get(atom)
        except TypeError:
            return None

    def chase(self, atom):
        """Skip identity producers (stop_gradient/copy chains)."""
        seen = 0
        while seen < 64:
            op = self.producer(atom)
            if op is None or op.name not in _IDENTITY \
                    or len(op.invars) != 1:
                return atom
            atom = op.invars[0]
            seen += 1
        return atom

    def describe(self, atom) -> str:
        k = self._memo.get(id(atom), "")
        if k.startswith("in:"):
            return f"invar[{k[3:]}]"
        if isinstance(atom, jax.core.Literal):
            return f"literal {atom.val!r}"
        if isinstance(atom, ConstAtom):
            return f"const{list(atom_shape(atom))}:{atom_dtype(atom)}"
        op = self.producer(atom)
        if op is None:
            return "free var"
        return op.path


def _params_digest(params, jmemo) -> str:
    """Canonical digest of an eqn's params: jaxpr-valued params recurse into
    a full canonical sub-digest (so scope ops — scan/while/cond/shard_map —
    prove body equivalence structurally); trace-time thunks and callables
    are skipped (their identity is not semantic across traces); everything
    else is repr'd with memory addresses scrubbed."""
    items = []
    for k in sorted(params):
        v = params[k]
        d = _value_digest(v, jmemo)
        if d is not None:
            items.append(f"{k}={d}")
    return ";".join(items)


def _value_digest(v, jmemo) -> Optional[str]:
    if callable(v) and not hasattr(v, "jaxpr") \
            and not isinstance(v, (type,)):
        return None  # trace-time thunk / closure — not semantic
    if hasattr(v, "jaxpr") or type(v).__name__ == "Jaxpr":
        return _jaxpr_digest(v, jmemo)
    if isinstance(v, (tuple, list)):
        parts = [_value_digest(x, jmemo) for x in v]
        return "(" + ",".join(p for p in parts if p is not None) + ")"
    if isinstance(v, np.ndarray) or isinstance(v, jax.Array):
        return _val_digest(v)
    return _ADDR_RE.sub("0x", repr(v))


def _jaxpr_digest(j, jmemo) -> str:
    """Canonical digest of a sub-jaxpr: the value-number keys of its outputs
    under its own positional invars (alpha-rename-free, same allowlist)."""
    cached = jmemo.get(id(j))
    if cached is not None:
        return cached[1]
    try:
        sub = CanonicalProgram(j, _jmemo=jmemo)
        dig = "jaxpr:" + hashlib.sha1(
            "|".join(sub.out_keys).encode()).hexdigest()[:20]
    except Exception:
        open_j, _ = _as_open(j)
        dig = f"jaxpr:opaque:{len(open_j.eqns)}eqns"
    jmemo[id(j)] = (j, dig)
    return dig


@dataclasses.dataclass
class EquivalenceCertificate:
    """Outcome of one structural equivalence proof."""

    equivalent: bool
    reason: str
    label_a: str = "A"
    label_b: str = "B"
    n_ops: Tuple[int, int] = (0, 0)
    outputs_compared: int = 0
    rewrites: Dict[str, int] = dataclasses.field(default_factory=dict)
    divergence: Optional[Diagnostic] = None

    def summary(self) -> str:
        state = "EQUIVALENT" if self.equivalent else "DIVERGENT"
        rw = ", ".join(f"{k}={v}" for k, v in sorted(self.rewrites.items()))
        return (f"equivalence[{self.label_a} ≡ {self.label_b}]: {state} — "
                f"{self.reason} ({self.n_ops[0]}/{self.n_ops[1]} ops, "
                f"{self.outputs_compared} outputs"
                + (f"; rewrites: {rw}" if rw else "") + ")")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "equivalent": self.equivalent,
            "reason": self.reason,
            "labels": [self.label_a, self.label_b],
            "n_ops": list(self.n_ops),
            "outputs_compared": self.outputs_compared,
            "rewrites": dict(self.rewrites),
            "divergence": (None if self.divergence is None
                           else str(self.divergence)),
        }


def canonicalize(closed) -> CanonicalProgram:
    """Canonical value numbering of a (closed) jaxpr — one side of a proof."""
    return CanonicalProgram(closed)


def _first_divergence(A: CanonicalProgram, B: CanonicalProgram,
                      out_idx: int, source: str) -> Diagnostic:
    """Synchronized backward walk from the first mismatched output pair to
    the first structurally diverging op (path, shapes, dtypes)."""
    stack = [(A.out_atoms[out_idx], B.out_atoms[out_idx])]
    seen = set()
    guard = 0
    while stack and guard < 20000:
        guard += 1
        a, b = stack.pop()
        a, b = A.chase(a), B.chase(b)
        if (id(a), id(b)) in seen:
            continue
        seen.add((id(a), id(b)))
        if A.key_of(a) == B.key_of(b):
            continue
        opa, opb = A.producer(a), B.producer(b)
        shapes = (atom_shape(a), atom_shape(b))
        dtypes = (str(atom_dtype(a)), str(atom_dtype(b)))
        if opa is None or opb is None:
            return Diagnostic(
                Severity.ERROR, "equivalence",
                f"{A.describe(a)} vs {B.describe(b)}",
                f"programs diverge at output {out_idx}: "
                f"{A.describe(a)} ≠ {B.describe(b)}",
                hint="the two tiers do not compute the same value here",
                shapes=shapes, dtypes=dtypes, source=source,
                data={"output_index": out_idx,
                      "a": A.describe(a), "b": B.describe(b)},
            )
        if opa.name != opb.name or _params_digest(opa.params, A._jmemo) \
                != _params_digest(opb.params, B._jmemo):
            why = ("op kinds differ" if opa.name != opb.name
                   else "op params differ")
            return Diagnostic(
                Severity.ERROR, "equivalence",
                f"{opa.path} vs {opb.path}",
                f"first divergence (output {out_idx}): {why} — "
                f"{opa.name} vs {opb.name}",
                hint="inspect the two op paths; this is the first point "
                     "where the programs stop being isomorphic",
                shapes=shapes, dtypes=dtypes, source=source,
                data={"output_index": out_idx, "a_path": opa.path,
                      "b_path": opb.path, "a_op": opa.name,
                      "b_op": opb.name},
            )
        # same op, same params: descend into the first differing input pair
        # (aligned by sorted key for commutative ops, positionally otherwise)
        ia = [(A.key_of(x), x) for x in opa.invars]
        ib = [(B.key_of(x), x) for x in opb.invars]
        if opa.name in _COMMUTATIVE and len(ia) == 2:
            ia.sort(key=lambda p: p[0])
            ib.sort(key=lambda p: p[0])
        if len(ia) != len(ib):
            return Diagnostic(
                Severity.ERROR, "equivalence",
                f"{opa.path} vs {opb.path}",
                f"first divergence (output {out_idx}): same op "
                f"{opa.name} applied with {len(ia)} vs {len(ib)} inputs",
                shapes=shapes, dtypes=dtypes, source=source,
                data={"output_index": out_idx, "a_path": opa.path,
                      "b_path": opb.path},
            )
        for (ka, xa), (kb, xb) in zip(ia, ib):
            if ka != kb:
                stack.append((xa, xb))
                break
        else:
            # inputs all match but output keys differ: output-index skew
            return Diagnostic(
                Severity.ERROR, "equivalence",
                f"{opa.path} vs {opb.path}",
                f"first divergence (output {out_idx}): same op, same "
                f"inputs, different output position",
                shapes=shapes, dtypes=dtypes, source=source,
                data={"output_index": out_idx, "a_path": opa.path,
                      "b_path": opb.path},
            )
    return Diagnostic(
        Severity.ERROR, "equivalence", f"output[{out_idx}]",
        f"programs diverge at output {out_idx} (divergence deeper than the "
        f"walk budget)",
        source=source, data={"output_index": out_idx},
    )


def prove_equivalent(a, b, *, extra_outputs_a: int = 0,
                     extra_outputs_b: int = 0, label_a: str = "A",
                     label_b: str = "B",
                     source: str = "equivalence") -> EquivalenceCertificate:
    """Certify two (closed) jaxprs structurally equivalent.

    ``extra_outputs_a``/``extra_outputs_b`` declare how many TRAILING
    outputs each side carries beyond the common contract (the telemetry
    triple, the rescue sentinel) — they are excluded from the proof.
    Returns an :class:`EquivalenceCertificate`; ``certificate.divergence``
    carries the structured first-divergence diagnostic when the proof
    fails. Raises on untraceable inputs (callers treat that as an
    *unprovable* certificate, distinct from a *divergent* one)."""
    A = a if isinstance(a, CanonicalProgram) else canonicalize(a)
    B = b if isinstance(b, CanonicalProgram) else canonicalize(b)
    n_ops = (len(A.ops), len(B.ops))
    rewrites = dict(Counter(A.rewrites) + Counter(B.rewrites))
    ka = A.out_keys[:len(A.out_keys) - int(extra_outputs_a)]
    kb = B.out_keys[:len(B.out_keys) - int(extra_outputs_b)]
    if len(ka) != len(kb):
        d = Diagnostic(
            Severity.ERROR, "equivalence", "outputs",
            f"output arity mismatch: {label_a} has {len(ka)} outputs, "
            f"{label_b} has {len(kb)} (beyond the declared extras)",
            hint="declare extra outputs (telemetry/sentinel) explicitly",
            source=source,
            data={"n_outputs": [len(ka), len(kb)],
                  "declared_extras": [extra_outputs_a, extra_outputs_b]},
        )
        return EquivalenceCertificate(
            False, "output arity mismatch", label_a, label_b, n_ops,
            min(len(ka), len(kb)), rewrites, d)
    for i, (x, y) in enumerate(zip(ka, kb)):
        if x != y:
            d = _first_divergence(A, B, i, source)
            return EquivalenceCertificate(
                False, f"outputs diverge starting at index {i}",
                label_a, label_b, n_ops, len(ka), rewrites, d)
    return EquivalenceCertificate(
        True, "all outputs canonically identical", label_a, label_b,
        n_ops, len(ka), rewrites)


def certify_callables(fn_a, fn_b, arg_specs, **kw) -> EquivalenceCertificate:
    """Trace two callables over the same ShapeDtypeStruct tree and prove
    them equivalent (the capture controller / serve-rung entry point)."""
    ca = jax.make_jaxpr(fn_a)(*arg_specs)
    cb = jax.make_jaxpr(fn_b)(*arg_specs)
    return prove_equivalent(ca, cb, **kw)


# ---------------------------------------------------------------------------
# structure diff (graph_lint --diff)
# ---------------------------------------------------------------------------
def program_diff(a, b, label_a: str = "A", label_b: str = "B",
                 extra_outputs_a: int = 0,
                 extra_outputs_b: int = 0) -> Tuple[
                     EquivalenceCertificate, List[str]]:
    """(certificate, printable diff lines) between two closed jaxprs:
    op-histogram delta, collective-schedule diff (kinds/axes/payloads in
    program order), and the first-divergence diagnostic when the structural
    proof fails."""
    from .sharding import schedule_of

    A, B = canonicalize(a), canonicalize(b)
    cert = prove_equivalent(
        A, B, label_a=label_a, label_b=label_b,
        extra_outputs_a=extra_outputs_a, extra_outputs_b=extra_outputs_b,
        source="graph_lint --diff")
    lines = [cert.summary()]
    ha = Counter(op.name for op in A.ops)
    hb = Counter(op.name for op in B.ops)
    delta = {n: (ha.get(n, 0), hb.get(n, 0))
             for n in sorted(set(ha) | set(hb))
             if ha.get(n, 0) != hb.get(n, 0)}
    if delta:
        lines.append(f"op histogram deltas ({label_a} vs {label_b}):")
        for n, (x, y) in delta.items():
            lines.append(f"  {n}: {x} vs {y}")
    else:
        lines.append("op histograms identical")
    sa, sb = schedule_of(A.ops), schedule_of(B.ops)
    if sa or sb:
        lines.append(f"collective schedule: {len(sa)} vs {len(sb)} "
                     "collectives")
        for i in range(max(len(sa), len(sb))):
            ra = _sched_str(sa[i]) if i < len(sa) else "—"
            rb = _sched_str(sb[i]) if i < len(sb) else "—"
            mark = " " if ra == rb else "!"
            lines.append(f" {mark} [{i}] {ra} | {rb}")
    else:
        lines.append("no collectives on either side")
    if cert.divergence is not None:
        lines.append(str(cert.divergence))
    return cert, lines


def _sched_str(rec: Dict[str, Any]) -> str:
    return (f"{rec['kind']}@{','.join(map(str, rec['axes']))} "
            f"{rec.get('payload_bytes', 0)}B")


# ---------------------------------------------------------------------------
# registry pass: runs only when a reference program is attached to the
# context (ctx.reference) — silent everywhere else, so existing self-lint
# gates see zero new diagnostics
# ---------------------------------------------------------------------------
@register_pass("equivalence")
def _equivalence_pass(ctx: Context) -> List[Diagnostic]:
    ref = getattr(ctx, "reference", None)
    if ref is None or ctx.closed is None:
        return []
    try:
        cert = prove_equivalent(
            ctx.closed, ref, label_a=ctx.source or "program",
            label_b="reference", source=ctx.source)
    except Exception as e:  # unprovable ≠ divergent: report, don't crash
        return [Diagnostic(
            Severity.WARNING, "equivalence", "program",
            f"equivalence unprovable: {type(e).__name__}: {e}",
            source=ctx.source)]
    if cert.equivalent:
        return []
    return [cert.divergence]
