"""Planner-guided rematerialization: `memory_budget` as an optimizer.

The PR 4 liveness planner (analysis.memory) only *reports*: it estimates a
program's peak HBM and errors past ``FLAGS_memory_budget_mb``. This module
closes the loop — it USES the per-buffer live ranges, byte sizes, and
recompute costs (the attribution registry's flop model) to pick
rematerialization points that bring the estimated peak under the budget,
and emits a structured :class:`RematPlan` that the execution layers apply.

Mechanism (validated against the planner itself): wrapping the WHOLE
forward in one ``jax.checkpoint``/policy does not move the peak — every
rematerialized value is recomputed up front and coexists through the
backward sweep, so the working set is unchanged. What does move it is
*segmented* remat: slice the traced loss jaxpr into contiguous stages at
planner-chosen cut points and wrap only the stages peak-liveness demands
in their own ``jax.checkpoint``. Each marked stage then keeps only its
boundary values live; its interior is recomputed immediately before that
stage's backward and freed after. Unmarked stages keep their residuals
saved and pay zero recompute — which is how a plan beats the uniform
per-block checkpoint configuration's flat 4/3 recompute tax
(PROFILE_GPT.md): it only recomputes the slices that actually hold the
peak up.

The planner works at the granularity of the loss jaxpr's top-level
equations (one per framework op — each is a pjit-wrapped fused region),
scores candidate segmentations by predicted recompute flops, and verifies
each candidate *exactly* by retracing the caller's full step with the
sliced forward and re-running the liveness planner over it — the reported
``peak_after`` is the same estimate ``memory_plan()`` would print for the
planned program, not a model of it.

Consumers: ``jit.compile_train_step(memory_plan=...)`` (the perf path),
the whole-step capture controller in ``core/lazy.py``
(``FLAGS_memory_plan=auto``), ``tools/graph_lint.py --plan``, and the
``optimizer.offload`` scheduler (``cold_state_indices`` marks accumulator
groups live only inside the update program).
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "RematPlan",
    "build_remat_plan",
    "sliced_callable",
    "plan_program",
    "cold_state_indices",
    "state",
]

_MB = float(1 << 20)


def _aval_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape or (1,))) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _eqn_out_bytes(eqn) -> int:
    return sum(
        _aval_bytes(v.aval) for v in eqn.outvars
        if type(v) is jax.core.Var
    )


def _eqn_flops(eqn) -> int:
    """Recompute cost of one top-level equation, via the attribution
    registry's flop model over its inlined flat ops (sees through the
    pjit wrapper — same estimates program_costs caches)."""
    from ..profiler.attribution import _op_flops
    from . import _inline_ops

    invars, seen = [], set()
    for a in eqn.invars:
        if isinstance(a, jax.core.Var) and id(a) not in seen:
            seen.add(id(a))
            invars.append(a)
    outvars = [v for v in eqn.outvars if type(v) is jax.core.Var]
    mini = jax.core.Jaxpr((), invars, outvars, [eqn])
    try:
        ops, _producers, _outs = _inline_ops(jax.core.ClosedJaxpr(mini, []))
        return sum(_op_flops(op) for op in ops)
    except Exception:
        return sum(_aval_bytes(v.aval) for v in outvars)


# ---------------------------------------------------------------------------
# Jaxpr slicing: a callable that evaluates the traced loss as a sequence of
# stages, each optionally under its own jax.checkpoint
# ---------------------------------------------------------------------------
def sliced_callable(closed, stages: Sequence[Tuple[int, int, bool]]):
    """Rebuild ``closed`` (a traced ClosedJaxpr) as a callable over its flat
    invars that evaluates the equations in contiguous ``(start, end,
    remat)`` stages. A ``remat=True`` stage is wrapped in ``jax.checkpoint``
    so only its boundary values survive the forward — its interior is
    recomputed during the backward. ``stages=[(0, n, False)]`` is the
    identity (bitwise-equal to evaluating ``closed`` directly, as is any
    other segmentation: the same equations run in the same order)."""
    jx = closed.jaxpr
    consts = list(closed.consts)
    outvar_set = {v for v in jx.outvars if isinstance(v, jax.core.Var)}
    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(jx.eqns):
        for a in eqn.invars:
            if isinstance(a, jax.core.Var):
                last_use[a] = i

    prepared = []
    for (start, end, remat) in stages:
        eqns = jx.eqns[start:end]
        produced = set()
        for eqn in eqns:
            produced.update(eqn.outvars)
        ins, seen = [], set()
        for eqn in eqns:
            for a in eqn.invars:
                if (isinstance(a, jax.core.Var) and a not in produced
                        and a not in seen):
                    seen.add(a)
                    ins.append(a)
        outs = []
        for eqn in eqns:
            for v in eqn.outvars:
                if type(v) is jax.core.Var and (
                        last_use.get(v, -1) >= end or v in outvar_set):
                    outs.append(v)
        sub = jax.core.Jaxpr((), ins, outs, eqns)

        def run_stage(vals, _sub=sub):
            return jax.core.eval_jaxpr(_sub, (), *vals)

        if remat:
            run_stage = jax.checkpoint(run_stage)
        prepared.append((ins, outs, run_stage))

    def run(*flat):
        env: Dict[Any, Any] = {}
        for v, c in zip(jx.constvars, consts):
            env[v] = c
        for v, a in zip(jx.invars, flat):
            env[v] = a
        for ins, outs, fn in prepared:
            vals = fn([env[a] for a in ins])
            for v, val in zip(outs, vals):
                env[v] = val
        return [
            a.val if isinstance(a, jax.core.Literal) else env[a]
            for a in jx.outvars
        ]

    return run


# ---------------------------------------------------------------------------
# The plan object
# ---------------------------------------------------------------------------
class RematPlan:
    """A chosen segmentation of one traced loss program, plus the planner's
    before/after peak estimates. Apply with :meth:`bind`; persist/display
    with :meth:`to_dict` / :meth:`summary`. ``closed`` (the traced loss
    jaxpr the stages index into) rides along for application but is not
    part of the fingerprint."""

    def __init__(self, *, stages, n_eqns, budget_bytes, peak_before_bytes,
                 peak_after_bytes, recompute_flops, full_remat_flops,
                 source="", note="", evals=0, closed=None):
        self.stages = tuple((int(s), int(t), bool(r)) for s, t, r in stages)
        self.n_eqns = int(n_eqns)
        self.budget_bytes = int(budget_bytes)
        self.peak_before_bytes = int(peak_before_bytes)
        self.peak_after_bytes = int(peak_after_bytes)
        self.recompute_flops = int(recompute_flops)
        self.full_remat_flops = int(full_remat_flops)
        self.source = source
        self.note = note
        self.evals = int(evals)
        self.closed = closed

    @property
    def has_cuts(self) -> bool:
        return any(r for _s, _t, r in self.stages)

    @property
    def feasible(self) -> bool:
        return self.budget_bytes <= 0 or (
            self.peak_after_bytes <= self.budget_bytes)

    @property
    def cut_points(self) -> Tuple[int, ...]:
        """Stage-boundary equation indices (where saved activations cut the
        rematerialized region)."""
        return tuple(s for s, _t, _r in self.stages[1:])

    @property
    def recompute_pct(self) -> float:
        """Predicted recompute flops as % of one full forward — the uniform
        per-block checkpoint plan sits at 100 (the measured 4/3 step tax)."""
        if not self.full_remat_flops:
            return 0.0
        return 100.0 * self.recompute_flops / self.full_remat_flops

    def fingerprint(self) -> str:
        payload = repr((self.stages, self.n_eqns, self.budget_bytes))
        return hashlib.sha1(payload.encode()).hexdigest()[:16]

    def bind(self, closed=None) -> Callable:
        """The planned executable: ``closed``'s flat invars in, flat outvars
        out, remat stages under their own ``jax.checkpoint``."""
        target = closed if closed is not None else self.closed
        if target is None:
            raise ValueError("RematPlan.bind() needs the traced loss jaxpr")
        return sliced_callable(target, self.stages)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "source": self.source,
            "n_eqns": self.n_eqns,
            "stages": [
                {"start": s, "end": t, "remat": r} for s, t, r in self.stages
            ],
            "cut_points": list(self.cut_points),
            "budget_mb": round(self.budget_bytes / _MB, 2),
            "peak_before_mb": round(self.peak_before_bytes / _MB, 2),
            "peak_after_mb": round(self.peak_after_bytes / _MB, 2),
            "recompute_flops": self.recompute_flops,
            "full_remat_flops": self.full_remat_flops,
            "recompute_pct": round(self.recompute_pct, 1),
            "feasible": self.feasible,
            "fingerprint": self.fingerprint(),
            "evals": self.evals,
            "note": self.note,
        }

    def summary(self) -> str:
        d = self.to_dict()
        lines = [
            f"memory plan [{self.source}] "
            f"{'FEASIBLE' if self.feasible else 'INFEASIBLE'} "
            f"fingerprint={d['fingerprint']}",
            f"  peak: {d['peak_before_mb']} MB -> {d['peak_after_mb']} MB "
            f"(budget {d['budget_mb']} MB)",
            f"  recompute: {d['recompute_pct']}% of one forward "
            f"(uniform per-block checkpoint = 100%)",
        ]
        if self.has_cuts:
            marked = [f"[{s}:{t})" + ("*" if r else "")
                      for s, t, r in self.stages]
            lines.append(
                f"  stages over {self.n_eqns} top-level eqns "
                f"(* = rematerialized): " + " ".join(marked))
            lines.append(f"  cut points (saved boundaries): "
                         f"{list(self.cut_points)}")
        else:
            why = self.note or "peak already under budget"
            lines.append(f"  no cuts chosen ({why})")
        return "\n".join(lines)

    def __repr__(self):
        return (f"RematPlan(source={self.source!r}, "
                f"peak={self.peak_before_bytes / _MB:.1f}->"
                f"{self.peak_after_bytes / _MB:.1f}MB, "
                f"budget={self.budget_bytes / _MB:.1f}MB, "
                f"cuts={list(self.cut_points)}, "
                f"recompute={self.recompute_pct:.0f}%, "
                f"feasible={self.feasible})")


# ---------------------------------------------------------------------------
# The planner
# ---------------------------------------------------------------------------
def _byte_balanced_bounds(weights: List[int], k: int) -> List[int]:
    """Split ``range(len(weights))`` into ``k`` contiguous chunks of roughly
    equal total weight (per-eqn output bytes) — balanced interiors keep the
    largest co-resident recompute working set small."""
    n = len(weights)
    total = max(1, sum(weights))
    bounds = [0]
    acc = 0
    target = total / k
    for i, w in enumerate(weights):
        acc += w
        while len(bounds) < k and acc >= target * len(bounds):
            nxt = i + 1
            if nxt > bounds[-1] and nxt < n:
                bounds.append(nxt)
            else:
                break
    while len(bounds) < k:
        nxt = min(n - 1, bounds[-1] + 1)
        if nxt <= bounds[-1]:
            break
        bounds.append(nxt)
    bounds.append(n)
    return bounds


def build_remat_plan(loss_closed, *, budget_bytes: int, measure: Callable,
                     source: str = "loss", max_evals: int = 8,
                     min_gain: float = 0.01) -> RematPlan:
    """Pick a segmentation of ``loss_closed`` whose *measured* whole-step
    peak fits ``budget_bytes``, spending as little recompute as possible.

    ``measure(stage_callable_or_None) -> peak_bytes`` is the caller's
    oracle: it must retrace its full step (forward + backward + update)
    with the given planned loss callable substituted in (``None`` = the
    unplanned step) and return the liveness planner's peak estimate — so
    every number in the plan is the exact figure ``memory_plan()`` reports
    for that program, not an approximation.

    Candidates are staged segmentations at increasing cut counts; within
    each, the *earliest* stages are marked for remat first (their residuals
    span the whole backward, so they are what holds the peak up) and the
    tail stage is kept saved — recompute stays strictly below the uniform
    per-block plan whenever such a candidate fits. Evaluation stops at the
    first (cheapest) feasible candidate, or falls back to the best peak
    seen (``min_gain`` improvement required) when the budget is
    unreachable — e.g. a captured-step program whose op outputs all escape
    to the host, which no remat can shrink."""
    jx = loss_closed.jaxpr
    n = len(jx.eqns)
    t0 = time.perf_counter()
    peak_before = int(measure(None))
    evals = 1

    flops = [_eqn_flops(e) for e in jx.eqns]
    out_bytes = [_eqn_out_bytes(e) for e in jx.eqns]
    full_flops = sum(flops)

    def finish(stages, peak_after, note):
        plan = RematPlan(
            stages=stages, n_eqns=n, budget_bytes=budget_bytes,
            peak_before_bytes=peak_before, peak_after_bytes=peak_after,
            recompute_flops=sum(
                sum(flops[s:t]) for s, t, r in stages if r),
            full_remat_flops=full_flops, source=source, note=note,
            evals=evals, closed=loss_closed,
        )
        _record(source, plan, (time.perf_counter() - t0) * 1000.0)
        return plan

    identity = [(0, n, False)]
    if budget_bytes <= 0 or peak_before <= budget_bytes:
        return finish(identity, peak_before, "peak already under budget")
    if n < 2:
        return finish(identity, peak_before, "program too small to slice")

    # candidate family: K byte-balanced stages, earliest m marked remat —
    # ordered globally by predicted recompute flops so the first feasible
    # candidate is also the cheapest one tried
    candidates = []
    for k in (2, 3, 4, 6, 8, 12, 16, 24, 32):
        if k > n:
            break
        bounds = _byte_balanced_bounds(out_bytes, k)
        for m in sorted({max(1, k // 2), k - 1, k}):
            stages = [
                (bounds[i], bounds[i + 1], i < m) for i in range(k)
            ]
            cost = sum(sum(flops[s:t]) for s, t, r in stages if r)
            candidates.append((cost, k, stages))
    candidates.sort(key=lambda c: (c[0], c[1]))

    seen, ordered = set(), []
    for cost, k, stages in candidates:
        sig = tuple(stages)
        if sig not in seen:
            seen.add(sig)
            ordered.append((cost, stages))

    # bisect the cost-ordered candidate list for the cheapest feasible
    # segmentation: more remat monotonically (in this family) trades flops
    # for peak, so log2(len) exact measurements find the frontier instead
    # of burning the eval budget on cheap plans that cannot fit
    best_stages, best_peak = identity, peak_before
    measured: Dict[int, int] = {}

    def peak_of(idx: int) -> int:
        nonlocal evals, best_stages, best_peak
        if idx not in measured:
            stages = ordered[idx][1]
            measured[idx] = int(measure(sliced_callable(loss_closed, stages)))
            evals += 1
            if measured[idx] < best_peak:
                best_stages, best_peak = stages, measured[idx]
        return measured[idx]

    lo, hi = 0, len(ordered) - 1
    found = None
    while lo <= hi and evals < max_evals:
        mid = (lo + hi) // 2
        if peak_of(mid) <= budget_bytes:
            found = mid
            hi = mid - 1
        else:
            lo = mid + 1
    # peak is only approximately monotone in recompute cost (an all-remat
    # high-K plan saves MORE boundaries than a lower-K one) — spend any
    # remaining evals walking left from the frontier toward cheaper
    # candidates the bisection's monotonicity assumption skipped
    if found is not None:
        i = found - 1
        while i >= 0 and evals < max_evals:
            if i not in measured and peak_of(i) <= budget_bytes:
                found = i
            i -= 1
    if found is not None:
        return finish(ordered[found][1], measured[found], "")

    if best_peak < peak_before * (1.0 - min_gain):
        return finish(best_stages, best_peak,
                      "budget unreachable; best reduction kept")
    return finish(identity, peak_before,
                  "remat cannot reduce this program's peak")


# ---------------------------------------------------------------------------
# Cold optimizer state (feeds paddle_tpu.optimizer.offload)
# ---------------------------------------------------------------------------
def cold_state_indices(closed, roles) -> List[Tuple[int, str]]:
    """Flat invar indices (+ role names) of optimizer-state inputs that are
    *cold*: first read only inside the trailing update program — after the
    last forward read of every feed input and past the midpoint of the
    step. Their buffers are dead through the forward + backward, which is
    exactly the window the offload scheduler parks them on the host."""
    jx = closed.jaxpr
    first_read: Dict[Any, int] = {}
    last_read: Dict[Any, int] = {}
    for i, eqn in enumerate(jx.eqns):
        for a in eqn.invars:
            if isinstance(a, jax.core.Var):
                first_read.setdefault(a, i)
                last_read[a] = i
    n = max(1, len(jx.eqns))
    feed_horizon = -1
    for v, (kind, _name) in zip(jx.invars, roles):
        if kind == "feed" and v in first_read:
            feed_horizon = max(feed_horizon, first_read[v])
    cold = []
    for i, (v, (kind, name)) in enumerate(zip(jx.invars, roles)):
        if kind != "buffer" or not str(name).startswith("opt_state"):
            continue
        fr = first_read.get(v)
        if fr is None:
            continue  # unread state passes through — trivially cold, but
            # offloading it saves nothing the donation didn't already
        if fr > feed_horizon and fr >= n // 2:
            cold.append((i, str(name)))
    return cold


# ---------------------------------------------------------------------------
# Whole-program planning for external callables (graph_lint --plan)
# ---------------------------------------------------------------------------
def plan_program(target, feed_specs=None, *, memory_budget_mb=None,
                 source=None, max_evals: int = 8) -> RematPlan:
    """Plan remat for a model/program the way ``graph_lint --plan`` sees it:
    trace the forward, wrap it in a synthetic training step (sum-of-outputs
    loss, vjp over the parameter inputs), and search segmentations of the
    forward until the step's planner peak fits the budget."""
    from . import Context, _context_of
    from ..core import flags as _flags
    from . import memory as _memory

    closed, roles, src = _context_of(target, feed_specs)
    source = source or f"plan:{src}"
    budget_mb = (float(_flags.flag("memory_budget_mb"))
                 if memory_budget_mb is None else float(memory_budget_mb))
    budget_bytes = int(budget_mb * _MB)

    jx = closed.jaxpr
    invars = list(jx.invars)
    roles = list(roles) + [("arg", f"in{i}")
                           for i in range(len(invars) - len(roles))]
    # differentiate w.r.t. the parameter inputs (all float inputs when the
    # target carries no roles — a bare callable's args are its "params")
    has_params = any(kind == "param" for kind, _ in roles)
    diff_idx = [
        i for i, (v, (kind, _n)) in enumerate(zip(invars, roles))
        if np.issubdtype(np.dtype(v.aval.dtype), np.inexact)
        and (kind == "param" or not has_params)
    ]
    if not diff_idx:
        raise ValueError(
            f"{source}: no differentiable (float) inputs to plan a "
            "training step over")
    specs = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype) for v in invars]

    def measure(stage_fn) -> int:
        run = stage_fn if stage_fn is not None else sliced_callable(
            closed, [(0, len(jx.eqns), False)])

        def step(*args):
            def lf(dvals):
                full = list(args)
                for i, v in zip(diff_idx, dvals):
                    full[i] = v
                outs = run(*full)
                tot = jnp.zeros((), jnp.float32)
                for o in outs:
                    if np.issubdtype(np.dtype(o.dtype), np.inexact):
                        tot = tot + jnp.sum(o.astype(jnp.float32))
                return tot
            lval, vjp = jax.vjp(lf, tuple(args[i] for i in diff_idx))
            (grads,) = vjp(jnp.ones((), jnp.float32))
            return lval, grads

        step_closed = jax.make_jaxpr(step)(*specs)
        ctx = Context(step_closed, roles, source)
        return _memory.plan_memory(ctx).peak_bytes

    return build_remat_plan(closed, budget_bytes=budget_bytes,
                            measure=measure, source=source,
                            max_evals=max_evals)


# ---------------------------------------------------------------------------
# Module state: last plan per source (for /statusz, metrics, events)
# ---------------------------------------------------------------------------
_lock = threading.Lock()
_state: Dict[str, Dict[str, Any]] = {}


def _record(source: str, plan: RematPlan, build_ms: float) -> None:
    doc = plan.to_dict()
    doc["build_ms"] = round(build_ms, 2)
    with _lock:
        _state[source] = doc
    try:
        from ..core import dispatch

        dispatch._counter_add("memory_plan_builds", 1)
        dispatch._emit(
            "memory_plan", site=source, phase="built",
            fingerprint=doc["fingerprint"], feasible=doc["feasible"],
            peak_before_mb=doc["peak_before_mb"],
            peak_after_mb=doc["peak_after_mb"],
            recompute_pct=doc["recompute_pct"],
        )
    except Exception:
        pass
    try:
        from ..profiler import metrics as _metrics

        reg = _metrics.default_registry()
        labels = {"source": source}
        reg.gauge("memory_plan_peak_before_mb",
                  doc="planner peak estimate before remat, MB",
                  labels=labels).set(doc["peak_before_mb"])
        reg.gauge("memory_plan_peak_after_mb",
                  doc="planner peak estimate with the chosen plan, MB",
                  labels=labels).set(doc["peak_after_mb"])
        reg.gauge("memory_plan_recompute_pct",
                  doc="predicted recompute as % of one forward "
                      "(uniform per-block checkpoint = 100)",
                  labels=labels).set(doc["recompute_pct"])
    except Exception:
        pass


def record_failure(source: str, err: BaseException) -> None:
    """Book a plan-build failure (the execution layers call this before
    falling back to the unplanned step)."""
    with _lock:
        _state[source] = {
            "source": source, "failed": True,
            "error": f"{type(err).__name__}: {err}",
        }
    try:
        from ..core import dispatch

        dispatch._counter_add("memory_plan_failures", 1)
        dispatch._emit("memory_plan", site=source, phase="failed",
                       error=type(err).__name__)
    except Exception:
        pass


def state() -> Dict[str, Any]:
    """Snapshot of the last plan (or failure) per source — the /statusz
    'memory plan & offload' section reads this."""
    with _lock:
        return {k: dict(v) for k, v in _state.items()}


def _reset_state() -> None:  # tests
    with _lock:
        _state.clear()
