"""paddle_tpu.analysis — graph verifier & lint-pass framework.

Reference analogue: the IR pass/verifier infrastructure over ProgramDesc
(paddle/fluid/framework/ir — Pass::Apply, graph_pattern_detector.h — and the
operators' InferShape/InferDtype checks). The reference verifies a proto op
graph; here every execution mode already funnels through one IR — the traced
jaxpr — so the verifier runs over flattened jaxprs obtained from any of:

  - a ``static.Program``           (``analysis.check(program)``),
  - a ``paddle.jit.to_static`` fn  (``analysis.check(static_fn, specs)``),
  - a dygraph ``nn.Layer``         (``analysis.check(layer, specs)``),
  - a plain traceable callable     (``analysis.check(fn, specs)``),
  - the pending lazy-dispatch segment (``analysis.check_pending_segment()``).

Passes are registered by name (``register_pass``) and produce structured
``Diagnostic`` records (severity, op path, shapes/dtypes involved, fix
hint). ``FLAGS_check_programs`` wires the suite into ``Executor.run``
compile time and lazy-segment flush: 1 = report every diagnostic as a
Python warning, 2 = additionally raise ``ProgramVerificationError`` on
error-severity findings.

The pattern passes need to see *through* the per-op jit wrappers (every
framework op arrives as a one-primitive ``pjit`` call), so the analysis IR
is an **inlined flat op list**: call-like equations are inlined with full
variable substitution, making cross-op producer chains (transpose∘transpose,
log∘softmax) visible, while control-flow bodies (``scan``/``while``/``cond``)
are recursed into as separate scopes.
"""
from __future__ import annotations

import dataclasses
import enum
import warnings
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core import flags as _flags

__all__ = [
    "Severity",
    "Diagnostic",
    "ProgramVerificationError",
    "check",
    "check_pending_segment",
    "check_launch_budget",
    "enforce",
    "register_pass",
    "pass_names",
    "run_passes",
]


class Severity(enum.IntEnum):
    """Diagnostic severity; ordered so ``>= Severity.ERROR`` comparisons work."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):  # "error", not "Severity.ERROR", in reports
        return self.name.lower()


@dataclasses.dataclass
class Diagnostic:
    """One structured finding from an analysis pass.

    The reference's pass framework logs free text; diagnostics here carry
    the op path plus the shapes/dtypes involved so tools (and tests) can
    key on them, and a fix hint aimed at the model author."""

    severity: Severity
    pass_name: str
    op: str  # op path, e.g. "eqn[12] transpose" or "feed:x"
    message: str
    hint: str = ""
    shapes: Tuple = ()
    dtypes: Tuple = ()
    source: str = ""
    # structured payload for tools (graph_lint --json, bench): the memory
    # passes put their peak/credit arithmetic here so consumers need not
    # parse the message text
    data: Dict = dataclasses.field(default_factory=dict)

    def __str__(self):
        loc = f" [{self.source}]" if self.source else ""
        hint = f" (hint: {self.hint})" if self.hint else ""
        return (
            f"{self.severity}[{self.pass_name}]{loc} {self.op}: "
            f"{self.message}{hint}"
        )


class ProgramVerificationError(RuntimeError):
    """Raised by enforce() when FLAGS_check_programs>=2 and an error-severity
    diagnostic is present. Carries the full diagnostic list."""

    def __init__(self, message, diagnostics):
        super().__init__(message)
        self.diagnostics = list(diagnostics)


# ---------------------------------------------------------------------------
# Analysis IR: inlined flat op list over a (closed) jaxpr
# ---------------------------------------------------------------------------
class ConstAtom:
    """A closed-over constant (weights/keys baked into the trace)."""

    __slots__ = ("val", "aval")

    def __init__(self, val):
        self.val = val
        try:
            self.aval = jax.core.get_aval(val)
        except Exception:  # non-array const (rare) — shapeless placeholder
            self.aval = None

    def __repr__(self):
        return f"ConstAtom({getattr(self.aval, 'str_short', lambda: '?')()})"


class CanonVar:
    """Fresh canonical SSA value for one inlined op instance's output.

    The per-op jit cache means two applications of the same op share ONE
    inner jaxpr object — its Vars are not unique across call sites — so the
    inliner mints a fresh canonical var per instance to keep the producer
    map sound."""

    __slots__ = ("aval",)

    def __init__(self, aval):
        self.aval = aval

    def __repr__(self):
        return f"CanonVar({self.aval})"


class FlatOp:
    """One primitive application in the inlined op list.

    ``invars`` are *canonical atoms*: top-level jaxpr Vars, per-instance
    CanonVars resolved across inlined call boundaries, Literals, or
    ConstAtoms — so ``producers[op.invars[0]]`` chases a producer chain even
    when each op sat in its own pjit wrapper."""

    __slots__ = ("name", "invars", "outvars", "params", "scope", "index")

    def __init__(self, name, invars, outvars, params, scope, index):
        self.name = name
        self.invars = invars
        self.outvars = outvars
        self.params = params
        self.scope = scope
        self.index = index

    @property
    def path(self) -> str:
        pre = f"{self.scope}/" if self.scope else ""
        return f"{pre}eqn[{self.index}] {self.name}"

    def __repr__(self):
        return f"<FlatOp {self.path}>"


# control-flow primitives: recursed into as separate scopes (their bodies see
# sliced/carried values, so invars cannot be substituted 1:1). shard_map is
# scoped for the same reason in the default (global-shape) analysis: its body
# vars carry PER-SHARD avals, so substituting the global-shaped outer atoms
# through the boundary would mix global and per-shard buffer sizes in one
# producer chain. The mesh-scoped analyzer (analysis.sharding) inlines
# through it instead, after rewriting every outer aval to its per-shard
# shape.
_SCOPE_PRIMS = {"scan", "while", "cond", "switch", "shard_map"}


def _as_open(j):
    """(open jaxpr, consts) from a ClosedJaxpr or a bare Jaxpr."""
    if hasattr(j, "jaxpr"):
        return j.jaxpr, list(j.consts)
    return j, []


def _sub_jaxprs(eqn):
    """('call', [sub]) for inline-with-substitution equations, ('scope', subs)
    for control-flow bodies, (None, []) for plain primitives."""
    name = eqn.primitive.name
    if name == "scan":
        return "scope", [eqn.params["jaxpr"]]
    if name == "shard_map":
        # per-shard body avals — a scope, NOT a call: the params carry a
        # "jaxpr" key, but call-inlining would substitute global-shaped
        # outer atoms for per-shard body invars (unsound sizes/chains)
        return "scope", [eqn.params["jaxpr"]]
    if name == "while":
        return "scope", [eqn.params["cond_jaxpr"], eqn.params["body_jaxpr"]]
    if name in ("cond", "switch"):
        return "scope", list(eqn.params["branches"])
    # "fun_jaxpr" is custom_vjp_call_jaxpr's primal body (custom_jvp_call
    # carries plain "call_jaxpr"): the custom-gradient API contract is that
    # the primal function and the fwd rule return the same primal outputs,
    # so determinism/equivalence analysis sees through the body as an
    # ordinary 1:1 call (arity mismatches still fall back to a scope below)
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is not None:
            return "call", [sub]
    return None, []


def _resolve(atom, env):
    if isinstance(atom, jax.core.Literal):
        return atom
    return env.get(atom, atom)


def _inline(open_jaxpr, consts, env, out, producers, scope):
    for cv, cval in zip(open_jaxpr.constvars, consts):
        env[cv] = ConstAtom(cval)
    for eqn in open_jaxpr.eqns:
        kind, subs = _sub_jaxprs(eqn)
        if kind == "call":
            sub_open, sub_consts = _as_open(subs[0])
            if len(sub_open.invars) == len(eqn.invars):
                ienv = {}
                for iv, outer in zip(sub_open.invars, eqn.invars):
                    ienv[iv] = _resolve(outer, env)
                _inline(sub_open, sub_consts, ienv, out, producers, scope)
                for ov, iov in zip(eqn.outvars, sub_open.outvars):
                    env[ov] = _resolve(iov, ienv)
                continue
            kind = "scope"  # arity mismatch — keep the call opaque, recurse
        if kind == "scope":
            for si, sub in enumerate(subs):
                sub_open, sub_consts = _as_open(sub)
                ienv = {iv: iv for iv in sub_open.invars}
                tag = eqn.primitive.name + (str(si) if len(subs) > 1 else "")
                _inline(sub_open, sub_consts, ienv, out, producers,
                        f"{scope}/{tag}" if scope else tag)
        canon = [CanonVar(ov.aval) for ov in eqn.outvars]
        op = FlatOp(
            eqn.primitive.name,
            [_resolve(v, env) for v in eqn.invars],
            canon,
            eqn.params,
            scope,
            len(out),
        )
        for ov, cv in zip(eqn.outvars, canon):
            env[ov] = cv
            producers[cv] = op
        out.append(op)


def _inline_ops(closed):
    """(flat ops, producer map, resolved output atoms) for a closed jaxpr."""
    open_jaxpr, consts = _as_open(closed)
    env: Dict[Any, Any] = {v: v for v in open_jaxpr.invars}
    out: List[FlatOp] = []
    producers: Dict[Any, FlatOp] = {}
    _inline(open_jaxpr, consts, env, out, producers, "")
    out_atoms = [_resolve(v, env) for v in open_jaxpr.outvars]
    return out, producers, out_atoms


# -- atom helpers (shared with passes.py) -----------------------------------
def atom_aval(a):
    return getattr(a, "aval", None)


def atom_shape(a):
    return tuple(getattr(atom_aval(a), "shape", ()))


def atom_dtype(a):
    dt = getattr(atom_aval(a), "dtype", None)
    try:
        return np.dtype(dt) if dt is not None else None
    except TypeError:
        return None  # extended dtypes (PRNG keys)


def atom_is_weak(a):
    return bool(getattr(atom_aval(a), "weak_type", False))


_PASSTHROUGH_SCALAR = {
    "convert_element_type", "broadcast_in_dim", "reshape", "stop_gradient",
    "squeeze", "expand_dims", "copy",
}

# tiny constant folder: framework lowerings build scalar configs as
# expressions (jnp.var's N - ddof, uniform's hi - lo); fold them so the
# passes see the value, as XLA's constant folding will
_FOLD_OPS = {
    "add": (2, lambda a, b: a + b),
    "sub": (2, lambda a, b: a - b),
    "mul": (2, lambda a, b: a * b),
    "div": (2, lambda a, b: a / b if b else None),
    "max": (2, lambda a, b: max(a, b)),
    "min": (2, lambda a, b: min(a, b)),
    "neg": (1, lambda a: -a),
}


def scalar_const(atom, producers, depth=6):
    """Python scalar behind `atom`, chasing converts/broadcasts and folding
    simple constant arithmetic; None if it is not a compile-time scalar."""
    if depth <= 0:
        return None
    if isinstance(atom, (jax.core.Literal, ConstAtom)):
        try:
            arr = np.asarray(atom.val)
        except Exception:
            return None
        if arr.size != 1:
            return None
        return arr.reshape(()).item()
    op = producers.get(atom)
    if op is None:
        return None
    if op.name in _PASSTHROUGH_SCALAR:
        return scalar_const(op.invars[0], producers, depth - 1)
    arity_fn = _FOLD_OPS.get(op.name)
    if arity_fn is not None and len(op.invars) == arity_fn[0]:
        vals = [scalar_const(a, producers, depth - 1) for a in op.invars]
        if all(v is not None for v in vals):
            try:
                return arity_fn[1](*vals)
            except Exception:
                return None
    return None


# ---------------------------------------------------------------------------
# Pass registry
# ---------------------------------------------------------------------------
_passes: "OrderedDict[str, Callable]" = OrderedDict()


def register_pass(name: str):
    """Decorator: register ``fn(ctx) -> List[Diagnostic]`` under ``name``."""

    def deco(fn):
        _passes[name] = fn
        return fn

    return deco


def pass_names() -> List[str]:
    return list(_passes)


class Context:
    """Everything a pass sees for one checked program."""

    def __init__(self, closed, roles, source, counters=None, budget=None,
                 donated=(), alias_groups=None, alias_refs=None,
                 memory_budget_mb=None):
        # closed=None builds a jaxpr-less context (counter-only passes like
        # launch_budget) — every field still gets its default, so passes
        # never need getattr guards against a partially-built Context
        self.closed = closed
        self.jaxpr = _as_open(closed)[0] if closed is not None else None
        # (kind, name) per jaxpr invar; kind in {"param","buffer","feed","arg"}
        self.roles: List[Tuple[str, str]] = list(roles)
        self.source = source
        self.counters = counters
        self.budget = budget
        # memory/donation info (analysis.memory): flat invar indices donated
        # to the program, groups of indices bound to one runtime buffer, and
        # {index: [description of live external alias]} from a runtime scan
        self.donated: Tuple[int, ...] = tuple(donated or ())
        self.alias_groups = list(alias_groups or [])
        self.alias_refs: Dict[int, List] = dict(alias_refs or {})
        self.memory_budget_mb = memory_budget_mb
        # mesh-scoped subclasses (analysis.sharding.ShardContext) set these
        # before delegating here; every pass can getattr-free test
        # ``ctx.mesh_axes`` to know whether avals are per-shard
        if not hasattr(self, "mesh_axes"):
            self.mesh_axes = None
        if not hasattr(self, "in_specs"):
            self.in_specs = None
        # canonical per-invar atoms: the top-level jaxpr Vars by default; a
        # mesh-scoped context replaces them with per-shard CanonVars so
        # invar_roles()/plan_memory operate on what one chip actually holds
        self.invar_atoms: List = []
        self.ops, self.producers, self.out_atoms = self._build_ir()
        if not self.invar_atoms and self.jaxpr is not None:
            self.invar_atoms = list(self.jaxpr.invars)

    def _build_ir(self):
        """(ops, producers, out_atoms) — overridden by ShardContext with the
        per-shard inliner."""
        return _inline_ops(self.closed) if self.closed is not None else ([], {}, [])

    def invar_roles(self):
        invars = list(self.invar_atoms)
        roles = self.roles
        if len(roles) < len(invars):
            roles = roles + [("arg", str(i)) for i in range(len(roles), len(invars))]
        return list(zip(invars, roles))

    def used_atoms(self):
        used = set()
        for op in self.ops:
            for a in op.invars:
                if isinstance(a, (jax.core.Var, CanonVar)):
                    used.add(a)
        for a in self.out_atoms:
            if isinstance(a, (jax.core.Var, CanonVar)):
                used.add(a)
        return used


def run_passes(ctx: Context, passes: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    names = list(passes) if passes is not None else pass_names()
    diags: List[Diagnostic] = []
    for name in names:
        fn = _passes.get(name)
        if fn is None:
            raise ValueError(
                f"unknown analysis pass {name!r}; registered: {pass_names()}"
            )
        for d in fn(ctx):
            if not d.source:
                d.source = ctx.source
            diags.append(d)
    diags.sort(key=lambda d: (-int(d.severity), d.pass_name, d.op))
    return diags


# ---------------------------------------------------------------------------
# Feed-spec normalization + tracing front-ends
# ---------------------------------------------------------------------------
def _norm_one_spec(spec, name=None):
    from ..core.dtype import to_np_dtype

    shape = getattr(spec, "shape", None)
    if shape is not None:
        dtype = getattr(spec, "dtype", "float32")
    else:
        shape, dtype = spec  # (shape, dtype) tuple
    shape = tuple(1 if d in (None, -1) else int(d) for d in shape)
    return (name or getattr(spec, "name", None), shape, to_np_dtype(dtype))


def _norm_specs(feed_specs) -> List[Tuple[Optional[str], Tuple, np.dtype]]:
    if feed_specs is None:
        return []
    if isinstance(feed_specs, dict):
        return [_norm_one_spec(s, name=n) for n, s in sorted(feed_specs.items())]
    if not isinstance(feed_specs, (list, tuple)):
        feed_specs = [feed_specs]
    return [_norm_one_spec(s) for s in feed_specs]


def _sds(specs):
    return tuple(jax.ShapeDtypeStruct(s, d) for _, s, d in specs)


def _trace_callable(fn, specs, layer=None, source="fn"):
    """Trace `fn(*tensors)` (optionally with `layer`'s params/buffers swapped
    in as jaxpr inputs) into a closed jaxpr + invar roles.

    Params/buffers become leading invars so the dead-code pass can report
    unused parameters; buffer values after the call are appended to the
    outputs so in-place running-stat updates (BatchNorm) are not reported
    as dead code — and so no tracer ever leaks into live layer state."""
    from ..core.dispatch import no_grad
    from ..core.tensor import Tensor
    from ..jit import _bind_values, _unwrap

    params = list(layer.named_parameters()) if layer is not None else []
    buffers = list(layer.named_buffers()) if layer is not None else []
    p_ts = [p for _, p in params]
    b_ts = [b for _, b in buffers]

    def traced(p_vals, b_vals, feed_vals):
        ins = [Tensor(v, stop_gradient=True) for v in feed_vals]
        with _bind_values(p_ts + b_ts, list(p_vals) + list(b_vals)), no_grad():
            out = fn(*ins)
            new_b = [b._value for b in b_ts]
        out = _unwrap(out)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        return outs + new_b

    p_specs = tuple(
        jax.ShapeDtypeStruct(tuple(p._value.shape), p._value.dtype) for p in p_ts
    )
    b_specs = tuple(
        jax.ShapeDtypeStruct(tuple(b._value.shape), b._value.dtype) for b in b_ts
    )
    closed = jax.make_jaxpr(traced)(p_specs, b_specs, _sds(specs))
    roles = (
        [("param", n) for n, _ in params]
        + [("buffer", n) for n, _ in buffers]
        + [("feed", n or f"arg{i}") for i, (n, _, _) in enumerate(specs)]
    )
    return closed, roles, source


def _trace_program(program, feed_specs=None):
    from ..core.dispatch import no_grad
    from ..core.dtype import to_np_dtype
    from ..core.tensor import Tensor
    from ..jit import _bind_values
    from ..static import program_guard

    import jax.numpy as jnp

    if program.builder is None:
        raise RuntimeError(
            "program has no builder; run layers under this program "
            "(or set_builder) before checking it"
        )
    if feed_specs is not None:
        specs = _norm_specs(feed_specs)
    else:
        items = sorted(program.feed_vars.items())
        specs = [
            (n, tuple(1 if d in (None, -1) else max(int(d), 1) for d in v.shape),
             to_np_dtype(v.dtype))
            for n, v in items
        ]
    names = [n for n, _, _ in specs]

    # warm eagerly first, exactly like Executor.run / Program._traced_jaxpr:
    # static.nn parameters must materialize outside any trace. Mark _warmed
    # only AFTER the run succeeds — a failed check() must not disable the
    # eager-warm path for later legitimate Executor.run calls
    if not getattr(program, "_warmed", False):
        with program_guard(program), no_grad():
            program.builder({
                n: Tensor(jnp.zeros(s, d), stop_gradient=True)
                for n, s, d in specs
            })
        program._warmed = True

    params = program.all_parameters()
    buffers = []
    for layer in program._iter_layers():
        if hasattr(layer, "named_buffers"):
            buffers.extend(layer.named_buffers())
    p_ts = list(params)
    b_ts = [b for _, b in buffers]

    def traced(p_vals, b_vals, feed_vals):
        feed = {n: Tensor(v, stop_gradient=True) for n, v in zip(names, feed_vals)}
        with _bind_values(p_ts + b_ts, list(p_vals) + list(b_vals)), \
                program_guard(program), no_grad():
            out = program.builder(feed)
            new_b = [b._value for b in b_ts]
        outs = out if isinstance(out, (list, tuple)) else [out]
        outs = [o._value if hasattr(o, "_value") else o for o in outs]
        return list(outs) + new_b

    p_specs = tuple(
        jax.ShapeDtypeStruct(tuple(p._value.shape), p._value.dtype) for p in p_ts
    )
    b_specs = tuple(
        jax.ShapeDtypeStruct(tuple(b._value.shape), b._value.dtype) for b in b_ts
    )
    closed = jax.make_jaxpr(traced)(
        p_specs, b_specs, tuple(jax.ShapeDtypeStruct(s, d) for _, s, d in specs)
    )
    roles = (
        [("param", getattr(p, "name", None) or f"param{i}")
         for i, p in enumerate(p_ts)]
        + [("buffer", n) for n, _ in buffers]
        + [("feed", n) for n in names]
    )
    return closed, roles, "Program"


def _context_of(target, feed_specs):
    from ..static import Program
    from ..jit import StaticFunction
    from ..nn.layer_base import Layer

    # raw jaxprs pass straight through (hook points hand these in)
    if hasattr(target, "jaxpr") and hasattr(target, "consts"):
        return target, [], "jaxpr"
    if hasattr(target, "eqns") and hasattr(target, "invars"):
        if getattr(target, "constvars", None):
            raise ValueError(
                "open jaxpr with constvars — pass the ClosedJaxpr instead"
            )
        return jax.core.ClosedJaxpr(target, []), [], "jaxpr"

    if isinstance(target, Program):
        return _trace_program(target, feed_specs)

    # paddle.jit.to_static products
    if isinstance(target, StaticFunction):
        specs = _norm_specs(feed_specs if feed_specs is not None else target._input_spec)
        if not specs:
            raise ValueError(
                "checking a to_static function requires feed_specs (or an "
                "input_spec on the function)"
            )
        name = getattr(target._dygraph_function, "__name__", "to_static")
        return _trace_callable(
            target._converted_function, specs, layer=target._layer,
            source=f"to_static:{name}",
        )
    inner = getattr(target, "_static_fn", None)
    if isinstance(inner, StaticFunction):
        return _context_of(inner, feed_specs)

    if isinstance(target, Layer):
        specs = _norm_specs(feed_specs)
        if not specs:
            raise ValueError("checking a Layer requires feed_specs")
        fn = target.forward
        if isinstance(fn, StaticFunction):
            fn = fn._converted_function
        return _trace_callable(
            fn, specs, layer=target, source=type(target).__name__
        )

    if callable(target):
        specs = _norm_specs(feed_specs)
        if not specs:
            raise ValueError("checking a callable requires feed_specs")
        return _trace_callable(
            target, specs, layer=None,
            source=getattr(target, "__name__", "fn"),
        )
    raise TypeError(
        f"cannot analyze object of type {type(target).__name__}: expected a "
        "Program, Layer, to_static function, callable, or (closed) jaxpr"
    )


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------
def check(
    program_or_fn,
    feed_specs=None,
    *,
    passes: Optional[Sequence[str]] = None,
    counters: Optional[Dict[str, Any]] = None,
    budget: Optional[int] = None,
    source: Optional[str] = None,
    donated: Sequence[int] = (),
    alias_groups=None,
    alias_refs=None,
    memory_budget_mb: Optional[float] = None,
) -> List[Diagnostic]:
    """Run the analysis pass suite over a traced program.

    ``program_or_fn``: a ``static.Program``, ``nn.Layer``, ``to_static``
    function, plain traceable callable, or an already-traced (closed) jaxpr.
    ``feed_specs``: input shapes/dtypes — ``InputSpec`` list, ``(shape,
    dtype)`` tuples, or a ``{name: spec}`` dict. Required unless the target
    is a Program (which knows its feed vars) or carries an input_spec.
    ``donated``/``alias_groups``/``alias_refs`` feed the memory passes:
    donated flat invar indices, indices sharing one runtime buffer, and
    live-external-alias descriptions per index (see ``analysis.memory``).
    ``memory_budget_mb`` overrides ``FLAGS_memory_budget_mb`` for this run.
    Returns diagnostics sorted most-severe first."""
    closed, roles, src = _context_of(program_or_fn, feed_specs)
    ctx = Context(
        closed, roles, source or src, counters=counters, budget=budget,
        donated=donated, alias_groups=alias_groups, alias_refs=alias_refs,
        memory_budget_mb=memory_budget_mb,
    )
    return run_passes(ctx, passes)


def check_pending_segment(passes=None) -> List[Diagnostic]:
    """Analyze this thread's pending lazy-dispatch segment WITHOUT flushing
    it. Returns [] when nothing is pending."""
    from ..core import lazy

    closed = lazy.pending_segment_jaxpr()
    if closed is None:
        return []
    ctx = Context(closed, [], "lazy-segment")
    return run_passes(ctx, passes)


def check_launch_budget(step_fn=None, *args, budget=None, counters=None,
                        warmup=2, **kwargs) -> List[Diagnostic]:
    """Audit steady-state device-program launches per step against a budget.

    Reuses the dispatch counters (PR 1): runs ``step_fn`` ``warmup`` times,
    then measures one step. Alternatively pass a ``counters`` dict captured
    around a step. ``budget=None`` picks the budget from the counters: 1
    when whole-step capture replayed the step as one donated program
    (``FLAGS_eager_step_capture``), else 3 — the lazy-dispatch steady state
    (fused forward + compiled-tape backward + fused optimizer —
    PROFILE_EAGER.md)."""
    if counters is None:
        if step_fn is None:
            raise ValueError("check_launch_budget needs a step_fn or counters")
        from ..profiler import measure_programs

        counters = measure_programs(step_fn, *args, warmup=warmup, **kwargs)
    ctx = Context(None, [], "launch-budget", counters=dict(counters),
                  budget=budget)
    return run_passes(ctx, ["launch_budget"])


def enforce(diags: List[Diagnostic], where: str, level: Optional[int] = None):
    """Apply the FLAGS_check_programs policy to a diagnostic list.

    level 0 (or empty diags): no-op. level>=1: each diagnostic becomes a
    Python warning. level>=2: error-severity findings raise
    ``ProgramVerificationError`` (after warning the rest)."""
    if level is None:
        level = int(_flags.flag("check_programs"))
    if level <= 0 or not diags:
        return diags
    errors = [d for d in diags if d.severity >= Severity.ERROR]
    for d in diags:
        warnings.warn(f"[{where}] {d}", stacklevel=3)
    if level >= 2 and errors:
        err = ProgramVerificationError(
            f"{where}: program verification failed with "
            f"{len(errors)} error-severity diagnostic(s):\n"
            + "\n".join(f"  {d}" for d in errors),
            diags,
        )
        try:
            from ..profiler import trace as _trace

            _trace.dump_postmortem(
                "verification_failed", exc=err, where=where,
                diagnostics=[str(d) for d in errors],
            )
        except Exception:
            pass  # the verdict must surface even if the dump fails
        raise err
    return diags


from . import passes as _builtin_passes  # noqa: E402,F401  (registers the suite)
from . import memory  # noqa: E402  (registers memory_budget / donation_safety)
from . import plan  # noqa: E402  (remat planner over the liveness estimates)
from . import sharding  # noqa: E402  (registers collective_cost / resharding_lint)
from . import equivalence  # noqa: E402  (registers the equivalence pass)

__all__ += ["memory", "plan", "sharding", "equivalence"]
