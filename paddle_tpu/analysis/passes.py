"""Built-in analysis passes over the inlined flat-op IR.

Reference analogue: fluid/framework/ir pass suite (identity_scale_op_clean,
delete_dropout_op, transpose folding in transfer_layout_elim_pass, the
is_test/AMP audits) plus the operators' InferDtype checks — reimplemented as
jaxpr-level lints. Each pass is ``fn(ctx) -> List[Diagnostic]`` registered by
name; severity policy:

  ERROR   — will produce wrong numbers or fail on TPU (f64 upcast,
            unguarded log),
  WARNING — probably a bug or a real perf hazard (dead op, redundant pair,
            fp16 long-axis sum, possible div-by-zero),
  INFO    — worth knowing, often benign (fusable transpose pair, bf16
            accumulation note).
"""
from __future__ import annotations

from typing import List

import jax
import numpy as np

from . import (
    Context,
    Diagnostic,
    Severity,
    atom_dtype,
    atom_is_weak,
    atom_shape,
    register_pass,
    scalar_const,
    _as_open,
    _sub_jaxprs,
)

_F64 = np.dtype(np.float64)
_F32 = np.dtype(np.float32)
_F16 = np.dtype(np.float16)
_BF16 = np.dtype("bfloat16") if hasattr(np, "dtype") else None
try:
    _BF16 = np.dtype(jax.numpy.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None

_LOW_PRECISION = {d for d in (_F16, _BF16) if d is not None}


def _is_float(dt):
    # jnp.issubdtype, not np: bfloat16/float8 are ml_dtypes extensions that
    # numpy's floating hierarchy does not know about
    try:
        return dt is not None and jax.numpy.issubdtype(dt, jax.numpy.floating)
    except TypeError:
        return False


def _is_real(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _float_dtypes(op):
    out = []
    for v in op.outvars:
        dt = atom_dtype(v)
        if _is_float(dt):
            out.append(dt)
    return out


# ---------------------------------------------------------------------------
# 1. shape/dtype verifier
# ---------------------------------------------------------------------------
_NARROW_FLOATS = {np.dtype(np.float32), _F16} | ({_BF16} if _BF16 else set())


@register_pass("dtype_check")
def dtype_check(ctx: Context) -> List[Diagnostic]:
    diags = []
    # -- silent float64 upcast: TPUs have no native f64. Flag the upcast
    # POINT — an op where a narrower float input becomes a non-weak f64
    # output (usually a numpy float64 scalar/array promotion). f64 derived
    # purely from integer bits (the RNG uniform's bitcast trick) or from
    # values that were already f64 is framework lowering, not an upcast.
    for op in ctx.ops:
        if not any(atom_dtype(a) in _NARROW_FLOATS for a in op.invars):
            continue
        for v in op.outvars:
            if atom_dtype(v) == _F64 and not atom_is_weak(v):
                diags.append(Diagnostic(
                    Severity.ERROR, "dtype_check", op.path,
                    "silent float64 upcast: "
                    f"{atom_dtype(op.invars[0])} input becomes float64",
                    hint="cast to float32/bfloat16 — check numpy float64 "
                         "scalars/arrays entering the graph and "
                         "jax_enable_x64",
                    shapes=(atom_shape(v),), dtypes=("float64",),
                ))
                break  # one diagnostic per op is enough

    # -- AMP bf16/f32 mixing audit: matmul/conv compute split across float
    # widths in one program means the autocast policy is not being applied
    # consistently (some casts will dominate step time, some accuracy)
    heavy = [op for op in ctx.ops
             if op.name in ("dot_general", "conv_general_dilated")]
    widths = {}
    for op in heavy:
        for dt in _float_dtypes(op):
            widths.setdefault(dt, op)
    low = [d for d in widths if d in _LOW_PRECISION]
    if low and _F32 in widths:
        lo = low[0]
        diags.append(Diagnostic(
            Severity.WARNING, "dtype_check", widths[_F32].path,
            f"mixed-precision compute: both {lo} and float32 "
            "matmul/conv ops in one program",
            hint="run the model under paddle.amp.auto_cast (O1/O2) or cast "
                 "weights/inputs consistently; stray f32 matmuls forfeit "
                 "most of the AMP speedup",
            dtypes=(str(lo), "float32"),
        ))

    # -- feed dtype mismatch: a float feed whose every use first converts it
    # to another float width was declared with the wrong dtype
    for invar, (kind, name) in ctx.invar_roles():
        if kind != "feed":
            continue
        dt = atom_dtype(invar)
        if not _is_float(dt):
            continue
        consumers = [op for op in ctx.ops if invar in op.invars]
        if not consumers:
            continue
        casts = {
            np.dtype(op.params["new_dtype"])
            for op in consumers
            if op.name == "convert_element_type"
        }
        if len(casts) == 1 and len(consumers) == len(
            [op for op in consumers if op.name == "convert_element_type"]
        ):
            (target,) = casts
            if target != dt and _is_float(target):
                diags.append(Diagnostic(
                    Severity.WARNING, "dtype_check", f"feed:{name}",
                    f"feed '{name}' declared {dt} but every use first casts "
                    f"it to {target}",
                    hint=f"declare the feed as {target} (or drop the casts) "
                         "to avoid a per-step convert",
                    dtypes=(str(dt), str(target)),
                ))
    return diags


# ---------------------------------------------------------------------------
# 2. dead code / unused feeds / unused parameters
# ---------------------------------------------------------------------------
def _eqn_label(eqn):
    """Human-readable primitive name(s) for a (possibly call-like) eqn."""
    kind, subs = _sub_jaxprs(eqn)
    if kind == "call":
        sub_open, _ = _as_open(subs[0])
        names = [e.primitive.name for e in sub_open.eqns
                 if e.primitive.name != "convert_element_type"]
        if not names:
            names = [e.primitive.name for e in sub_open.eqns]
        if len(names) == 1:
            inner_eqn = [e for e in sub_open.eqns
                         if e.primitive.name == names[0]][0]
            return _eqn_label(inner_eqn)
        if names:
            return "+".join(names[:3]) + ("…" if len(names) > 3 else "")
    return eqn.primitive.name


def _dead_eqns(open_jaxpr, path, acc, index_base=0):
    live = {v for v in open_jaxpr.outvars if isinstance(v, jax.core.Var)}
    status = []
    for eqn in reversed(open_jaxpr.eqns):
        is_live = bool(getattr(eqn, "effects", None)) or any(
            not isinstance(ov, jax.core.DropVar) and ov in live
            for ov in eqn.outvars
        )
        status.append((eqn, is_live))
        if is_live:
            live.update(v for v in eqn.invars if isinstance(v, jax.core.Var))
    for i, (eqn, is_live) in enumerate(reversed(status)):
        here = f"{path}eqn[{i}]"
        if not is_live:
            # zero-output equations are framework no-ops (XLA erases them),
            # not user defects — only value-producing dead ops are findings
            # (a fully-unused output shows up as a DropVar, which still
            # counts: the computation itself is the waste)
            if len(eqn.outvars) > 0:
                acc.append((eqn, here))
        else:
            kind, subs = _sub_jaxprs(eqn)
            for si, sub in enumerate(subs):
                sub_open, _ = _as_open(sub)
                tag = eqn.primitive.name + (str(si) if len(subs) > 1 else "")
                _dead_eqns(sub_open, f"{here}/{tag}/", acc)


@register_pass("dead_code")
def dead_code(ctx: Context) -> List[Diagnostic]:
    diags = []
    dead = []
    _dead_eqns(ctx.jaxpr, "", dead)
    for eqn, path in dead:
        shapes = tuple(tuple(getattr(v.aval, "shape", ())) for v in eqn.outvars)
        diags.append(Diagnostic(
            Severity.WARNING, "dead_code", f"{path} {_eqn_label(eqn)}",
            "dead op: results are never used",
            hint="remove the computation (it still costs compile time, and "
                 "under eager dispatch it runs)",
            shapes=shapes,
        ))
    used = ctx.used_atoms()
    for invar, (kind, name) in ctx.invar_roles():
        if invar in used:
            continue
        if kind == "feed":
            diags.append(Diagnostic(
                Severity.WARNING, "dead_code", f"feed:{name}",
                f"unused feed '{name}': declared but never consumed",
                hint="drop the static.data declaration or wire it into the "
                     "program",
                shapes=(atom_shape(invar),),
            ))
        elif kind == "param":
            diags.append(Diagnostic(
                Severity.WARNING, "dead_code", f"param:{name}",
                f"unused parameter '{name}': it will train as dead weight",
                hint="delete the parameter or stop passing it to the "
                     "optimizer",
                shapes=(atom_shape(invar),),
            ))
        elif kind == "buffer":
            diags.append(Diagnostic(
                Severity.INFO, "dead_code", f"buffer:{name}",
                f"unused buffer '{name}'",
                shapes=(atom_shape(invar),),
            ))
    return diags


# ---------------------------------------------------------------------------
# 3. redundant-op patterns
# ---------------------------------------------------------------------------
def _perm_compose(p1, p2):
    # result of transpose(transpose(x, p1), p2)
    return tuple(p1[i] for i in p2)


def _from_rng(atom, producers, depth=12):
    """True when `atom` derives from raw random bits / bitcasts — arithmetic
    there is framework RNG lowering (uniform = bits*(hi-lo)+lo), not user
    code, and not worth a lint."""
    stack = [atom]
    seen = 0
    while stack and seen < depth:
        a = stack.pop()
        seen += 1
        op = producers.get(a)
        if op is None:
            continue
        if op.name.startswith("random_") or op.name in (
            "bitcast_convert_type", "threefry2x32",
        ):
            return True
        stack.extend(a for a in op.invars if not isinstance(a, jax.core.Literal))
    return False


@register_pass("redundant_ops")
def redundant_ops(ctx: Context) -> List[Diagnostic]:
    diags = []
    prod = ctx.producers
    for op in ctx.ops:
        if op.name == "transpose":
            p = prod.get(op.invars[0])
            if p is not None and p.name == "transpose":
                perm = _perm_compose(
                    tuple(p.params["permutation"]),
                    tuple(op.params["permutation"]),
                )
                if perm == tuple(range(len(perm))):
                    diags.append(Diagnostic(
                        Severity.WARNING, "redundant_ops", op.path,
                        "transpose∘transpose cancels out to identity",
                        hint="remove both transposes",
                        shapes=(atom_shape(op.invars[0]),),
                    ))
                else:
                    diags.append(Diagnostic(
                        Severity.INFO, "redundant_ops", op.path,
                        "back-to-back transposes",
                        hint=f"fuse into one transpose with perm={list(perm)}",
                    ))
        elif op.name in ("mul", "add", "sub", "div"):
            checks = {
                "mul": ((0, 1.0), (1, 1.0)),
                "add": ((0, 0.0), (1, 0.0)),
                "sub": ((1, 0.0),),
                "div": ((1, 1.0),),
            }[op.name]
            for idx, ident in checks:
                if idx >= len(op.invars):
                    continue
                v = scalar_const(op.invars[idx], prod)
                if _is_real(v) and float(v) == ident:
                    other = op.invars[1 - idx]
                    # const∘const is a compile-time expression XLA folds for
                    # free, and arithmetic on raw RNG bits is the uniform
                    # lowering — neither is a user-level finding
                    if scalar_const(other, prod) is not None:
                        break
                    if _from_rng(other, prod):
                        break
                    expr = {"mul": "x*1", "add": "x+0", "sub": "x-0",
                            "div": "x/1"}[op.name]
                    diags.append(Diagnostic(
                        Severity.WARNING, "redundant_ops", op.path,
                        f"identity arithmetic: {expr} is a no-op",
                        hint="drop the op (likely a stale scale/bias or a "
                             "disabled branch left in the graph)",
                        shapes=(atom_shape(op.outvars[0]),),
                    ))
                    break
        elif op.name in ("reduce_sum", "reduce_max", "reduce_min",
                         "reduce_prod"):
            p = prod.get(op.invars[0])
            if p is not None and p.name == "broadcast_in_dim":
                in_shape = atom_shape(p.invars[0])
                out_shape = tuple(p.params["shape"])
                bdims = tuple(p.params["broadcast_dimensions"])
                expanded = {
                    d for d in range(len(out_shape))
                    if d not in bdims
                    or in_shape[bdims.index(d)] != out_shape[d]
                }
                hit = expanded & set(op.params.get("axes", ()))
                if hit:
                    factor = int(np.prod([out_shape[d] for d in hit]))
                    diags.append(Diagnostic(
                        Severity.WARNING, "redundant_ops", op.path,
                        "broadcast-then-reduce: materializes and reduces "
                        f"{factor}× redundant data",
                        hint="reduce before broadcasting, or express the "
                             "contraction as matmul/einsum",
                        shapes=(in_shape, out_shape),
                    ))
        elif op.name == "log":
            p = prod.get(op.invars[0])
            if p is not None and p.name == "div":
                pn = prod.get(p.invars[0])
                if pn is not None and pn.name == "exp":
                    diags.append(Diagnostic(
                        Severity.WARNING, "redundant_ops", op.path,
                        "log(softmax(x)) computed as two ops",
                        hint="use F.log_softmax: one fused op, and it cannot "
                             "underflow to log(0) = -inf",
                    ))
        elif op.name in ("psum", "psum2") and \
                getattr(ctx, "mesh_axes", None) is None:
            # collective idioms on plain contexts; a mesh-scoped context
            # defers to resharding_lint (analysis.sharding) so the full
            # suite never reports one defect twice
            p = prod.get(op.invars[0]) if op.invars else None
            if p is not None and p.name in ("psum", "psum2"):
                a0 = set(_coll_axis_names(op.params))
                a1 = set(_coll_axis_names(p.params))
                # psum(psum(x, 'a'), 'b') is the legitimate staged two-axis
                # reduction — only the SAME axis set is redundant
                if a0 and a0 == a1:
                    diags.append(Diagnostic(
                        Severity.WARNING, "redundant_ops", op.path,
                        f"psum∘psum over the same axis {sorted(a0)}: the "
                        "second all-reduce multiplies by the group size and "
                        "doubles the wire traffic",
                        hint="reduce once (or psum(x, ('a','b')) for one "
                             "fused all-reduce over both axes)",
                        shapes=(atom_shape(op.invars[0]),),
                    ))
        elif op.name in ("slice", "dynamic_slice", "squeeze") and \
                getattr(ctx, "mesh_axes", None) is None:
            p = prod.get(op.invars[0]) if op.invars else None
            if p is not None and p.name == "all_gather" and \
                    atom_shape(op.outvars[0]) == atom_shape(p.invars[0]):
                diags.append(Diagnostic(
                    Severity.WARNING, "redundant_ops", op.path,
                    "all_gather immediately sliced back to the local shard: "
                    "a full-axis round trip that ends where it started",
                    hint="drop the gather (the shard is already local) or "
                         "keep the gathered value if other shards are read",
                    shapes=(atom_shape(p.invars[0]),),
                ))
    return diags


def _coll_axis_names(params):
    ax = params.get("axes", params.get("axis_name"))
    if ax is None:
        return ()
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


# ---------------------------------------------------------------------------
# 4. numerical-hazard lint
# ---------------------------------------------------------------------------
# ops that preserve the sign/positivity property we are chasing
_CHAIN_PASSTHROUGH = {
    "broadcast_in_dim", "reshape", "convert_element_type", "stop_gradient",
    "transpose", "squeeze", "expand_dims", "copy", "slice", "dynamic_slice",
    "concatenate", "reduce_sum", "min", "reduce_window_sum",
    # sqrt preserves positivity, so x/sqrt(var+eps) chases through to +eps
    "sqrt", "rsqrt",
}
# ops whose output is strictly positive (guard log/div) given any input
_POSITIVE = {"exp", "logistic"}
# ops whose output is non-negative (guard sqrt)
_NONNEG = {"abs", "square"} | _POSITIVE


def _guarded(atom, ctx, nonneg_ok=False, depth=8):
    """Best-effort proof that `atom` is positive (or ≥0 when nonneg_ok):
    chases the producer chain through shape/convert ops looking for a
    guarding op (clip/max with a positive floor, +eps, exp/sigmoid, |x|,
    x², even powers)."""
    prod = ctx.producers
    seen = 0
    stack = [atom]
    while stack and seen < depth:
        a = stack.pop()
        seen += 1
        v = scalar_const(a, prod)
        if v is not None:
            if _is_real(v) and (v > 0 or (nonneg_ok and v >= 0)):
                return True
            continue
        op = prod.get(a)
        if op is None:
            continue
        if op.name in _POSITIVE:
            return True
        if nonneg_ok and op.name in _NONNEG:
            return True
        if op.name == "max":  # clip floor: max(c, x) with c > 0 (≥ 0)
            for o in op.invars:
                c = scalar_const(o, prod)
                if _is_real(c) and (c > 0 or (nonneg_ok and c >= 0)):
                    return True
            stack.extend(op.invars)  # max of guarded values is guarded
        elif op.name == "add":  # x + eps heuristic (eps a positive scalar)
            for o in op.invars:
                c = scalar_const(o, prod)
                if _is_real(c) and c > 0:
                    return True
        elif op.name == "integer_pow":
            if int(op.params.get("y", 1)) % 2 == 0 and nonneg_ok:
                return True
        elif op.name == "mul":  # x*x is ≥ 0
            if nonneg_ok and len(op.invars) == 2 and \
                    op.invars[0] is op.invars[1]:
                return True
        elif op.name == "select_n" and len(op.invars) == 3:
            # the explicit zero-replacement guard — where(x == 0, c, x)
            # with c > 0 (the safe-softmax / flash-attention idiom):
            # the zero case is replaced by a positive constant and every
            # other case is x itself, so the result never hits zero
            pred, c0, c1 = op.invars
            try:
                pop = prod.get(pred)
            except TypeError:  # Literal predicate
                pop = None
            if pop is not None and pop.name == "eq":
                for const_case, x_case in ((c0, c1), (c1, c0)):
                    c = scalar_const(const_case, prod)
                    if not (_is_real(c) and c > 0):
                        continue
                    cmp = [scalar_const(o, prod) for o in pop.invars]
                    if any(v == 0 for v in cmp if _is_real(v)) and \
                            any(o is x_case for o in pop.invars):
                        return True
        elif op.name in _CHAIN_PASSTHROUGH:
            stack.append(op.invars[0])
    return False


@register_pass("numeric_hazards")
def numeric_hazards(ctx: Context) -> List[Diagnostic]:
    diags = []
    roles = dict(ctx.invar_roles())
    for op in ctx.ops:
        if op.name == "log":
            if not _guarded(op.invars[0], ctx):
                diags.append(Diagnostic(
                    Severity.ERROR, "numeric_hazards", op.path,
                    "unguarded log: operand can reach 0 or go negative "
                    "(NaN/-inf)",
                    hint="clip first (paddle.log(paddle.clip(x, min=eps))), "
                         "or use paddle.log1p / F.log_softmax",
                    shapes=(atom_shape(op.invars[0]),),
                    dtypes=(str(atom_dtype(op.invars[0])),),
                ))
        elif op.name == "div":
            if len(op.invars) > 1:
                den = op.invars[1]
                c = scalar_const(den, ctx.producers)
                if c is not None and c != 0:
                    continue
                if not _guarded(den, ctx):
                    diags.append(Diagnostic(
                        Severity.WARNING, "numeric_hazards", op.path,
                        "possible division by zero: denominator has no "
                        "positivity guard",
                        hint="add an epsilon (x / (d + eps)) or clip the "
                             "denominator",
                        shapes=(atom_shape(den),),
                    ))
        elif op.name in ("sqrt", "rsqrt"):
            if not _guarded(op.invars[0], ctx, nonneg_ok=(op.name == "sqrt")):
                diags.append(Diagnostic(
                    Severity.WARNING, "numeric_hazards", op.path,
                    f"unguarded {op.name}: negative input gives NaN"
                    + ("" if op.name == "sqrt" else ", zero gives inf"),
                    hint="add an epsilon under the root "
                         f"({op.name}(x + eps)) or clip to ≥ 0",
                    shapes=(atom_shape(op.invars[0]),),
                ))
        elif op.name == "exp":
            a = op.invars[0]
            if a in roles and roles[a][0] in ("feed", "arg"):
                diags.append(Diagnostic(
                    Severity.WARNING, "numeric_hazards", op.path,
                    "exp applied directly to a raw input: overflows to inf "
                    "beyond ~88 (f32) / ~11 (f16)",
                    hint="normalize first (subtract the max, as softmax "
                         "does) or clip the input range",
                    shapes=(atom_shape(a),),
                ))
        elif op.name in ("reduce_sum", "reduce_prod", "cumsum"):
            dt = atom_dtype(op.invars[0])
            if dt not in _LOW_PRECISION:
                continue
            shape = atom_shape(op.invars[0])
            axes = op.params.get("axes", ())
            if op.name == "cumsum":
                axes = (op.params.get("axis", 0),)
            n = int(np.prod([shape[a] for a in axes])) if axes else 1
            if n > 2048:
                sev = Severity.WARNING if dt == _F16 else Severity.INFO
                why = ("float16 saturates at 65504"
                       if dt == _F16 else
                       "bfloat16 has an 8-bit mantissa")
                diags.append(Diagnostic(
                    sev, "numeric_hazards", op.path,
                    f"{dt} reduction over {n} elements: {why}, long-axis "
                    "accumulation loses precision",
                    hint="accumulate in float32: x.astype('float32')"
                         ".sum(...).astype(x.dtype)",
                    shapes=(shape,), dtypes=(str(dt),),
                ))
    return diags


# ---------------------------------------------------------------------------
# 5. program/launch budget (reuses the PR 1 dispatch counters)
# ---------------------------------------------------------------------------
@register_pass("launch_budget")
def launch_budget(ctx: Context) -> List[Diagnostic]:
    if not ctx.counters:
        return []  # only meaningful when a counter snapshot is provided
    c = ctx.counters
    # whole-step capture (FLAGS_eager_step_capture) tightens the budget: a
    # captured steady-state step is ONE donated XLA program, not three, and
    # each accumulate-only microstep of a captured k-step gradient-
    # accumulation cycle replays as one captured program (counted in
    # capture_accum_replays). The auto budget is therefore one program per
    # replay in the measured window — a k-cycle window legitimately
    # launches k captured programs, and an accumulation loop under
    # FLAGS_check_programs must not warn spuriously.
    replays = int(c.get("capture_replays", 0))
    accum_replays = int(c.get("capture_accum_replays", 0))
    captured = replays > 0 or accum_replays > 0
    if ctx.budget is not None:
        budget = ctx.budget
    else:
        budget = (replays + accum_replays) if captured else 3
    diags = []
    programs = int(c.get("programs", 0))
    if programs > budget:
        parts = ", ".join(
            f"{k.removesuffix('_programs')}={c[k]}"
            for k in ("op_programs", "segment_programs", "backward_programs",
                      "optimizer_programs", "captured_programs")
            if c.get(k)
        )
        what = (
            "one captured program per update step / accumulate microstep"
            if captured
            else "fused forward + compiled-tape backward + fused optimizer"
        )
        diags.append(Diagnostic(
            Severity.WARNING, "launch_budget", "step",
            f"step launched {programs} device programs "
            f"(budget {budget}: {what}); breakdown: {parts}",
            hint="enable FLAGS_eager_lazy_dispatch, keep data-dependent "
                 "(jit=False) ops out of the hot loop, and check "
                 "flush_reasons in paddle.profiler.dispatch_counters()",
        ))
    if captured and programs <= budget:
        what_ran = (
            "each microstep of the accumulation cycle replayed as one "
            "captured XLA program (update step donated)"
            if accum_replays
            else "the step replayed as 1 XLA program with parameters and "
                 "optimizer state donated in place"
        )
        diags.append(Diagnostic(
            Severity.INFO, "launch_budget", "step",
            f"whole-step capture active: {what_ran} "
            f"(capture_replays={replays}"
            + (f", capture_accum_replays={accum_replays}" if accum_replays
               else "")
            + ")",
        ))
    fallbacks = int(c.get("capture_fallbacks", 0))
    if fallbacks > 0:
        reasons = c.get("capture_fallback_reasons") or {}
        parts = ", ".join(f"{k}={v}" for k, v in sorted(reasons.items()))
        diags.append(Diagnostic(
            Severity.WARNING, "launch_budget", "step",
            f"step fell back out of whole-step capture {fallbacks} time(s)"
            + (f" ({parts})" if parts else ""),
            # built-in grad clipping and k-step gradient accumulation are
            # CAPTURABLE patterns now — they no longer belong on this
            # permanent-bailout list (only custom clip subclasses do)
            hint="a steady-state step keeps capture only when its signature "
                 "is stable: avoid per-step shape/scalar changes, tensor "
                 "hooks, retain_graph/create_graph, custom grad-clip "
                 "subclasses (the built-in ClipGradBy* configs capture "
                 "fine), irregular accumulation cycles, and reads of .grad "
                 "or pending tensors between backward() and "
                 "optimizer.step()",
        ))
    if int(c.get("segment_cache_misses", 0)) > 0:
        diags.append(Diagnostic(
            Severity.INFO, "launch_budget", "step",
            f"steady-state step still compiled "
            f"{c['segment_cache_misses']} new segment(s)",
            hint="unstable segment signatures (varying shapes/scalars) "
                 "defeat the segment cache — check flush_reasons",
        ))
    return diags


# ---------------------------------------------------------------------------
# 6. determinism lint — the static twin of the bitwise guarantees the
# elastic resharding contract (distributed.fleet.elastic) depends on:
# per-replica runs must be bitwise reproducible, and cross-replica
# reductions must be world-size invariant when world sizes stay powers of
# two (deterministic_tree_sum's documented invariant)
# ---------------------------------------------------------------------------
# scatters that COMBINE duplicate-index updates (min/max are associative and
# commutative, so their accumulation order cannot change the result)
_ACCUM_SCATTERS = {"scatter-add", "scatter-mul"}
# primitives whose results leave the deterministic traced world: host
# callbacks observe wall-clock / host iteration order, so a replay is not
# bitwise bound to the original run
_CALLBACK_PRIMS = {"pure_callback", "io_callback", "callback",
                   "outside_call", "host_callback_call"}
# the sampler core: ops that consume a PRNG key atom and emit bits — two
# samplers fed the SAME key atom draw identical streams
_RNG_CONSUMERS = {"random_bits", "threefry2x32"}
# 1:1 key plumbing, chased through when resolving a sampler's key root.
# Key-DERIVING ops (random_fold_in, random_split, random_seed) deliberately
# stop the chase: their outputs are NEW keys, and conflating them would
# flag every split subkey pair as a reuse
_KEY_PLUMBING = {"random_wrap", "random_unwrap"}


def _key_root(atom, producers, depth=8):
    while depth > 0:
        if isinstance(atom, jax.core.Literal):
            return atom
        op = producers.get(atom)
        if op is None or op.name not in _KEY_PLUMBING or not op.invars:
            return atom
        atom = op.invars[0]
        depth -= 1
    return atom


def _index_root(atom, producers, depth=12):
    """Chase an index tensor through shape/convert plumbing to the value
    that actually carries the indices."""
    while depth > 0:
        if isinstance(atom, jax.core.Literal):
            return atom
        op = producers.get(atom)
        if op is None or op.name not in _CHAIN_PASSTHROUGH or not op.invars:
            return atom
        atom = op.invars[0]
        depth -= 1
    return atom


def _indices_provably_unique(root, producers):
    """True when the index values cannot contain duplicates: an iota (or a
    compile-time constant whose values are distinct)."""
    op = producers.get(root) if not isinstance(
        root, jax.core.Literal) else None
    if op is not None and op.name == "iota":
        return True
    val = getattr(root, "val", None)  # Literal / ConstAtom
    if val is not None:
        try:
            arr = np.asarray(val)
            return arr.size == np.unique(arr).size
        except Exception:
            return False
    return False


@register_pass("determinism")
def determinism(ctx: Context) -> List[Diagnostic]:
    from .sharding import _axis_sizes_from_ops

    diags = []
    prod = ctx.producers
    # mesh-scoped contexts carry axis sizes; a plain Context analyzing a
    # shard_map-bearing program reads them off the shard_map mesh params
    axis_sizes = getattr(ctx, "mesh_axes", None) \
        or _axis_sizes_from_ops(ctx.ops)

    # index roots of every gather in the program: a float scatter-add over
    # the SAME index root is autodiff's gather transpose (embedding /
    # take_along_axis gradients) — XLA combines its duplicate updates in a
    # fixed order per compilation, and the whole-step parity certificates
    # (analysis.equivalence) already bind it bitwise to the eager path, so
    # it is not a user-facing hazard
    gather_roots = set()
    for op in ctx.ops:
        if op.name == "gather" and len(op.invars) >= 2:
            r = _index_root(op.invars[1], prod)
            if not isinstance(r, jax.core.Literal):
                gather_roots.add(id(r))

    key_users = {}
    for op in ctx.ops:
        if op.name in _ACCUM_SCATTERS and len(op.invars) >= 3:
            dt = atom_dtype(op.outvars[0])
            if not _is_float(dt):
                continue  # integer accumulation is exact in any order
            if op.params.get("unique_indices"):
                continue  # caller promised no duplicates
            root = _index_root(op.invars[1], prod)
            if _indices_provably_unique(root, prod):
                continue
            if not isinstance(root, jax.core.Literal) \
                    and id(root) in gather_roots:
                continue  # autodiff gather transpose (see above)
            diags.append(Diagnostic(
                Severity.WARNING, "determinism", op.path,
                f"float {op.name} with potentially-duplicate indices: the "
                "order duplicate updates combine in is "
                "implementation-defined, so results need not be bitwise "
                "reproducible across backends/compilations",
                hint="pass unique_indices=True if the indices are provably "
                     "unique, accumulate in int/f64 and cast, or sort "
                     "indices first (segment_sum over sorted ids)",
                shapes=(atom_shape(op.outvars[0]),),
                dtypes=(str(dt),),
            ))
        elif op.name in ("psum", "psum2"):
            dt = atom_dtype(op.outvars[0]) if op.outvars else None
            if not _is_float(dt):
                continue
            names = _coll_axis_names(op.params)
            n = 1
            for a in names:
                n *= int(axis_sizes.get(a, 1))
            if n > 1 and (n & (n - 1)) != 0:
                diags.append(Diagnostic(
                    Severity.WARNING, "determinism", op.path,
                    f"cross-replica float reduction over a group of {n} "
                    f"(axes {list(names)}): a non-power-of-two group has no "
                    "balanced reduction tree, so the result is not bitwise "
                    "invariant across world sizes",
                    hint="keep reduction group sizes powers of two, or "
                         "route host-side re-reductions through "
                         "deterministic_tree_sum "
                         "(distributed.fleet.elastic), whose pairwise tree "
                         "is world-size invariant for power-of-two counts",
                    shapes=(atom_shape(op.outvars[0]),),
                    dtypes=(str(dt),),
                ))
        elif op.name in _RNG_CONSUMERS and op.invars:
            k = _key_root(op.invars[0], prod)
            if not isinstance(k, jax.core.Literal):
                key_users.setdefault(id(k), []).append(op)
        elif op.name in _CALLBACK_PRIMS:
            diags.append(Diagnostic(
                Severity.WARNING, "determinism", op.path,
                "host callback escapes the traced program: its result can "
                "depend on wall-clock time or host iteration order, so a "
                "replay is not bitwise bound to the original run",
                hint="move the computation into the traced program, or "
                     "accept that this step is unreproducible and exclude "
                     "it from parity checks",
            ))

    for ops in key_users.values():
        if len(ops) < 2:
            continue
        first = ops[0]
        for op in ops[1:]:
            diags.append(Diagnostic(
                Severity.WARNING, "determinism", op.path,
                f"PRNG key reused: the same key feeds {first.path} and "
                f"{op.path}, which therefore draw IDENTICAL random streams",
                hint="split or fold_in the key per consumer "
                     "(jax.random.split / paddle.seed threading); reused "
                     "keys silently correlate dropout masks and init draws",
            ))
    return diags
