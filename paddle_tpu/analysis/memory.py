"""Static memory planner: liveness, peak-HBM estimation & donation safety.

Reference analogue: the reference treats memory as a first-class subsystem
(AllocatorFacade, best-fit / auto-growth strategies, stream-safe allocation
— paddle/fluid/memory/allocation/). On TPU, XLA's buffer assignment owns
HBM, so the planner's job moves *earlier*: compute, statically on the same
inlined flat-op IR every execution mode funnels through (PR 2), what XLA's
allocator will be asked to hold — per-buffer live ranges, a linear-scan
peak estimate, and whether buffer donation (PR 3's `donate_argnums`) is
actually safe. The liveness arithmetic follows XLA's buffer-liveness
analysis and memory planners like Checkmate (Jain et al., MLSys 2020):

  - every non-literal atom (jaxpr input, closed-over constant, op output)
    is one buffer sized from its aval (shape x dtype itemsize);
  - an op output is born at its op and dies at its last read (or escapes
    with the program outputs); constants live for the program's lifetime;
  - a NON-donated input is caller-owned: its buffer is unavailable for
    reuse for the whole execution. A DONATED input dies entering its last
    read — XLA aliases the buffer onto that op's output (the in-place
    ``p -= lr*g`` update reuse ``donate_argnums`` exists for), so old and
    new values never coexist. This is exactly the HBM saving whole-step
    capture claims, and ``donation_credit_bytes`` quantifies it (peak
    without donation minus peak with donation);
  - peak HBM = max over time of the live-buffer sum. The estimate is an
    *unfused upper bound*: XLA's fusion never materializes more than this,
    and for segment/captured programs (whose op outputs all escape to the
    host framework) it is tight — see MEMORY_PLAN.md for the
    estimated-vs-measured methodology.

Two passes are registered in the PR 2 registry:

  - ``memory_budget``: reports estimated peak HBM (with the top-k largest
    live buffers) and errors when it exceeds ``FLAGS_memory_budget_mb`` or
    the detected device HBM;
  - ``donation_safety``: statically proves/refutes that each donated
    argument position is never aliased by a live external reference —
    returned-unchanged outputs, double-bound donated positions, and (via
    the gc-based ``donated_buffer_alias_diags`` scan wired into the
    whole-step capture replay and ``compile_train_step``) use-after-donate
    patterns like ``state_dict()``/``detach()`` aliases held across steps,
    flagged *before* XLA invalidates the buffer at runtime.

Both stay silent unless configured (a budget set, donation info present,
or device HBM exceeded), so the default ``FLAGS_check_programs`` suites
add no noise.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core import flags as _flags
from . import (
    Context,
    Diagnostic,
    Severity,
    register_pass,
    _SCOPE_PRIMS,
    ConstAtom,
)

__all__ = [
    "BlockPoolPlan",
    "Buffer",
    "MemoryPlan",
    "plan_block_pool",
    "plan_memory",
    "captured_step_plans",
    "device_hbm_bytes",
    "tensor_aliases",
    "donated_buffer_alias_diags",
    "donated_buffer_diags",
    "donation_gate",
    "traced_program_diags",
]

_MB = float(1 << 20)


def _dtype_itemsize(dt) -> int:
    try:
        return int(np.dtype(dt).itemsize)
    except TypeError:
        # jax extended dtypes (PRNG keys wrap uint32[2], float8 wrappers)
        return int(getattr(dt, "itemsize", 8))


def _aval_nbytes(aval) -> int:
    if aval is None:
        return 0
    shape = tuple(getattr(aval, "shape", ()))
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * _dtype_itemsize(dt)


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / _MB:.1f}MB"
    if n >= 1 << 10:
        return f"{n / 1024:.1f}KB"
    return f"{n}B"


@dataclasses.dataclass
class Buffer:
    """One planned buffer: a jaxpr input, closed-over constant, or op
    output, with its (donation-credited) live range over the op timeline
    (born=-1: exists at program entry; dies=n_ops: escapes/held to exit)."""

    kind: str  # "param" | "buffer" | "feed" | "arg" | "const" | "op" | "body"
    name: str  # role name, op path, or const tag
    shape: Tuple
    dtype: str
    nbytes: int
    born: int
    dies: int
    donated: bool = False

    def label(self) -> str:
        return f"{self.kind}:{self.name}" if self.kind != "op" else self.name


class MemoryPlan:
    """Result of the liveness simulation over one program."""

    def __init__(self, buffers, n_ops, peak_bytes, peak_index, peak_op_path,
                 peak_no_donation_bytes):
        self.buffers: List[Buffer] = buffers
        self.n_ops = n_ops
        self.peak_bytes = peak_bytes
        self.peak_index = peak_index  # op timeline position of the peak
        self.peak_op_path = peak_op_path
        self.peak_no_donation_bytes = peak_no_donation_bytes

    @property
    def donation_credit_bytes(self) -> int:
        """HBM the donated inputs free below the no-donation peak — the
        saving buffer donation is worth on this program."""
        return self.peak_no_donation_bytes - self.peak_bytes

    @property
    def input_bytes(self) -> int:
        return sum(b.nbytes for b in self.buffers
                   if b.born < 0 and b.kind != "const")

    @property
    def const_bytes(self) -> int:
        return sum(b.nbytes for b in self.buffers if b.kind == "const")

    @property
    def output_bytes(self) -> int:
        return sum(b.nbytes for b in self.buffers
                   if b.born >= 0 and b.dies >= self.n_ops)

    @property
    def boundary_bytes(self) -> int:
        """Live bytes at program exit: non-donated inputs + constants +
        escaping outputs — what stays resident between launches."""
        return sum(b.nbytes for b in self.live_at(self.n_ops))

    def live_at(self, t: int) -> List[Buffer]:
        return [b for b in self.buffers if b.born <= t <= b.dies]

    def top_live(self, k: int = 5) -> List[Buffer]:
        live = sorted(self.live_at(self.peak_index),
                      key=lambda b: -b.nbytes)
        return live[:k]

    def to_dict(self) -> Dict:
        top = self.top_live(5)
        return {
            "peak_bytes": int(self.peak_bytes),
            "peak_mb": round(self.peak_bytes / _MB, 3),
            "peak_index": int(self.peak_index),
            "peak_op": self.peak_op_path,
            "peak_no_donation_bytes": int(self.peak_no_donation_bytes),
            "donation_credit_bytes": int(self.donation_credit_bytes),
            "input_bytes": int(self.input_bytes),
            "const_bytes": int(self.const_bytes),
            "output_bytes": int(self.output_bytes),
            "boundary_bytes": int(self.boundary_bytes),
            "n_ops": int(self.n_ops),
            "n_buffers": len(self.buffers),
            "top_live": [
                {"name": b.label(), "shape": list(map(int, b.shape)),
                 "dtype": b.dtype, "nbytes": int(b.nbytes),
                 "donated": b.donated}
                for b in top
            ],
        }

    def __repr__(self):
        return (f"<MemoryPlan peak={_fmt_bytes(self.peak_bytes)} "
                f"@{self.peak_op_path or self.peak_index} "
                f"credit={_fmt_bytes(self.donation_credit_bytes)} "
                f"ops={self.n_ops} buffers={len(self.buffers)}>")


def _peak_of(intervals: Sequence[Tuple[int, int, int]], n_ops: int):
    """(peak bytes, peak time) over timeline t in [-1, n_ops] for
    (born, dies, nbytes) intervals (inclusive on both ends)."""
    delta = [0] * (n_ops + 3)
    for born, dies, nb in intervals:
        if dies < born or nb <= 0:
            continue
        delta[born + 1] += nb
        delta[dies + 2] -= nb
    cur, peak, at = 0, 0, -1
    for t in range(-1, n_ops + 1):
        cur += delta[t + 1]
        if cur > peak:
            peak, at = cur, t
    return peak, at


def _scope_extra(op, scope_prefix, scope_peaks) -> int:
    """Transient charge for a control-flow op: the max internal peak among
    body scopes this op could own (body scopes of same-primitive siblings
    share one tag, so the charge is the conservative max)."""
    if op.name not in _SCOPE_PRIMS:
        return 0
    best = 0
    for tag, pk in scope_peaks.items():
        local = tag[len(scope_prefix):] if scope_prefix else tag
        if "/" not in local and local.startswith(op.name):
            best = max(best, pk)
    return best


def _scope_peak(ops, scope, scope_peaks) -> int:
    """Internal peak of one control-flow body scope (approximate: body
    invars live throughout, outputs die at their last in-scope read)."""
    n = len(ops)
    last_use: Dict[int, int] = {}
    avals: Dict[int, int] = {}
    produced = set()
    for op in ops:
        for ov in op.outvars:
            produced.add(id(ov))
    intervals = []
    for i, op in enumerate(ops):
        for a in op.invars:
            if isinstance(a, jax.core.Literal):
                continue
            last_use[id(a)] = i
            avals[id(a)] = _aval_nbytes(getattr(a, "aval", None))
    for aid, die in last_use.items():
        if aid not in produced:  # body input / carried value
            intervals.append((-1, n, avals.get(aid, 0)))
    for i, op in enumerate(ops):
        extra = _scope_extra(op, scope + "/", scope_peaks)
        if extra:
            intervals.append((i, i, extra))
        for ov in op.outvars:
            nb = _aval_nbytes(getattr(ov, "aval", None))
            intervals.append((i, last_use.get(id(ov), i), nb))
    peak, _ = _peak_of(intervals, n)
    return peak


def plan_memory(ctx: Context, donated: Optional[Sequence[int]] = None,
                *, mesh=None, in_specs=None) -> MemoryPlan:
    """Liveness simulation of ``ctx``'s program; ``donated`` overrides the
    context's donated invar-index set (e.g. to compare with/without).

    ``mesh``/``in_specs`` rebuild the context per-shard first (via
    ``analysis.sharding.shard_context``) so every buffer is sized to one
    device's shard and the returned peak is **per device** — the multi-chip
    budget ROADMAP item 1 needs. A context that is already mesh-scoped
    (``ctx.mesh_axes`` set) is planned as-is."""
    if mesh is not None and getattr(ctx, "mesh_axes", None) is None:
        from .sharding import shard_context

        ctx = shard_context(
            ctx.closed, ctx.roles, mesh=mesh, in_specs=in_specs,
            donated=getattr(ctx, "donated", ()),
            source=ctx.source,
            memory_budget_mb=getattr(ctx, "memory_budget_mb", None),
            alias_groups=getattr(ctx, "alias_groups", None),
            alias_refs=getattr(ctx, "alias_refs", None),
        )
    donated_set = set(
        donated if donated is not None else getattr(ctx, "donated", ()) or ()
    )
    by_scope: Dict[str, List] = {}
    for op in ctx.ops:
        by_scope.setdefault(op.scope, []).append(op)
    scope_peaks: Dict[str, int] = {}
    for scope in sorted((s for s in by_scope if s),
                        key=lambda s: -s.count("/")):
        scope_peaks[scope] = _scope_peak(by_scope[scope], scope, scope_peaks)

    top = by_scope.get("", [])
    n = len(top)
    last_use: Dict = {}
    for i, op in enumerate(top):
        for a in op.invars:
            if not isinstance(a, jax.core.Literal):
                last_use[a] = i
    out_set = set()
    for a in getattr(ctx, "out_atoms", ()):
        if not isinstance(a, jax.core.Literal):
            try:
                out_set.add(a)
            except TypeError:
                pass

    buffers: List[Buffer] = []

    def _mk(kind, name, aval, born, dies, donated=False):
        buffers.append(Buffer(
            kind, name, tuple(getattr(aval, "shape", ())),
            str(getattr(aval, "dtype", "?")), _aval_nbytes(aval),
            born, dies, donated,
        ))

    # jaxpr inputs: caller-owned for the whole program unless donated. A
    # donated buffer dies ENTERING its last read: XLA aliases it onto that
    # op's output (the in-place p -= lr*g update reuse donate_argnums
    # exists for), so old and new values never coexist. Never-read donated
    # buffers are freed at program entry — full credit.
    for idx, (invar, (kind, name)) in enumerate(ctx.invar_roles()):
        don = idx in donated_set
        if invar in out_set:
            dies = n
        elif don:
            dies = last_use.get(invar, 0) - 1
        else:
            dies = n
        _mk(kind, name, getattr(invar, "aval", None), -1, dies, don)

    # closed-over constants: baked into the executable, resident
    # throughout. Dedupe by the underlying VALUE — the inliner mints a
    # fresh ConstAtom per inline instance, but a shared inner jaxpr's
    # constant is one buffer no matter how many call sites reference it
    seen_consts = set()
    for op in top:
        for a in op.invars:
            if isinstance(a, ConstAtom) and id(a.val) not in seen_consts:
                seen_consts.add(id(a.val))
                _mk("const", f"const@{op.path}", a.aval, -1, n)

    # op outputs: born at their op, die at the last read / escape with the
    # program outputs. Control-flow ops charge their body's internal peak
    # as a transient during the op itself.
    produced = set()
    for i, op in enumerate(top):
        extra = _scope_extra(op, "", scope_peaks)
        if extra:
            buffers.append(Buffer("body", f"{op.path} body", (), "-",
                                  extra, i, i))
        for oi, ov in enumerate(op.outvars):
            produced.add(ov)
            dies = n if ov in out_set else last_use.get(ov, i)
            suffix = f"#{oi}" if len(op.outvars) > 1 else ""
            _mk("op", op.path + suffix, getattr(ov, "aval", None), i, dies)

    # output positions that are not a fresh op output — input passthroughs,
    # constants, and repeated atoms — each materialize their OWN buffer at
    # exit: an un-donated XLA program copies aliased outputs instead of
    # forwarding the input buffer (measured: jit output arrays are distinct
    # allocations per position, see MEMORY_PLAN.md)
    seen_outs = set()
    for pos, a in enumerate(getattr(ctx, "out_atoms", ())):
        if isinstance(a, jax.core.Literal):
            _mk("out-copy", f"output[{pos}]", getattr(a, "aval", None), n, n)
            continue
        fresh = a in produced and a not in seen_outs
        seen_outs.add(a)
        if not fresh:
            _mk("out-copy", f"output[{pos}]", getattr(a, "aval", None), n, n)

    peak, at = _peak_of([(b.born, b.dies, b.nbytes) for b in buffers], n)
    nodon_peak, _ = _peak_of(
        [(b.born, n if b.donated else b.dies, b.nbytes) for b in buffers], n
    )
    peak_op = top[at].path if 0 <= at < n else ("exit" if at >= n else "entry")
    return MemoryPlan(buffers, n, peak, at, peak_op, nodon_peak)


# ---------------------------------------------------------------------------
# Device HBM detection (budget fallback when no explicit flag is set)
# ---------------------------------------------------------------------------
_hbm_cache: List = [False, None]


def device_hbm_bytes() -> Optional[int]:
    """Accelerator memory capacity of device 0, or None when the backend
    does not report one (CPU runs return None so tests stay quiet).

    Never FORCES backend initialization: a trace-only lint must not grab
    the accelerator (or block on a held libtpu) just to ask its size —
    when no backend is up yet, report None without caching so a later
    call after initialization still probes."""
    if _hbm_cache[0]:
        return _hbm_cache[1]
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return None  # uninitialized — don't init, don't cache
    except Exception:
        pass  # cannot tell — fall through and probe as before
    val = None
    try:
        d = jax.devices()[0]
        if getattr(d, "platform", "") in ("tpu", "gpu"):
            stats = d.memory_stats() or {}
            val = int(stats.get("bytes_limit") or 0) or None
    except Exception:
        val = None
    _hbm_cache[:] = [True, val]
    return val


# ---------------------------------------------------------------------------
# Pass 6: memory_budget
# ---------------------------------------------------------------------------
@register_pass("memory_budget")
def memory_budget(ctx: Context) -> List[Diagnostic]:
    if not getattr(ctx, "ops", None):
        return []
    budget_mb = getattr(ctx, "memory_budget_mb", None)
    if budget_mb is None:
        flagged = float(_flags.flag("memory_budget_mb"))
        budget_mb = flagged if flagged > 0 else None
    donated = tuple(getattr(ctx, "donated", ()) or ())
    hbm = device_hbm_bytes()
    if budget_mb is None and not donated and hbm is None:
        return []  # not configured — stay silent in the default suites

    plan = plan_memory(ctx)
    diags = []
    if budget_mb is not None or donated:
        # the peak report is emitted only when the user configured a budget
        # or donation info is present — a detected device HBM alone gates
        # the OOM error below but must not turn every checked program into
        # a warning under FLAGS_check_programs (stay-silent contract)
        top = plan.top_live(5)
        top_str = ", ".join(
            f"{b.label()} {b.dtype}{list(b.shape)} {_fmt_bytes(b.nbytes)}"
            for b in top
        )
        credit = (
            f"; donation credit {_fmt_bytes(plan.donation_credit_bytes)} "
            f"({len([b for b in plan.buffers if b.donated])} donated buffers)"
            if donated else ""
        )
        # mesh-scoped contexts carry per-shard avals, so the whole plan —
        # peak, inputs, donation credit — is what ONE device holds
        per_dev = (" per device" if getattr(ctx, "mesh_axes", None) else "")
        diags.append(Diagnostic(
            Severity.INFO, "memory_budget",
            plan.peak_op_path
            if 0 <= plan.peak_index < plan.n_ops else "program",
            f"estimated peak HBM{per_dev} {_fmt_bytes(plan.peak_bytes)} "
            f"(inputs {_fmt_bytes(plan.input_bytes)}, consts "
            f"{_fmt_bytes(plan.const_bytes)}, outputs "
            f"{_fmt_bytes(plan.output_bytes)}{credit}); "
            f"largest live: {top_str}",
            shapes=tuple(b.shape for b in top),
            dtypes=tuple(b.dtype for b in top),
            data=plan.to_dict(),
        ))
    per_dev = (" per device" if getattr(ctx, "mesh_axes", None) else "")
    budget_bytes = int(budget_mb * _MB) if budget_mb else None
    if budget_bytes is not None and plan.peak_bytes > budget_bytes:
        diags.append(Diagnostic(
            Severity.ERROR, "memory_budget", "program",
            f"estimated peak HBM{per_dev} {_fmt_bytes(plan.peak_bytes)} exceeds the "
            f"declared budget of {budget_mb:g} MB "
            f"(FLAGS_memory_budget_mb)",
            hint="shrink batch/activation sizes, enable whole-step capture "
                 "donation (FLAGS_eager_capture_donate), or raise the "
                 "budget; the largest live buffers are listed in the "
                 "memory report diagnostic",
            data={"peak_bytes": int(plan.peak_bytes),
                  "budget_mb": float(budget_mb)},
        ))
    if hbm is not None and plan.peak_bytes > hbm:
        diags.append(Diagnostic(
            Severity.ERROR, "memory_budget", "program",
            f"estimated peak HBM {_fmt_bytes(plan.peak_bytes)} exceeds "
            f"device memory ({_fmt_bytes(hbm)}): this program will OOM at "
            "buffer assignment",
            hint="shard the model, shrink the batch, or enable recompute",
            data={"peak_bytes": int(plan.peak_bytes), "hbm_bytes": int(hbm)},
        ))
    return diags


def _use_after_donate_diag(label, holders, source="") -> Diagnostic:
    """The one use-after-donate ERROR, shared by the static pass (caller-
    provided alias_refs) and the runtime gc scan."""
    held = "; ".join(str(h) for h in holders[:3])
    more = f" (+{len(holders) - 3} more)" if len(holders) > 3 else ""
    return Diagnostic(
        Severity.ERROR, "donation_safety", label,
        f"use-after-donate: {len(holders)} live external reference(s) "
        f"alias this donated buffer [{held}{more}]; on TPU/GPU the alias "
        "dies with the donation (state_dict()/detach() held across a "
        "donated step is the classic shape of this bug)",
        hint="copy before holding (alias.clone()), drop the alias before "
             "the step, or set FLAGS_eager_capture_donate=0 to keep "
             "1-program capture without donation",
        source=source,
    )


# ---------------------------------------------------------------------------
# Pass 7: donation_safety
# ---------------------------------------------------------------------------
@register_pass("donation_safety")
def donation_safety(ctx: Context) -> List[Diagnostic]:
    donated = set(getattr(ctx, "donated", ()) or ())
    if not donated:
        return []  # nothing donated — vacuously safe, stay silent
    roles = ctx.invar_roles()
    alias_refs = getattr(ctx, "alias_refs", None) or {}
    alias_groups = getattr(ctx, "alias_groups", None) or []
    out_ids = {id(a) for a in getattr(ctx, "out_atoms", ())}
    last_use = set()
    for op in ctx.ops:
        for a in op.invars:
            last_use.add(id(a))

    diags: List[Diagnostic] = []

    def _name(idx):
        if idx < len(roles):
            kind, name = roles[idx][1]
            return f"{kind}:{name}"
        return f"arg:{idx}"

    for idx in sorted(donated):
        if idx >= len(roles):
            continue
        invar = roles[idx][0]
        if id(invar) in out_ids:
            diags.append(Diagnostic(
                Severity.ERROR, "donation_safety", _name(idx),
                "donated input is returned unchanged: the fetched output "
                "aliases a buffer XLA has already reused",
                hint="drop the passthrough output or remove this position "
                     "from donate_argnums",
                shapes=(tuple(getattr(invar.aval, "shape", ())),),
            ))
        elif id(invar) not in last_use:
            diags.append(Diagnostic(
                Severity.INFO, "donation_safety", _name(idx),
                "donated input is never read: its buffer is freed at "
                "program entry (full donation credit)",
            ))

    for group in alias_groups:
        g = set(group)
        dg = g & donated
        if dg and len(g) > 1:
            names = ", ".join(_name(i) for i in sorted(g))
            diags.append(Diagnostic(
                Severity.ERROR, "donation_safety", _name(min(dg)),
                f"one runtime buffer is bound to {len(g)} argument "
                f"positions ({names}) and at least one of them is donated: "
                "XLA will reuse the buffer while another position still "
                "reads it",
                hint="pass distinct arrays, or exclude the position from "
                     "donation",
            ))

    for idx, holders in sorted(alias_refs.items()):
        if idx not in donated or not holders:
            continue
        diags.append(_use_after_donate_diag(_name(idx), list(holders)))

    if not any(d.severity >= Severity.ERROR for d in diags):
        diags.append(Diagnostic(
            Severity.INFO, "donation_safety", "program",
            f"all {len(donated)} donated argument positions verified: no "
            "escaping outputs, no double-bound buffers, no live external "
            "aliases",
        ))
    return diags


def donation_verdicts(ctx: Context) -> List[Dict[str, object]]:
    """Per-position donation_safety verdicts over ``ctx``'s donated invars.

    One record per donated flat argument position:
    ``{"position", "role", "proven", "diagnostics"}`` — ``proven`` is True
    iff no ERROR-severity donation_safety diagnostic names the position
    (by its ``kind:name`` role label, directly as the diagnostic's op or
    inside a group-alias message). This is the gate the mesh-aware capture
    controller keys donation on — EVERY position must prove, or the
    captured program replays non-donated (capture_donation_fallbacks) —
    and the per-position table ``graph_lint --mesh --json`` prints."""
    from . import run_passes

    donated = sorted(set(getattr(ctx, "donated", ()) or ()))
    diags = [d for d in run_passes(ctx, ["donation_safety"])
             if d.pass_name == "donation_safety"]
    roles = ctx.invar_roles()

    def _name(idx):
        if idx < len(roles):
            kind, name = roles[idx][1]
            return f"{kind}:{name}"
        return f"arg:{idx}"

    out = []
    for idx in donated:
        label = _name(idx)
        errs = [d for d in diags
                if d.severity >= Severity.ERROR
                and (d.op == label or label in (d.message or ""))]
        out.append({
            "position": int(idx),
            "role": label,
            "proven": not errs,
            "diagnostics": [d.message for d in errs],
        })
    return out


# ---------------------------------------------------------------------------
# Runtime alias scan (the compile-time cross-check of the capture path's
# aliased_leaves fallback): enumerate live Tensor objects wrapping an array
# ---------------------------------------------------------------------------
def _scan_tensor_holders(target_ids, exclude=()) -> Dict[int, List[str]]:
    """ONE ``gc.get_objects()`` heap pass: {id(array): [description of live
    Tensor wrapping it]} for every id in ``target_ids`` (a per-buffer
    ``gc.get_referrers`` walk would traverse the heap once per parameter —
    prohibitive for large models under FLAGS_check_programs)."""
    import gc

    from ..core.tensor import Tensor

    ex = {id(t) for t in exclude}
    found: Dict[int, List[str]] = {}
    for obj in gc.get_objects():
        if isinstance(obj, Tensor) and id(obj) not in ex:
            v = getattr(obj, "_value", None)
            if id(v) in target_ids:
                name = getattr(obj, "name", "") or "<unnamed>"
                found.setdefault(id(v), []).append(
                    f"Tensor {name} shape={tuple(getattr(v, 'shape', ()))}"
                )
    return found


def tensor_aliases(arr, exclude=()) -> List[str]:
    """Descriptions of live ``Tensor`` objects (outside ``exclude``) whose
    ``_value`` IS ``arr``. These are exactly the references a buffer
    donation invalidates: ``p.detach()`` results, ``state_dict()`` wrappers,
    saved activations — held across a donated step, they die with it."""
    return _scan_tensor_holders({id(arr)}, exclude).get(id(arr), [])


def donated_buffer_alias_diags(named_arrays, exclude=(),
                               source="captured-step") -> List[Diagnostic]:
    """donation_safety diagnostics for to-be-donated runtime buffers.

    ``named_arrays``: [(label, jax array)] about to be donated;
    ``exclude``: Tensor objects that legitimately own them (the parameters
    themselves). One ERROR per aliased buffer, [] when all are clean.

    One ``gc.get_objects()`` heap pass covers ALL buffers."""
    found = _scan_tensor_holders(
        {id(arr) for _label, arr in named_arrays}, exclude
    )
    diags = []
    for label, arr in named_arrays:
        holders = found.get(id(arr), [])
        if holders:
            diags.append(_use_after_donate_diag(label, holders, source))
    return diags


def donated_buffer_diags(named_arrays, exclude=(),
                         source="captured-step") -> List[Diagnostic]:
    """The full runtime donation-safety scan shared by the whole-step
    capture replay and ``compile_train_step``: duplicate-bound buffers
    (tied weights — one array at two donated positions, which XLA cannot
    donate twice) plus the live-external-alias scan. Error-severity
    findings bump the ``donation_alias_flags`` dispatch counter."""
    by_id: Dict[int, List[str]] = {}
    for label, arr in named_arrays:
        by_id.setdefault(id(arr), []).append(label)
    diags: List[Diagnostic] = []
    for labels in by_id.values():
        if len(labels) > 1:
            diags.append(Diagnostic(
                Severity.ERROR, "donation_safety", labels[0],
                f"one runtime buffer is bound to {len(labels)} donated "
                f"positions ({', '.join(labels)}): XLA cannot donate the "
                "same buffer twice — the second donation reads an "
                "already-reused buffer",
                hint="untie the arrays (clone one), or exclude the shared "
                     "buffer from donation",
                source=source,
            ))
    diags += donated_buffer_alias_diags(named_arrays, exclude=exclude,
                                        source=source)
    if diags:
        from ..core.dispatch import _counters

        _counters["donation_alias_flags"] += len(diags)
    return diags


def donation_gate(params, states, trace_thunk, roles, donated, source,
                  static_diags=None) -> List[Diagnostic]:
    """The one donation-safety gate shared by the whole-step capture replay
    and ``compile_train_step``: runtime scan of the to-be-donated param and
    optimizer-state buffers (duplicates + live external aliases) plus the
    static traced-program passes, then ``enforce`` per
    ``FLAGS_check_programs``. Pass the previous return value as
    ``static_diags`` to reuse the (expensive) static result — it is only
    returned after enforce() succeeds, so a raising verdict is re-proven on
    the next call instead of being disarmed."""
    from . import enforce

    named = [
        (f"param:{getattr(p, 'name', '') or i}", p._value)
        for i, p in enumerate(params)
    ]
    for i, st in enumerate(states):
        for k in sorted(st):
            named.append((f"opt_state:{i}.{k}", st[k]))
    diags = donated_buffer_diags(named, exclude=params, source=source)
    if static_diags is None:
        static_diags = traced_program_diags(trace_thunk, roles, donated,
                                            source)
    enforce(diags + static_diags, where=f"{source} donation")
    return static_diags


def traced_program_diags(trace_thunk, roles, donated,
                         source) -> List[Diagnostic]:
    """Once-per-build static check of a donated program: trace it (no
    compile) and run the memory passes. Tracing failures yield [] — the
    static check must never break the step it audits."""
    from . import run_passes

    try:
        closed = trace_thunk()
        ctx = Context(closed, roles, source, donated=donated)
        return run_passes(ctx, ["memory_budget", "donation_safety"])
    except Exception:
        return []


@dataclasses.dataclass
class BlockPoolPlan:
    """Planner verdict sizing a paged KV block pool (paddle.serving).

    ``num_blocks`` is None when no budget is configured anywhere (flag,
    argument, or detected device HBM) — the caller applies its own default.
    ``overhead_bytes`` is the decode program's estimated peak *excluding*
    the pool itself: weights, activations, the gathered block views, block
    tables. The pool gets whatever the budget leaves."""

    num_blocks: Optional[int]
    block_bytes: int
    budget_bytes: Optional[int]
    overhead_bytes: int
    trace_peak_bytes: int

    @property
    def est_peak_hbm_mb(self) -> float:
        """Estimated peak of the traced decode program (MB)."""
        return self.trace_peak_bytes / _MB

    def pool_bytes(self, num_blocks: Optional[int] = None) -> int:
        n = self.num_blocks if num_blocks is None else num_blocks
        return int(n or 0) * self.block_bytes


def plan_block_pool(trace_thunk, *, block_bytes: int,
                    pool_bytes_in_trace: int = 0,
                    budget_mb: Optional[float] = None,
                    roles: Sequence = (), donated: Sequence[int] = (),
                    source: str = "serving-decode") -> BlockPoolPlan:
    """Size a paged KV block pool against the memory budget — the serving
    half of the ``memory_budget`` pass: trace the decode program once (no
    compile) over a MINIMAL pool, estimate its peak with the liveness
    planner, subtract the minimal pool's own bytes to get the non-pool
    overhead, and floor-divide the remaining budget by the per-block cost.
    The engine then refuses admission past the resulting pool instead of
    letting XLA OOM mid-decode.

    Budget precedence: explicit ``budget_mb`` > FLAGS_memory_budget_mb > the
    detected device HBM; with none of the three, ``num_blocks`` is None.
    Tracing failures fall back to an overhead of 0 (budget // block_bytes)
    rather than breaking engine construction."""
    if budget_mb is None:
        flagged = float(_flags.flag("memory_budget_mb"))
        budget_mb = flagged if flagged > 0 else None
    budget_bytes = int(budget_mb * _MB) if budget_mb is not None else None
    if budget_bytes is None:
        hbm = device_hbm_bytes()
        budget_bytes = int(hbm) if hbm else None

    peak = 0
    try:
        closed = trace_thunk()
        ctx = Context(closed, list(roles), source, donated=tuple(donated))
        peak = plan_memory(ctx, donated=tuple(donated)).peak_bytes
    except Exception:
        peak = int(pool_bytes_in_trace)
    overhead = max(0, int(peak) - int(pool_bytes_in_trace))

    num_blocks: Optional[int] = None
    if budget_bytes is not None:
        num_blocks = max(0, (budget_bytes - overhead) // int(block_bytes))
    return BlockPoolPlan(
        num_blocks=num_blocks,
        block_bytes=int(block_bytes),
        budget_bytes=budget_bytes,
        overhead_bytes=overhead,
        trace_peak_bytes=int(peak),
    )


def captured_step_plans():
    """(donation-credited plan, no-donation plan) of the most recently
    replayed captured whole-step program on this thread, or None — the
    shared recipe behind bench.py's memory trajectory and
    paddle.profiler.measure_programs."""
    from ..core import lazy

    prog = lazy.captured_step_program()
    if prog is None:
        return None
    closed, donated, roles = prog
    ctx = Context(closed, roles, "captured-step")
    return plan_memory(ctx, donated=donated), plan_memory(ctx, donated=())
