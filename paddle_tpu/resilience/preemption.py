"""Preemption-safe training: SIGTERM/SIGINT → finish the in-flight step,
emergency-checkpoint, exit cleanly, resume losing at most one step.

TPU fleets are preemptible by design: the scheduler sends SIGTERM and gives
the process a grace window. The guard's signal handler only sets a flag (so
the in-flight step always runs to completion — or, for a deferred captured
step, resolves through the normal fallback path when the loop flushes); the
training loop then observes the flag at the next step boundary, fires an
emergency AsyncCheckpointer.save, and raises `Preempted` (a SystemExit, so
generic `except Exception` recovery code can't swallow it). On relaunch,
`train_step_range` restores the emergency snapshot and continues from the
next step — the CheckFreq discipline: checkpointing frequency bounds lost
work, and the preemption path bounds it to one step.
"""
from __future__ import annotations

import signal
import threading
from typing import Any, Callable, Dict, Optional

__all__ = ["Preempted", "PreemptionGuard"]


class Preempted(SystemExit):
    """Raised at the step boundary after a preemption signal; carries the
    signal and the last completed step. SystemExit subclass: training loops
    that catch Exception for fault recovery do not accidentally absorb it."""

    def __init__(self, signum: int, step: Optional[int] = None):
        super().__init__(128 + int(signum))
        self.signum = int(signum)
        self.step = step

    def __str__(self):
        name = signal.Signals(self.signum).name
        return f"preempted by {name} (last completed step: {self.step})"


class PreemptionGuard:
    """Installable SIGTERM/SIGINT latch + emergency-checkpoint hook.

    Usage::

        guard = paddle.resilience.PreemptionGuard(checkpointer, state_dict)
        with guard:
            for step in range(n):
                train_one_step()
                guard.step_boundary(step)   # raises Preempted after a signal

    or hand the guard to `paddle.distributed.checkpoint.train_step_range`,
    which wires the boundary check (and the restore on relaunch) for you.
    """

    def __init__(self, checkpointer=None, state_dict: Optional[Dict[str, Any]] = None,
                 signals=None, on_preempt: Optional[Callable[[int], None]] = None):
        self.checkpointer = checkpointer
        self.state_dict = state_dict
        self.signals = tuple(signals or (signal.SIGTERM, signal.SIGINT))
        self.on_preempt = on_preempt
        self.preempted = False
        self.signum: Optional[int] = None
        self._prev = {}
        self._installed = False

    def bind(self, checkpointer, state_dict):
        """Late-bind the emergency-save target (no-op for already-set
        fields) — used by train_step_range/train_epoch_range."""
        if self.checkpointer is None:
            self.checkpointer = checkpointer
        if self.state_dict is None:
            self.state_dict = state_dict

    # -- signal plumbing ----------------------------------------------------
    def _handler(self, signum, frame):
        self.preempted = True
        self.signum = signum
        from ..core import dispatch

        dispatch._counters["preemptions"] += 1

    def install(self):
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self  # signal.signal is main-thread-only; stay passive
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._handler)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev = {}
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- boundary protocol ---------------------------------------------------
    def emergency_save(self, step: int):
        """Flush in-flight lazy/captured work, then make this boundary's
        snapshot durable before the process exits: an in-flight async save
        that already covers the boundary is joined (not redone), anything
        else is superseded by a synchronous save — commits are serialized
        either way, so the LATEST pointer can never name a
        partially-persisted snapshot."""
        from ..core import dispatch, lazy

        # resolve any pending segment or deferred captured backward first:
        # the step either finishes (flush) or rolls back onto the 3-program
        # path (capture abort) — state is consistent before the snapshot
        lazy.flush_if_pending("preemption")
        if self.checkpointer is not None and self.state_dict is not None:
            emergency = getattr(self.checkpointer, "emergency_save", None)
            if emergency is not None:
                emergency(step, self.state_dict)
            else:  # duck-typed checkpointer without the join/supersede path
                self.checkpointer.save(step, self.state_dict)
            self.checkpointer.wait()
            dispatch._counters["emergency_saves"] += 1

    def step_boundary(self, step: int):
        """Call after each completed step; raises Preempted (after the
        emergency save) when a signal arrived during the step."""
        if not self.preempted:
            return
        if self.on_preempt is not None:
            self.on_preempt(step)
        self.emergency_save(step)
        signum = self.signum if self.signum is not None else signal.SIGTERM
        exc = Preempted(signum, step)
        try:
            from ..profiler import trace as _trace

            _trace.emit("preempt", site="guard", step=step, signum=signum)
            # the emergency snapshot is durable by now; the postmortem
            # records what the run looked like at the boundary it exits on
            _trace.dump_postmortem("preempted", exc=exc, signum=signum,
                                   last_completed_step=step)
        except Exception:
            pass  # diagnostics must never block the preemption exit
        raise exc
