"""paddle.resilience — the fault-tolerant training runtime.

Production accelerators fail in four ways the execution tiers themselves
don't handle: transient device/compile errors, numeric blowups, preemption
signals, and crashes mid-checkpoint. This package weaves recovery for all
four through the existing execution choke points (per-op dispatch, lazy
segment flush, captured-step replay, checkpoint IO) instead of bolting it
onto user code:

  faults      deterministic fault injection (FLAGS_fault_inject) — the chaos
              harness tests and tools/chaos_probe.py drive
  retry       transient-vs-fatal classification + capped exponential backoff
  ladder      graceful degradation: repeated faults demote a tier
              captured(1 program) → lazy(3) → per-op(13), cooldown re-promotes
  rescue      fused non-finite sentinel + skip/lr-backoff/abort policies
              (FLAGS_numeric_rescue), integrated with amp.GradScaler
  preemption  SIGTERM/SIGINT guard → emergency checkpoint → resume ≤1 step
  runtime     the execute() wrapper binding it all to the dispatcher

Every retry, fault, demotion, rescue, and emergency save is counted in
paddle.profiler.dispatch_counters(). See RESILIENCE.md for the fault model
and the sentinel arithmetic.
"""
from __future__ import annotations

from . import faults, ladder, preemption, rescue, retry, runtime  # noqa: F401
from .faults import (  # noqa: F401
    FaultClause,
    FaultPlan,
    InjectedCompileError,
    InjectedExecuteError,
    InjectedFault,
    InjectedHang,
    current_step,
    parse_fault_spec,
)
from .ladder import (  # noqa: F401
    DegradationLadder,
    LadderPolicy,
    degradation_ladder,
)
from .preemption import Preempted, PreemptionGuard  # noqa: F401
from .rescue import (  # noqa: F401
    Abort,
    LRBackoff,
    RescuePolicy,
    SkipStep,
)
from .retry import RetryPolicy, is_transient  # noqa: F401
from .runtime import execute, on_step_end, state  # noqa: F401

__all__ = [
    "Abort",
    "DegradationLadder",
    "FaultClause",
    "FaultPlan",
    "InjectedCompileError",
    "InjectedExecuteError",
    "InjectedFault",
    "InjectedHang",
    "LRBackoff",
    "LadderPolicy",
    "Preempted",
    "PreemptionGuard",
    "RescuePolicy",
    "RetryPolicy",
    "SkipStep",
    "current_step",
    "degradation_ladder",
    "execute",
    "is_transient",
    "on_step_end",
    "parse_fault_spec",
    "reset",
    "state",
]


def reset():
    """Reset injection plan, step counter, and ladder state (test/chaos
    scenario isolation)."""
    runtime.reset()
