"""Deterministic fault-injection harness (FLAGS_fault_inject).

The chaos half of the resilience runtime: a spec string describes *synthetic*
faults — device/runtime errors, compile errors, simulated hangs, NaN
poisoning, mid-write kills — and the harness fires them at the execution
choke points (per-op dispatch, lazy-segment flush, compiled-tape backward,
fused optimizer update, captured-step replay, checkpoint IO).

Spec grammar (comma-separated clauses, tokens separated by ':'):

    FLAGS_fault_inject="execute:p=0.2,compile:step>=3,nan:grads"

    clause   := kind (':' qualifier)*
    kind     := execute | compile | hang | nan | kill
    qualifier:= p=<float>      fire probability per (site, step)
              | step>=<int> | step<=<int> | step=<int>   step window
              | x=<int>        consecutive attempts the fault fires at one
                               matched (site, step) before letting the
                               retry through (default 1)
              | <word>         target filter: a site name for execute/
                               compile/hang/kill (op, segment, backward,
                               optimizer, captured, checkpoint, prefill,
                               decode) or a value target for nan (grads)

Decisions are SEEDED per (clause, site, step) from FLAGS_fault_seed, so a
failing run replays exactly: the same step faults at the same site every
time. Injected errors are raised BEFORE the wrapped program executes, so a
retry re-runs the program from scratch — injection never corrupts state.
"""
from __future__ import annotations

import os
import time
import zlib
from typing import List, Optional

from ..core import flags

__all__ = [
    "FaultClause",
    "FaultPlan",
    "InjectedCompileError",
    "InjectedExecuteError",
    "InjectedFault",
    "InjectedHang",
    "active_plan",
    "advance_step",
    "current_step",
    "maybe_kill",
    "parse_fault_spec",
    "reset",
]

_KINDS = ("execute", "compile", "hang", "nan", "kill")

# the closed set of site targets a clause may name: the execution choke
# points routed through resilience.runtime.execute (including the serving
# engine's prefill/decode program launches), plus the nan-injection
# targets — validated at parse time so a typo'd site fails loud instead of
# silently matching nothing
_SITES = frozenset((
    "op", "segment", "backward", "optimizer", "captured", "checkpoint",
    "prefill", "decode",
    "grads",
))


class InjectedFault(RuntimeError):
    """Synthetic fault from the harness. Raised before the wrapped program
    runs, so retrying the call is always safe."""

    transient = True


class InjectedExecuteError(InjectedFault):
    """Synthetic device/runtime failure (an XLA UNAVAILABLE/INTERNAL stand-in)."""


class InjectedCompileError(InjectedFault):
    """Synthetic compile failure at a fresh-compile point."""


class InjectedHang(InjectedFault):
    """Simulated hang: the harness stalls FLAGS_fault_hang_ms, then raises as
    if a watchdog had fired — classified transient, so the retry path runs."""


class FaultClause:
    """One parsed clause of the spec."""

    __slots__ = ("kind", "p", "step_lo", "step_hi", "step_eq", "repeat",
                 "target", "index")

    def __init__(self, kind: str, index: int):
        if kind not in _KINDS:
            raise ValueError(
                f"invalid fault kind {kind!r}: expected one of {_KINDS}"
            )
        self.kind = kind
        self.index = index
        self.p = 1.0
        self.step_lo: Optional[int] = None
        self.step_hi: Optional[int] = None
        self.step_eq: Optional[int] = None
        self.repeat = 1
        self.target: Optional[str] = None

    def matches(self, kind: str, site: str, step: int) -> bool:
        if self.kind != kind:
            return False
        if self.target is not None and self.target != site:
            return False
        if self.step_eq is not None and step != self.step_eq:
            return False
        if self.step_lo is not None and step < self.step_lo:
            return False
        if self.step_hi is not None and step > self.step_hi:
            return False
        return True

    def __repr__(self):
        return (f"<FaultClause {self.kind} p={self.p} target={self.target} "
                f"step=[{self.step_lo},{self.step_eq},{self.step_hi}] "
                f"x={self.repeat}>")


def parse_fault_spec(spec: str) -> List[FaultClause]:
    """Parse a FLAGS_fault_inject spec into clauses; raises on junk."""
    clauses: List[FaultClause] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        tokens = raw.split(":")
        clause = FaultClause(tokens[0].strip(), len(clauses))
        for tok in tokens[1:]:
            tok = tok.strip()
            if tok.startswith("p="):
                clause.p = float(tok[2:])
            elif tok.startswith("step>="):
                clause.step_lo = int(tok[6:])
            elif tok.startswith("step<="):
                clause.step_hi = int(tok[6:])
            elif tok.startswith("step="):
                clause.step_eq = int(tok[5:])
            elif tok.startswith("x="):
                clause.repeat = max(1, int(tok[2:]))
            elif tok and ("=" not in tok and "<" not in tok and ">" not in tok):
                if tok not in _SITES:
                    raise ValueError(
                        f"unknown fault site {tok!r} in clause {raw!r}: "
                        f"expected one of {sorted(_SITES)} — a typo here "
                        "would silently inject nothing"
                    )
                if clause.target is not None:
                    raise ValueError(
                        f"duplicate site in clause {raw!r}: a clause takes "
                        "at most one site target"
                    )
                clause.target = tok
            else:
                raise ValueError(
                    f"invalid fault-spec qualifier {tok!r} in clause {raw!r}"
                )
        clauses.append(clause)
    return clauses


class FaultPlan:
    """Parsed spec + the per-(clause, site, step) occurrence bookkeeping that
    makes injection deterministic AND lets a retry eventually succeed: a
    clause fires at most `x` consecutive attempts per matched (site, step)."""

    def __init__(self, clauses: List[FaultClause], seed: int):
        self.clauses = clauses
        self.seed = int(seed)
        self._fired = {}

    def _roll(self, clause: FaultClause, site: str, step: int) -> bool:
        if clause.p >= 1.0:
            return True
        key = f"{self.seed}:{clause.index}:{site}:{step}".encode()
        return (zlib.crc32(key) / 2**32) < clause.p

    def _fires(self, kind: str, site: str, step: int) -> Optional[FaultClause]:
        for clause in self.clauses:
            if not clause.matches(kind, site, step):
                continue
            if not self._roll(clause, site, step):
                continue
            key = (clause.index, site, step)
            n = self._fired.get(key, 0)
            if n >= clause.repeat:
                continue
            self._fired[key] = n + 1
            return clause
        return None

    def would_fire(self, kind: str, site: str, step: int) -> bool:
        """Non-consuming peek: True when `check`/`nan_fires` for this
        (kind, site, step) would fire right now (x= budget not exhausted).
        The capture controller uses it to route nan injection to a tier
        that can poison a materialized gradient, without spending the
        budget the fallback path's real check will consume."""
        for clause in self.clauses:
            if not clause.matches(kind, site, step):
                continue
            if not self._roll(clause, site, step):
                continue
            if self._fired.get((clause.index, site, step), 0) >= clause.repeat:
                continue
            return True
        return False

    def check(self, kind: str, site: str, step: int):
        """Raise the injected fault for (kind, site, step), if one fires."""
        clause = self._fires(kind, site, step)
        if clause is None:
            return
        if kind == "compile":
            raise InjectedCompileError(
                f"injected compile fault at site '{site}' (step {step})"
            )
        if kind == "hang":
            time.sleep(float(flags.flag("fault_hang_ms")) / 1000.0)
            raise InjectedHang(
                f"injected hang at site '{site}' (step {step}): watchdog fired"
            )
        raise InjectedExecuteError(
            f"injected device fault at site '{site}' (step {step}): "
            "UNAVAILABLE: simulated transient runtime error"
        )

    def nan_fires(self, target: str, step: int) -> bool:
        """True when a `nan:<target>` clause fires this step (counted like
        execute faults: at most `x` times per (target, step))."""
        return self._fires("nan", target, step) is not None

    def kill_fires(self, site: str, step: int) -> bool:
        return self._fires("kill", site, step) is not None

    def prune(self, step: int):
        """Drop occurrence bookkeeping older than a few steps so long runs
        don't grow the dict without bound."""
        if len(self._fired) > 256:
            stale = [k for k in self._fired if k[2] < step - 4]
            for k in stale:
                del self._fired[k]


# ---------------------------------------------------------------------------
# Module state: the active plan (cached per (spec, seed)) and the global
# step counter the qualifiers are evaluated against. The step advances at
# every optimizer.step() boundary (resilience.runtime.on_step_end).
# ---------------------------------------------------------------------------
_plan: Optional[FaultPlan] = None
_plan_key = None
_step = 0


def active_plan() -> Optional[FaultPlan]:
    """The FaultPlan for the current FLAGS_fault_inject value, or None when
    injection is off. Changing the flag (or the seed) resets the plan's
    occurrence bookkeeping — each scenario replays from scratch; that
    includes toggling injection off and back on with the SAME spec, so the
    cached plan (and its consumed x= budgets) is dropped on the off edge."""
    global _plan, _plan_key
    spec = str(flags.flag("fault_inject"))
    if not spec:
        _plan = None
        _plan_key = None
        return None
    seed = int(flags.flag("fault_seed"))
    key = (spec, seed)
    if _plan_key != key:
        _plan = FaultPlan(parse_fault_spec(spec), seed)
        _plan_key = key
    return _plan


def current_step() -> int:
    return _step


def advance_step():
    global _step
    _step += 1
    if _plan is not None:
        _plan.prune(_step)


def reset():
    """Clear the plan cache and the step counter (test isolation)."""
    global _plan, _plan_key, _step
    _plan = None
    _plan_key = None
    _step = 0


def maybe_kill(site: str):
    """Hard-exit the process when a `kill:<site>` clause fires — the
    crash-consistency probe for checkpoint IO (a mid-save kill must never
    corrupt the latest restorable snapshot)."""
    plan = active_plan()
    if plan is not None and plan.kill_fires(site, current_step()):
        os._exit(137)
