"""Step-level numeric rescue over the fused non-finite sentinel.

With FLAGS_numeric_rescue set, the fused optimizer update (and the captured
whole-step program) computes ONE extra scalar output — `any(~isfinite(g))`
over every gradient — and gates the parameter/state update on it in-program:
a blown-up step leaves params and optimizer state untouched without any
additional program launch (verified by measure_programs: programs-per-step
stays 13/3/1 per tier). The host then reads the sentinel and applies the
configured policy:

    skip        drop the step (update already suppressed in-program)
    lr_backoff  drop the step AND multiply the lr by
                FLAGS_numeric_rescue_lr_factor (a loss-spike brake)
    abort       raise FloatingPointError (fail fast, e.g. under a debugger)

AMP integration: when a GradScaler drove the step, a rescued step also marks
the scaler's found_inf so dynamic loss scaling backs off — and the scaler
skips its own per-grad host isfinite scan (the sentinel subsumes it).
"""
from __future__ import annotations

import warnings
from typing import Optional

from ..core import flags

__all__ = [
    "Abort",
    "LRBackoff",
    "RescuePolicy",
    "SkipStep",
    "active",
    "handle_sentinel",
    "mode",
    "policy",
]


def mode() -> str:
    return str(flags.flag("numeric_rescue"))


def active() -> bool:
    return mode() != ""


class RescuePolicy:
    """What to do — beyond the in-program update suppression — when the
    sentinel reports non-finite gradients."""

    name = ""

    def apply(self, optimizer):
        raise NotImplementedError


class SkipStep(RescuePolicy):
    name = "skip"

    def apply(self, optimizer):
        pass  # update already suppressed in-program


class LRBackoff(RescuePolicy):
    name = "lr_backoff"

    def apply(self, optimizer):
        from ..core import dispatch

        factor = float(flags.flag("numeric_rescue_lr_factor"))
        try:
            optimizer.set_lr(optimizer.get_lr() * factor)
            dispatch._counters["rescue_lr_backoffs"] += 1
        except RuntimeError:
            # scheduler-driven lr: the optimizer refuses set_lr — degrade to
            # skip-step and say so once
            warnings.warn(
                "numeric_rescue=lr_backoff: optimizer lr is scheduler-driven; "
                "rescued steps are skipped without backing off the lr",
                stacklevel=3,
            )


class Abort(RescuePolicy):
    name = "abort"

    def apply(self, optimizer):
        raise FloatingPointError(
            "non-finite gradients at optimizer.step "
            f"(step {_current_step()}): numeric_rescue=abort"
        )


def _current_step() -> int:
    from . import faults

    return faults.current_step()


_POLICIES = {p.name: p for p in (SkipStep(), LRBackoff(), Abort())}


def policy() -> Optional[RescuePolicy]:
    m = mode()
    if not m:
        return None
    pol = _POLICIES.get(m)
    if pol is None:
        raise ValueError(
            f"unknown FLAGS_numeric_rescue policy {m!r}: expected one of "
            f"{sorted(_POLICIES)}"
        )
    return pol


def handle_sentinel(optimizer, bad) -> bool:
    """Host-read the fused sentinel; on non-finite apply the policy.

    Returns True when the step was rescued (params/state unchanged). Reading
    `bad` blocks on the already-launched step program — it never launches a
    new one."""
    if not bool(bad):
        return False
    from ..core import dispatch

    dispatch._counters["numeric_rescues"] += 1
    step = _current_step()
    dispatch._emit("rescue", site="optimizer", policy=mode(), step=step)
    # triage postmortem (no-op unless FLAGS_postmortem_dir is set): the
    # attribution section names the out-of-trend parameter group (fused
    # telemetry recorded the spike BEFORE this handler ran) and recovers
    # the offending batch's sample ids from the registered sampler;
    # FLAGS_postmortem_keep bounds a rescue storm's dump volume
    try:
        dispatch._trace_module().dump_postmortem(
            "numeric_rescue", policy=mode(), step=step)
    except Exception:
        pass  # diagnostics must never add a second failure
    scaler = getattr(optimizer, "_rescue_scaler", None)
    if scaler is not None:
        # dynamic loss scaling reacts to the rescued step exactly as it
        # would to its own inf scan
        scaler._found_inf = True
    pol = policy()
    if pol is not None:
        pol.apply(optimizer)
    return True
