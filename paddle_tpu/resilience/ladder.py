"""Graceful-degradation ladder over the execution tiers.

Generalizes the whole-step capture fallback (PR 3) into policy objects: the
three eager execution tiers — captured (1 program/step), lazy (3), per-op
(13) — form a ladder, and repeated faults at a tier *demote* it: the runtime
stops attempting that tier and the step runs one rung down, with identical
numerics (the tier-parity contract every tier already guarantees). After a
cooldown of clean steps the tier is re-promoted and the fast path is tried
again — a CUDA-Graphs-style capture-with-fallback loop, but driven by
observed fault history instead of per-call mismatch alone.

Demotions are keyed: the captured tier demotes per step-signature (the same
(segment, tape, optimizer) triple that keys the capture cache), so one
misbehaving step shape doesn't take capture away from every other step; the
lazy tier demotes globally (segment faults are not signature-local).
"""
from __future__ import annotations

from typing import Any, Dict, Hashable, Optional, Tuple

from ..core import flags

__all__ = ["DegradationLadder", "LadderPolicy", "TIERS", "degradation_ladder"]

# ladder rungs, fastest first; per_op is the floor and never demotes
TIERS = ("captured", "lazy", "per_op")


class LadderPolicy:
    """Demotion/re-promotion thresholds. Defaults read the FLAGS_ladder_*
    values at access time so flag changes apply live; explicit values pin."""

    def __init__(self, demote_after: Optional[int] = None,
                 cooldown_steps: Optional[int] = None):
        self._demote_after = demote_after
        self._cooldown = cooldown_steps

    @property
    def demote_after(self) -> int:
        if self._demote_after is not None:
            return self._demote_after
        return int(flags.flag("ladder_demote_after"))

    @property
    def cooldown_steps(self) -> int:
        if self._cooldown is not None:
            return self._cooldown
        return int(flags.flag("ladder_cooldown_steps"))

    def __repr__(self):
        return (f"LadderPolicy(demote_after={self.demote_after}, "
                f"cooldown_steps={self.cooldown_steps})")


class _TierState:
    __slots__ = ("faults", "demoted", "clean_steps", "fault_this_step")

    def __init__(self):
        self.faults = 0
        self.demoted = False
        self.clean_steps = 0
        self.fault_this_step = False


class DegradationLadder:
    """Fault-history state machine per (tier, key)."""

    def __init__(self, policy: Optional[LadderPolicy] = None):
        self.policy = policy or LadderPolicy()
        self._states: Dict[Tuple[str, Hashable], _TierState] = {}
        # fast-path flag read by the per-op dispatcher on every op
        self._lazy_demoted = False

    def _state(self, tier: str, key: Hashable) -> _TierState:
        st = self._states.get((tier, key))
        if st is None:
            st = _TierState()
            self._states[(tier, key)] = st
        return st

    def allows(self, tier: str, key: Hashable = None) -> bool:
        """May the runtime attempt `tier` (for step-signature `key`)?"""
        if tier == "per_op":
            return True
        st = self._states.get((tier, key))
        if st is not None and st.demoted:
            return False
        if key is not None:
            st = self._states.get((tier, None))
            if st is not None and st.demoted:
                return False
        return True

    def record_fault(self, tier: str, key: Hashable = None):
        """One DISRUPTIVE fault observed at `tier` (fatal, or transient with
        retries exhausted — recovered retries re-run the same program and
        don't count; see runtime._record_fault)."""
        if tier == "per_op":
            return  # the floor: faults there are retried, never demoted
        st = self._state(tier, key)
        st.faults += 1
        st.fault_this_step = True
        st.clean_steps = 0
        if not st.demoted and st.faults >= self.policy.demote_after:
            st.demoted = True
            self._count("ladder_demotions")
            self._emit("demote", tier, key, st.faults)
            if tier == "lazy":
                self._lazy_demoted = True

    def step_end(self):
        """Step-boundary tick: demoted tiers accrue clean steps and
        re-promote after the cooldown."""
        for (tier, _key), st in list(self._states.items()):
            if st.demoted:
                if not st.fault_this_step:
                    st.clean_steps += 1
                if st.clean_steps >= self.policy.cooldown_steps:
                    st.demoted = False
                    st.faults = 0
                    st.clean_steps = 0
                    self._count("ladder_promotions")
                    self._emit("promote", tier, _key, 0)
                    if tier == "lazy":
                        self._lazy_demoted = any(
                            s.demoted for (t, _k), s in self._states.items()
                            if t == "lazy"
                        )
            st.fault_this_step = False

    def any_demoted(self) -> bool:
        """Cheap gate for the perf-regression sentinel: a demoted tier IS
        slower — that slowdown is resilience working, not a regression."""
        return any(st.demoted for st in self._states.values())

    def state(self) -> Dict[str, Any]:
        """Snapshot for profiler/bench introspection."""
        demoted = sorted(
            tier + ("" if key is None else f"[{key}]")
            for (tier, key), st in self._states.items() if st.demoted
        )
        return {
            "policy": repr(self.policy),
            "demoted": demoted,
            "tracked": len(self._states),
            "faults": {
                tier + ("" if key is None else f"[{key}]"): st.faults
                for (tier, key), st in self._states.items() if st.faults
            },
        }

    def reset(self):
        self._states.clear()
        self._lazy_demoted = False

    @staticmethod
    def _count(name: str):
        from ..core import dispatch

        dispatch._counters[name] += 1

    @staticmethod
    def _emit(action: str, tier: str, key, faults: int):
        from ..core import dispatch

        dispatch._emit("ladder", site=tier, action=action,
                       key=None if key is None else str(key), faults=faults)


_ladder = DegradationLadder()


def degradation_ladder() -> DegradationLadder:
    """The process-wide ladder instance the execution runtime consults."""
    return _ladder
