"""Transient-vs-fatal error classification + capped exponential backoff.

The classification contract: only errors that a *re-execution of the same
pure program* could plausibly clear are transient — injected harness faults,
XLA runtime errors whose status codes name infrastructure conditions
(UNAVAILABLE, RESOURCE_EXHAUSTED, ...), connection/timeout errors, and
checkpoint-IO OSErrors. Everything else (shape errors, user exceptions,
verification failures, NaN detections) is fatal and propagates after a
single attempt — retrying a deterministic failure only hides it.
"""
from __future__ import annotations

import errno as _errno
import random
from typing import Optional

from ..core import flags
from .faults import InjectedFault

__all__ = ["RetryPolicy", "default_policy", "is_transient"]

# substrings of XLA/PJRT runtime-status messages that mark infrastructure
# (not program) failures — the codes CheckFreq-style runtimes retry on
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "connection reset",
    "socket closed",
    "temporarily unavailable",
)
_TRANSIENT_TYPE_NAMES = ("XlaRuntimeError", "JaxRuntimeError", "RpcError")

# deterministic program/user errors: never retried even when a message
# happens to contain a marker word
_FATAL_TYPES = (
    FloatingPointError,
    AssertionError,
    TypeError,
    ValueError,
    KeyError,
    IndexError,
    AttributeError,
    NotImplementedError,
)

# OSErrors whose cause is deterministic — a bad path, permissions, a full or
# read-only disk: retrying the same call cannot succeed, and backing off
# `retry_max` times before surfacing them only delays the real error
_FATAL_OS_TYPES = (
    PermissionError,
    FileNotFoundError,
    FileExistsError,
    IsADirectoryError,
    NotADirectoryError,
)
_FATAL_ERRNOS = frozenset(
    e for e in (
        _errno.EACCES, _errno.EPERM, _errno.ENOENT, _errno.EEXIST,
        _errno.ENOSPC, _errno.EROFS, _errno.EISDIR, _errno.ENOTDIR,
        _errno.ENOTEMPTY, _errno.ENAMETOOLONG, _errno.EINVAL, _errno.EBADF,
    ) if e is not None
)


def is_transient(e: BaseException) -> bool:
    """True when retrying the failed (pure) call could plausibly succeed."""
    if isinstance(e, InjectedFault):
        return e.transient
    if not isinstance(e, Exception):
        return False  # KeyboardInterrupt / SystemExit / Preempted propagate
    if isinstance(e, _FATAL_TYPES):
        return False
    if isinstance(e, OSError):
        # connection drops / flaky mounts retry; deterministic filesystem
        # failures (ENOSPC, EACCES, ENOENT, ...) fail loud on attempt one
        if isinstance(e, _FATAL_OS_TYPES) or e.errno in _FATAL_ERRNOS:
            return False
        return True
    if type(e).__name__ in _TRANSIENT_TYPE_NAMES:
        # PJRT runtime errors surface infra failures (device preempted,
        # relay dropped); compile-time program errors raise python types
        # handled above, so a runtime-status error here is worth one retry
        return True
    return any(m in str(e) for m in _TRANSIENT_MARKERS)


class RetryPolicy:
    """Capped exponential backoff with jitter.

    Arguments default to the FLAGS_retry_* values at call time, so a policy
    object constructed once stays in sync with runtime flag changes; pass
    explicit values to pin a policy."""

    def __init__(self, max_retries: Optional[int] = None,
                 backoff_ms: Optional[float] = None,
                 backoff_max_ms: Optional[float] = None,
                 jitter: float = 0.25):
        self._max_retries = max_retries
        self._backoff_ms = backoff_ms
        self._backoff_max_ms = backoff_max_ms
        self.jitter = float(jitter)

    @property
    def max_retries(self) -> int:
        if self._max_retries is not None:
            return self._max_retries
        return int(flags.flag("retry_max"))

    @property
    def backoff_ms(self) -> float:
        if self._backoff_ms is not None:
            return self._backoff_ms
        return float(flags.flag("retry_backoff_ms"))

    @property
    def backoff_max_ms(self) -> float:
        if self._backoff_max_ms is not None:
            return self._backoff_max_ms
        return float(flags.flag("retry_backoff_max_ms"))

    def delay_ms(self, attempt: int) -> float:
        """Backoff before retry `attempt` (1-based): base * 2^(attempt-1),
        capped, with multiplicative jitter so synchronized workers don't
        retry in lockstep."""
        base = self.backoff_ms * (2.0 ** max(0, attempt - 1))
        base = min(base, self.backoff_max_ms)
        if base <= 0:
            return 0.0
        return base * (1.0 + self.jitter * random.random())


_default = RetryPolicy()


def default_policy() -> RetryPolicy:
    return _default
