"""The choke-point executor: fault injection + retry/backoff + ladder
accounting around every device-program launch.

`execute(site, thunk)` is the one wrapper the execution runtime routes
program launches through — per-op dispatch ("op"), lazy-segment flush
("segment"), compiled-tape backward ("backward"), fused optimizer update
("optimizer"), captured-step build/replay ("captured"), and checkpoint IO
("checkpoint"). It consults the fault-injection plan (synthetic faults are
raised BEFORE the thunk runs, so a retry re-executes from scratch), retries
transient failures with capped exponential backoff + jitter, and reports
every fault to the degradation ladder so repeatedly-faulting tiers demote.

Every event lands in paddle.profiler.dispatch_counters():
fault_events / injected_faults / transient_faults / fatal_faults /
retry_attempts / retry_exhausted / retry_backoff_ms / fault_sites.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Hashable, Optional

from ..core import flags
from . import faults
from . import ladder as _ladder
from . import rescue as _rescue
from . import retry as _retry

__all__ = ["execute", "lazy_tier_ok", "captured_tier_ok", "on_step_end",
           "reset", "state"]

# site → ladder tier that owns faults there. Per-op/backward/optimizer
# programs run at the ladder floor (retried, never demoted); checkpoint IO
# is not an execution tier. The serving engine's prefill/decode launches
# run at the captured tier keyed by their bucket signature — a disruptive
# fault demotes that ONE bucket's program captured→lazy→per-op while other
# buckets keep replaying their captured executables.
_SITE_TIER = {
    "segment": "lazy",
    "captured": "captured",
    "prefill": "captured",
    "decode": "captured",
}

# exception type names that must pass through untouched: control-flow and
# verdict exceptions, not faults (counted elsewhere or not at all)
_PASSTHROUGH = frozenset((
    "_CaptureIneligible",
    "ProgramVerificationError",
    "Preempted",
    "FloatingPointError",
))

_dispatch = None


def _disp():
    global _dispatch
    if _dispatch is None:
        from ..core import dispatch as d

        _dispatch = d
    return _dispatch


def execute(site: str, thunk: Callable[[], Any], *, fresh: bool = False,
            ladder_key: Hashable = None, retry_unsafe: bool = False) -> Any:
    """Run `thunk()` under the resilience policy for `site`.

    `fresh=True` marks a fresh-compile point (the thunk's first run will
    compile), enabling `compile:` fault clauses there. `ladder_key` scopes
    ladder demotion (the captured tier passes its step-signature hash).
    `retry_unsafe=True` marks a thunk whose input buffers are DONATED: a
    real transient fault from inside it may fire after XLA consumed the
    inputs, so it is never re-invoked in place — the fault is recorded as
    disruptive (the ladder demotes) and propagates to the caller's fallback
    path. Injected faults raise BEFORE the thunk runs, so they still retry."""
    plan = faults.active_plan()
    if plan is None:
        # hot path (no fault injection): one call, no flag reads; a real
        # failure re-enters below with full classify/retry/ladder handling
        try:
            return thunk()
        except BaseException as e:
            if type(e).__name__ in _PASSTHROUGH or not isinstance(e, Exception):
                raise
            pending = e
    else:
        pending = None
    max_retries = int(flags.flag("retry_max"))
    attempt = 0
    while True:
        try:
            if pending is not None:
                e, pending = pending, None
                raise e
            if plan is not None:
                step = faults.current_step()
                if fresh:
                    plan.check("compile", site, step)
                plan.check("execute", site, step)
                plan.check("hang", site, step)
            return thunk()
        except BaseException as e:
            if type(e).__name__ in _PASSTHROUGH or not isinstance(e, Exception):
                raise
            transient = _retry.is_transient(e)
            replayable = transient and not (
                retry_unsafe and not isinstance(e, faults.InjectedFault)
            )
            disruptive = not replayable or attempt >= max_retries
            _record_fault(site, e, transient, ladder_key, disruptive)
            if not replayable:
                _postmortem_escape(site, e, attempt)
                raise
            if attempt >= max_retries:
                _disp()._counters["retry_exhausted"] += 1
                _postmortem_escape(site, e, attempt)
                raise
            attempt += 1
            d = _disp()
            d._counters["retry_attempts"] += 1
            delay = _retry.default_policy().delay_ms(attempt)
            d._emit("retry", site=site, attempt=attempt,
                    delay_ms=round(delay, 2), error=type(e).__name__)
            if delay > 0:
                time.sleep(delay / 1000.0)
            d._counters["retry_backoff_ms"] += delay


def _postmortem_escape(site: str, e: BaseException, attempt: int):
    """An unrecovered fault is escaping execute() — fatal, donated-input
    unsafe, or retries exhausted. Dump a crash postmortem (no-op unless
    FLAGS_postmortem_dir is set): even when a HIGHER tier's fallback later
    completes the step, the dump records why this launch failed — site,
    retries, classification, and the flight recorder's event tail."""
    try:
        _disp()._trace_module().dump_postmortem(
            "unrecovered_fault", exc=e, site=site, retries=attempt,
            transient=_retry.is_transient(e),
            injected=isinstance(e, faults.InjectedFault),
        )
    except Exception:
        pass  # diagnostics must never add a second failure


def _record_fault(site: str, e: BaseException, transient: bool,
                  ladder_key: Hashable, disruptive: bool):
    d = _disp()
    c = d._counters
    c["fault_events"] += 1
    sites = c["fault_sites"]
    sites[site] = sites.get(site, 0) + 1
    injected = isinstance(e, faults.InjectedFault)
    if injected:
        c["injected_faults"] += 1
    c["transient_faults" if transient else "fatal_faults"] += 1
    d._emit("fault", site=site, error=type(e).__name__, transient=transient,
            injected=injected, disruptive=disruptive)
    # only DISRUPTIVE faults (fatal, or transient with retries exhausted)
    # count toward ladder demotion: a retried-and-recovered fault re-ran the
    # exact same program, so it never perturbs numerics — demoting on it
    # would switch tiers mid-run for no reliability gain
    if disruptive:
        tier = _SITE_TIER.get(site)
        if tier is not None:
            _ladder.degradation_ladder().record_fault(tier, key=ladder_key)


def lazy_tier_ok() -> bool:
    """Fast gate read by the per-op dispatcher: False while the ladder has
    the lazy tier demoted (ops then take the per-op path)."""
    return not _ladder.degradation_ladder()._lazy_demoted


def captured_tier_ok(key: Hashable = None) -> bool:
    return _ladder.degradation_ladder().allows("captured", key)


def on_step_end(source: str = "train"):
    """Optimizer.step boundary tick: advances the fault-injection step
    counter, the ladder's cooldown clocks, the stall watchdog's heartbeat
    (paddle.profiler.trace / FLAGS_trace_stall_ms), and — when
    FLAGS_sentinel_pct > 0 — the perf-regression sentinel's step-time
    baseline for `source` ('train' from optimizer.step, 'serve[<uid>]'
    from each serving engine's tick; training steps running under an
    armed whole-step capture key by its signature so a re-capture
    re-baselines)."""
    faults.advance_step()
    _ladder.degradation_ladder().step_end()
    try:
        _disp()._trace_module().step_heartbeat(source)
    except Exception:
        pass  # observability must never break the step boundary
    try:
        key = source
        if source == "train":
            from ..core import lazy as _lazy

            sig = _lazy.step_signature_id()
            if sig is not None:
                key = f"train[{sig}]"
        # attribution cost registry: the step-boundary lap feeds the
        # host-inclusive `step`-category EMA (a slowdown BETWEEN program
        # launches still attributes to its train/serve key). Inner try:
        # an attribution failure must not cost the sentinel its lap.
        try:
            from ..profiler import attribution as _attribution

            _attribution.step_lap(key)
        except Exception:
            pass
        from ..profiler import sentinel as _sentinel

        if _sentinel.PerfSentinel.enabled():
            _sentinel.default_sentinel().lap(key)
    except Exception:
        pass  # the sentinel must never break the step boundary


def state() -> dict:
    """Snapshot of the resilience runtime (profiler.measure_programs's
    `_resilience` entry and bench.py's resilience block read this)."""
    return {
        "step": faults.current_step(),
        "fault_inject": str(flags.flag("fault_inject")),
        "retry_max": int(flags.flag("retry_max")),
        "numeric_rescue": _rescue.mode(),
        "ladder": _ladder.degradation_ladder().state(),
    }


def reset():
    """Reset harness + ladder state (test isolation; counters are reset
    separately via paddle.profiler.reset_dispatch_counters)."""
    faults.reset()
    _ladder.degradation_ladder().reset()
