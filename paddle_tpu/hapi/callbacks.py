"""hapi callbacks. Reference: python/paddle/hapi/callbacks.py."""
from __future__ import annotations

import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """reference: callbacks.py ProgBarLogger."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            loss = logs.get("loss")
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch}: step {step}{total} - loss: {loss:.4f}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            extras = " - ".join(
                f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                if isinstance(v, (int, float)) and k != "step"
            )
            print(f"Epoch {epoch} done in {dt:.1f}s - {extras}")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each epoch/batch
    (reference: callbacks.py LRSchedulerCallback)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None) if opt else None
        return lr if hasattr(lr, "step") else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class EarlyStopping(Callback):
    """reference: callbacks.py EarlyStopping."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0  # reference attr: epoch training halted at
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        # fresh state per fit() so the callback instance is reusable
        # (the reference resets here too)
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0
        self.model.stop_training = False

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True


class ModelCheckpoint(Callback):
    """reference: callbacks.py ModelCheckpoint — routed through the shared
    checkpoint machinery (paddle.distributed.checkpoint.AsyncCheckpointer):
    pipelined boundary snapshots with retention and a crash-consistent
    LATEST pointer instead of ad-hoc per-epoch file writes. `save_freq`
    accepts `"auto"` for CheckFreq cadence tuning against the
    FLAGS_ckpt_overhead_pct overhead budget.

    `resume=True` (default) restores the latest snapshot from `save_dir`
    on train begin — params, optimizer accumulators AND the data-iterator
    state (sampler epoch/cursor + framework RNG, when `fit` handed the
    train loader over) — and reports `resume_epoch` so `fit` continues at
    the next epoch instead of re-reading the data from the top."""

    def __init__(self, save_freq=1, save_dir=None, resume=True):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.resume = bool(resume)
        self.resume_epoch = 0
        self.checkpointer = None
        self._cadence = None
        self._t0 = None
        self._train_loader = None  # set by fit() for iterator-state resume

    def set_train_loader(self, loader):
        self._train_loader = loader

    def on_train_begin(self, logs=None):
        if not self.save_dir:
            return
        from ..distributed.checkpoint import (
            AsyncCheckpointer,
            CheckpointCadence,
            restore_training_state,
            training_state,
        )

        optimizer = getattr(self.model, "_optimizer", None)
        data = self._train_loader
        if data is not None and not hasattr(data, "state_dict"):
            data = None
        state = training_state(self.model.network, optimizer, data=data)
        self.checkpointer = AsyncCheckpointer(self.save_dir)
        self.resume_epoch = 0
        if self.resume:
            restored = self.checkpointer.restore_latest(state)
            if restored is not None:
                restore_training_state(state, optimizer=optimizer,
                                       data=data)
                self.resume_epoch = restored + 1
        self._cadence = CheckpointCadence(
            self.checkpointer, state, self.save_freq,
        )

    def on_epoch_begin(self, epoch, logs=None):
        self._t0 = time.perf_counter()

    def on_epoch_end(self, epoch, logs=None):
        if self._cadence is not None:
            dt = (time.perf_counter() - self._t0) if self._t0 else 0.0
            self._cadence.boundary(epoch, dt)

    def on_train_end(self, logs=None):
        if self.checkpointer is not None:
            self.checkpointer.wait()
            # classic Model.load-compatible artifact alongside the
            # checkpointer snapshots
            import os

            self.model.save(os.path.join(self.save_dir, "final"))


class VisualDL(Callback):
    """Metrics logging hook (reference: callbacks.py VisualDL). Writes a
    plain JSONL scalars file (no visualdl dependency in this environment)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir

    def on_epoch_end(self, epoch, logs=None):
        import json
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps({"epoch": epoch, **(logs or {})}) + "\n")


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer LR when a metric plateaus (reference:
    callbacks.py ReduceLROnPlateau). Works on optimizers with a float LR
    or a ReduceOnPlateau-style scheduler."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.cooldown_counter = 0
        self.best = None

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        if self.cooldown_counter > 0:
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            lr = opt._learning_rate
            if hasattr(lr, "last_lr"):
                # scheduler: scale base AND current lr by factor, so future
                # step() calls (which recompute from base_lr) carry the
                # reduction without re-applying accumulated decay
                lr.base_lr = max(float(lr.base_lr) * self.factor, self.min_lr)
                new = max(float(lr.last_lr) * self.factor, self.min_lr)
                lr.last_lr = new
            else:
                new = max(float(lr) * self.factor, self.min_lr)
                opt.set_lr(new)
            if self.verbose:
                print(f"\nEpoch {epoch}: reducing learning rate to {new}.")
            self.cooldown_counter = self.cooldown
            self.wait = 0
