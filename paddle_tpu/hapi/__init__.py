"""paddle.hapi — the Keras-like high-level Model API.

Reference analogue: python/paddle/hapi/model.py:907 (Model with
prepare:1486/fit/evaluate/predict, dygraph & static adapters) + callbacks.py.
The TPU adapter is the compiled train step (paddle_tpu.jit), so hapi fit()
trains through one fused XLA program per shape.
"""
from .model import Model  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
)
from .summary import summary  # noqa: F401
