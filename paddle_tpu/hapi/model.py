"""hapi Model. Reference: python/paddle/hapi/model.py:907."""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

import paddle_tpu as paddle

from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..nn.layer_base import Layer
from .callbacks import CallbackList, ProgBarLogger


class Model:
    """reference: hapi/model.py Model(network, inputs=None, labels=None)."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        """reference: model.py:1486."""
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, list) else [metrics]
        self._train_step = None
        return self

    def _loss_fn(self, outputs, labels):
        loss = self._loss(outputs, labels)
        if isinstance(loss, (list, tuple)):
            loss = sum(loss[1:], loss[0])
        if loss.ndim > 0:
            loss = loss.mean()
        return loss

    # -- batch-level API -----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        if self._train_step is None:
            self._train_step = paddle.jit.compile_train_step(
                self.network, self._loss_fn, self._optimizer
            )
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        loss = self._train_step(*ins, *labs)
        return [float(loss)]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*ins)
        loss = self._loss_fn(outputs, labels if not isinstance(labels, (list, tuple)) else labels[0])
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, labels if not isinstance(labels, (list, tuple)) else labels[0]))
            metrics.append(m.accumulate())
        return [float(loss)], metrics

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*ins)
        return [out.numpy() if isinstance(out, Tensor) else out]

    # -- loop API ------------------------------------------------------------
    def fit(
        self,
        train_data=None,
        eval_data=None,
        batch_size=1,
        epochs=1,
        eval_freq=1,
        log_freq=10,
        save_dir=None,
        save_freq=1,
        verbose=2,
        drop_last=False,
        shuffle=True,
        num_workers=0,
        callbacks=None,
        **kwargs,
    ):
        """reference: model.py fit.

        `save_dir` checkpoints through `paddle.distributed.checkpoint.
        AsyncCheckpointer` (pipelined snapshot + background commit) every
        `save_freq` epochs; `save_freq="auto"` tunes the cadence against
        the FLAGS_ckpt_overhead_pct budget (CheckFreq). A classic
        `final.pdparams`/`final.pdopt` pair is written at train end."""
        train_loader = (
            train_data
            if isinstance(train_data, DataLoader)
            else DataLoader(
                train_data, batch_size=batch_size, shuffle=shuffle,
                drop_last=drop_last, num_workers=num_workers,
            )
        )
        eval_loader = None
        if eval_data is not None:
            eval_loader = (
                eval_data
                if isinstance(eval_data, DataLoader)
                else DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
            )
        cbks = CallbackList(callbacks or ([ProgBarLogger(log_freq, verbose)] if verbose else []))
        cbks.set_model(self)
        cbks.set_params(
            {
                "epochs": epochs,
                "steps": len(train_loader) if hasattr(train_loader, "__len__") else None,
                "verbose": verbose,
                "metrics": ["loss"] + [m.name() for m in self._metrics],
            }
        )
        # periodic saving rides the shared checkpoint machinery via the
        # ModelCheckpoint callback (paddle.distributed.checkpoint): async
        # pipelined snapshots with retention + crash-consistent LATEST
        # pointer instead of ad-hoc per-epoch file writes, save_freq="auto"
        # gets the CheckFreq cadence tuner under the FLAGS_ckpt_overhead_pct
        # budget, and a classic final.pdparams/.pdopt pair lands at train
        # end for Model.load workflows
        ckpt_cb = None
        if save_dir:
            from .callbacks import ModelCheckpoint

            ckpt_cb = ModelCheckpoint(save_freq=save_freq, save_dir=save_dir)
            ckpt_cb.set_model(self)
            ckpt_cb.set_train_loader(train_loader)
            cbks.append(ckpt_cb)
        self.stop_training = False  # stale stop from a previous fit()
        cbks.on_train_begin()
        # a save_dir with committed snapshots resumes at the NEXT epoch —
        # params, optimizer moments and the data-iterator state (sampler
        # epoch/cursor, RNG) all came back in on_train_begin, so the run
        # continues instead of re-reading every epoch from the top
        start_epoch = ckpt_cb.resume_epoch if ckpt_cb is not None else 0
        train_sampler = getattr(train_loader, "batch_sampler", None)
        for epoch in range(start_epoch, epochs):
            if self.stop_training:
                break
            if hasattr(train_sampler, "set_epoch"):
                # epoch-deterministic shuffling: the sampler's permutation
                # is a function of the epoch index, so a resumed run draws
                # the same per-epoch streams the original would have
                train_sampler.set_epoch(epoch)
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                x, y = self._split_batch(batch)
                (loss,) = self.train_batch(x, y)
                logs = {"loss": loss, "step": step}
                cbks.on_train_batch_end(step, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
        cbks.on_train_end(logs if "logs" in dir() else {})
        # training is over — no more step heartbeats will arrive, which is
        # indistinguishable from a stall; stand the watchdog down so a
        # finished fit() (or a following long eval) never dumps a spurious
        # stall postmortem (FLAGS_trace_stall_ms)
        try:
            from ..profiler import trace as _trace

            _trace.watchdog_disarm("train")
        except Exception:
            pass
        return self

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return batch[0], batch[1]
        return batch, None

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, **kwargs):
        loader = (
            eval_data
            if isinstance(eval_data, DataLoader)
            else DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        )
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            x, y = self._split_batch(batch)
            (loss,), _ = self.eval_batch(x, y)
            losses.append(loss)
        out = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            name = m.name()
            res = m.accumulate()
            if isinstance(name, list):
                out.update(dict(zip(name, res)))
            else:
                out[name] = res
        return out

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = (
            test_data
            if isinstance(test_data, DataLoader)
            else DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        )
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.predict_batch(x)[0])
        if stack_outputs:
            return [np.concatenate(outs, axis=0)]
        return [outs]

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        """reference: model.py save — training=False exports for inference."""
        if training:
            paddle.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                paddle.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            raise NotImplementedError(
                "inference export via Model.save(training=False): use "
                "paddle.jit.save with an input_spec"
            )

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(paddle.load(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(paddle.load(path + ".pdopt"))
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary

        return _summary(self.network, input_size, dtypes=dtype)
