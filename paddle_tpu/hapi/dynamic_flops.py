"""paddle.flops — per-layer FLOPs/params report.

Reference analogue: python/paddle/hapi/dynamic_flops.py:25 flops() — runs a
forward over a zeros input with per-layer-type counting hooks. Same counting
conventions (multiply-add counted once; conv counts kernel MACs; norm/act
count elementwise passes).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle

from .. import nn

__all__ = ["flops"]


def _prod(xs):
    out = 1
    for v in xs:
        out *= int(v)
    return out


def _count_conv(layer, x, y):
    # kernel MACs per output element x output elements (+bias)
    kh_kw_cin = _prod(layer.weight.shape[1:])
    out_elems = _prod(y.shape)
    total = out_elems * kh_kw_cin
    if getattr(layer, "bias", None) is not None:
        total += out_elems
    return total


def _count_linear(layer, x, y):
    total = _prod(y.shape) * layer.weight.shape[0]
    if getattr(layer, "bias", None) is not None:
        total += _prod(y.shape)
    return total


def _count_norm(layer, x, y):
    return 2 * _prod(x.shape)


def _count_act(layer, x, y):
    return _prod(x.shape)


def _count_pool(layer, x, y):
    return _prod(y.shape)


_DEFAULT_COUNTERS = [
    ((nn.Conv1D, nn.Conv2D, nn.Conv3D, nn.Conv2DTranspose), _count_conv),
    ((nn.Linear,), _count_linear),
    ((nn.BatchNorm1D, nn.BatchNorm2D, nn.BatchNorm3D, nn.LayerNorm,
      nn.GroupNorm, nn.InstanceNorm2D), _count_norm),
    ((nn.ReLU, nn.ReLU6, nn.GELU, nn.Sigmoid, nn.Tanh, nn.Hardswish,
      nn.Hardsigmoid, nn.Swish, nn.Silu, nn.LeakyReLU, nn.Softmax), _count_act),
    ((nn.MaxPool2D, nn.AvgPool2D, nn.AdaptiveAvgPool2D), _count_pool),
]


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total FLOPs of one forward at `input_size` (reference:
    hapi/dynamic_flops.py flops). custom_ops: {LayerType: fn(layer, x, y)}."""
    rows = []
    total = [0]
    params_total = [0]
    handles = []

    def _counter_for(layer):
        if custom_ops:
            for cls, fn in custom_ops.items():
                if isinstance(layer, cls):
                    return fn
        for classes, fn in _DEFAULT_COUNTERS:
            # tolerate layer classes absent from some builds
            real = tuple(c for c in classes if isinstance(c, type))
            if isinstance(layer, real):
                return fn
        return None

    def _hook(layer, inputs, output):
        fn = _counter_for(layer)
        if fn is None:
            return
        x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
        y = output[0] if isinstance(output, (tuple, list)) else output
        n = int(fn(layer, x, y))
        p = sum(_prod(q.shape) for q in layer.parameters(include_sublayers=False))
        total[0] += n
        params_total[0] += p
        rows.append((type(layer).__name__, tuple(x.shape), tuple(y.shape), p, n))

    for sub in net.sublayers(include_self=True):
        if not sub._sub_layers:  # leaves only: avoid double counting
            handles.append(sub.register_forward_post_hook(_hook))

    was_training = net.training
    net.eval()
    try:
        x = paddle.to_tensor(np.zeros(input_size, np.float32))
        with paddle.no_grad():
            net(x)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    if print_detail:
        print(f"{'Layer':<24}{'Input':<20}{'Output':<20}{'Params':>10}{'FLOPs':>14}")
        for name, xs, ys, p, n in rows:
            print(f"{name:<24}{str(xs):<20}{str(ys):<20}{p:>10}{n:>14}")
    print(f"Total Flops: {total[0]}     Total Params: {params_total[0]}")
    return total[0]
