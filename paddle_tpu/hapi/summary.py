"""paddle.summary. Reference: python/paddle/hapi/model_summary.py."""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle

from ..nn.layer_base import Layer


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Layer-by-layer output shapes + param counts via forward hooks."""
    rows = []
    hooks = []

    def register(layer, prefix):
        def hook(l, ins, outs):
            out = outs[0] if isinstance(outs, (list, tuple)) else outs
            shape = list(out.shape) if hasattr(out, "shape") else "?"
            n_params = sum(p.size for p in l._parameters.values() if p is not None)
            rows.append((prefix or l.__class__.__name__, l.__class__.__name__, shape, n_params))

        hooks.append(layer.register_forward_post_hook(hook))

    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaves only
            register(sub, name)

    if input is not None:
        x = input
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = input_size if isinstance(input_size, list) else [input_size]
        dts = dtypes if isinstance(dtypes, list) else [dtypes] * len(sizes)
        xs = []
        for s, dt in zip(sizes, dts):
            shape = [1 if (d is None or d == -1) else d for d in s]
            xs.append(paddle.zeros(shape, dtype=dt or "float32"))
        x = xs if len(xs) > 1 else xs[0]

    was_training = net.training
    net.eval()
    try:
        net(*x) if isinstance(x, list) else net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters() if not p.stop_gradient)
    width = max([len(r[0]) for r in rows] + [10])
    lines = [f"{'Layer':<{width}}  {'Type':<20} {'Output Shape':<20} {'Params':>10}"]
    lines.append("-" * (width + 55))
    for name, typ, shape, n in rows:
        lines.append(f"{name:<{width}}  {typ:<20} {str(shape):<20} {n:>10}")
    lines.append("-" * (width + 55))
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": int(total), "trainable_params": int(trainable)}
