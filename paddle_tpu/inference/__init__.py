"""paddle.inference — the deployment predictor API.

Reference analogue: paddle/fluid/inference/api/analysis_predictor.h:90
(AnalysisPredictor), paddle_analysis_config.h (AnalysisConfig), and the
ZeroCopyTensor get/set handles (paddle_tensor.h). The reference pipeline is:
load proto program + params → run ~40 IR analysis/fusion passes → execute on
a naive/graph executor, optionally carving TensorRT subgraphs.

TPU-native design: the "analysis" stage IS XLA — paddle.jit.save already
exported the model as one StableHLO program (every fusion pass the reference
hand-writes is an XLA pass), so the predictor only deserializes the program,
binds the saved weights, and jit-executes. Zero-copy handles hold device
arrays directly; `copy_from_cpu`/`copy_to_cpu` are the only host boundaries.
Shape-polymorphic artifacts (batch-symbolic dims from jit.save) run any batch
size without recompiling the artifact — XLA compiles once per concrete shape
and caches.
"""
from __future__ import annotations

import warnings
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Config",
    "GenerativePredictor",
    "Predictor",
    "PredictorPool",
    "Tensor",
    "create_predictor",
    "PrecisionType",
    "PlaceType",
]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kTPU = 2


class Config:
    """AnalysisConfig analogue (reference: paddle_analysis_config.h).

    Construct from a model path prefix (the `path` given to paddle.jit.save /
    static.save_inference_model). GPU/TensorRT/MKLDNN toggles are accepted
    for script parity; on TPU they either map to the XLA path or no-op with
    a warning.
    """

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # accept either Config(prefix) or Config(prefix+".pdmodel", prefix+".pdparams")
        prefix = prog_file or ""
        for suffix in (".stablehlo", ".pdmodel", ".pdparams"):
            if prefix.endswith(suffix):
                prefix = prefix[: -len(suffix)]
                break
        self._prefix = prefix
        self._device = "tpu"
        self._memory_optim = True
        self._ir_optim = True
        self._threads = 1
        self._generative_model = None
        self._serving_opts: Dict = {}

    # --- model location -------------------------------------------------
    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        """Update the model location; other toggles keep their values."""
        prefix = prog_file
        for suffix in (".stablehlo", ".pdmodel", ".pdparams"):
            if prefix.endswith(suffix):
                prefix = prefix[: -len(suffix)]
                break
        if params_file is not None:
            p = params_file
            for suffix in (".stablehlo", ".pdmodel", ".pdparams"):
                if p.endswith(suffix):
                    p = p[: -len(suffix)]
                    break
            if p != prefix:
                warnings.warn(
                    f"params_file prefix {p!r} differs from prog_file prefix "
                    f"{prefix!r}; paddle_tpu artifacts keep program and params "
                    "under one prefix — using the prog_file prefix"
                )
        self._prefix = prefix

    def model_dir(self) -> str:
        return self._prefix

    def prog_file(self) -> str:
        return self._prefix + ".stablehlo"

    def params_file(self) -> str:
        return self._prefix + ".pdmodel"

    # --- device selection -------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100, device_id: int = 0):
        warnings.warn("enable_use_gpu: no GPU on this platform; using the default accelerator")
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def use_gpu(self) -> bool:
        return False

    def set_cpu_math_library_num_threads(self, n: int):
        self._threads = n

    # --- generative serving (paddle.serving) --------------------------------
    def enable_generative_serving(self, model, **serving_opts):
        """Route this predictor onto the paddle.serving continuous-batching
        engine instead of the plain StableHLO executor: ``model`` is a live
        generative LM (``models.gpt.GPTForPretraining``-shaped — KV-cache
        decode through per-layer cache views). ``serving_opts`` forward to
        ``serving.ServingConfig`` (block_size, prompt_buckets, ...).
        ``enable_memory_optim`` then controls whether the paged KV block
        pool is sized by the memory planner against FLAGS_memory_budget_mb
        (on, the default) or left at the unbudgeted default size (off)."""
        self._generative_model = model
        self._serving_opts = dict(serving_opts)

    def is_generative(self) -> bool:
        return self._generative_model is not None

    # --- optimization toggles (XLA always optimizes; kept for parity) ------
    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag: bool = True):
        """For generative serving predictors this is a REAL knob: on, the
        paged KV block pool is budgeted by the static memory planner
        (analysis.memory.plan_block_pool) and admission is refused past the
        budget; off, the pool takes the unbudgeted default size. For plain
        StableHLO predictors XLA already plans buffers — kept for parity."""
        self._memory_optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        warnings.warn(
            "enable_tensorrt_engine is a no-op on TPU and deprecated here: "
            "the XLA program is already fused; for generative-model serving "
            "use Config.enable_generative_serving (paddle.serving)",
            DeprecationWarning, stacklevel=2,
        )

    def enable_mkldnn(self, *a, **k):
        warnings.warn(
            "enable_mkldnn is a no-op on TPU and deprecated here: XLA owns "
            "kernel selection",
            DeprecationWarning, stacklevel=2,
        )

    def switch_use_feed_fetch_ops(self, flag: bool):
        pass

    def switch_specify_input_names(self, flag: bool = True):
        pass

    def summary(self) -> str:
        return (
            f"Config(prefix={self._prefix!r}, device={self._device}, "
            f"ir_optim={self._ir_optim}, memory_optim={self._memory_optim})"
        )


class Tensor:
    """Zero-copy IO handle (reference: paddle_tensor.h ZeroCopyTensor).

    Holds a device array; copy_from_cpu uploads once, copy_to_cpu is the
    only host read. Distinct from paddle.Tensor on purpose, mirroring the
    reference's separate inference tensor type.
    """

    def __init__(self, name: str, dtype=None, shape=None):
        self._name = name
        self._value = None
        self._dtype = np.dtype(dtype) if dtype is not None else None
        self._declared_shape = shape

    def name(self) -> str:
        return self._name

    def reshape(self, shape):
        """Declare the upcoming input shape (reference keeps explicit reshape
        before copy_from_cpu; here the copy itself fixes the shape, so this
        only validates against the artifact's signature)."""
        self._declared_shape = list(shape)

    def copy_from_cpu(self, data):
        arr = np.asarray(data)
        if self._dtype is not None and arr.dtype != self._dtype:
            arr = arr.astype(self._dtype)
        self._value = jnp.asarray(arr)

    def share_external_data(self, data):
        # device arrays pass through without copy
        self._value = data._value if hasattr(data, "_value") else jnp.asarray(data)

    def copy_to_cpu(self):
        if self._value is None:
            raise RuntimeError(f"output handle '{self._name}' has no data; call run() first")
        return np.asarray(jax.device_get(self._value))

    def shape(self):
        return list(self._value.shape) if self._value is not None else list(self._declared_shape or [])

    def type(self):
        v = self._value
        return str(v.dtype) if v is not None else str(self._dtype)


class Predictor:
    """AnalysisPredictor analogue over a StableHLO artifact.

    reference call path (§3.6): CreatePredictor → Analyzer::Run pass pipeline
    → executor loop. Here: deserialize → jax.jit(exported.call) → one XLA
    execution per run(), weights resident on device.
    """

    def __init__(self, config: Config):
        from ..framework.artifact import load_artifact

        self._config = config
        self._exported, self._state, meta = load_artifact(config._prefix)
        if config._device == "cpu" and jax.default_backend() != "cpu":
            # the artifact is lowered for the platform that exported it; a
            # cross-platform retarget would need re-export, not a device_put
            warnings.warn(
                "disable_gpu(): the artifact runs on the platform it was "
                "exported for; re-export on the target platform to retarget"
            )
        self._input_names: List[str] = list(meta["input_names"])
        self._output_names: List[str] = list(meta["output_names"])
        in_dtypes = meta.get("input_dtypes") or [None] * len(self._input_names)
        in_shapes = meta.get("input_shapes") or [None] * len(self._input_names)
        self._inputs: Dict[str, Tensor] = {
            n: Tensor(n, dt, sh) for n, dt, sh in zip(self._input_names, in_dtypes, in_shapes)
        }
        self._outputs: Dict[str, Tensor] = {n: Tensor(n) for n in self._output_names}
        self._call = jax.jit(self._exported.call)

    # --- handles ---------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    # --- execution ---------------------------------------------------------
    def run(self, inputs=None):
        """Execute the program. Either set input handles beforehand, or pass
        a list of numpy arrays in input order (newer reference API)."""
        if inputs is not None:
            if len(inputs) != len(self._input_names):
                raise ValueError(
                    f"run() got {len(inputs)} inputs; the model has "
                    f"{len(self._input_names)}: {self._input_names}"
                )
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        vals = []
        for n in self._input_names:
            h = self._inputs[n]
            if h._value is None:
                raise RuntimeError(f"input '{n}' not set; call copy_from_cpu first")
            vals.append(h._value)
        out = self._call(*self._state, *vals)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        for n, o in zip(self._output_names, outs):
            self._outputs[n]._value = o
        if inputs is not None:
            return [np.asarray(jax.device_get(o)) for o in outs]
        return True

    def health(self) -> str:
        """Health of the replica behind this predictor. A plain StableHLO
        predictor is stateless — always ``"ready"`` (the serving-backed
        GenerativePredictor reports its engine's live state)."""
        return "ready"

    def serviceable(self) -> bool:
        return True

    def clone(self) -> "Predictor":
        """Share the deserialized program + weights; fresh IO handles
        (reference: AnalysisPredictor::Clone shares the scope/engine)."""
        p = object.__new__(Predictor)
        p._config = self._config
        p._exported = self._exported
        p._state = self._state
        p._input_names = list(self._input_names)
        p._output_names = list(self._output_names)
        p._inputs = {n: Tensor(n, h._dtype, h._declared_shape) for n, h in self._inputs.items()}
        p._outputs = {n: Tensor(n) for n in self._output_names}
        p._call = self._call
        return p

    def try_shrink_memory(self):
        pass


class GenerativePredictor:
    """Predictor-surface adapter over the paddle.serving engine — what
    ``create_predictor`` returns for a Config with
    ``enable_generative_serving`` set. Zero-copy handles stay: feed
    ``input_ids`` ([b, s] int, one prompt per row) and optionally
    ``prompt_lens`` ([b] int true lengths for right-padded rows); after
    ``run()`` the ``tokens`` handle holds [b, max_new] generated ids,
    -1-padded past each row's completion."""

    def __init__(self, config: Config):
        from .. import serving as _serving

        self._config = config
        opts = dict(config._serving_opts)
        self._max_new = int(opts.pop("max_new_tokens", 0)) or None
        self._eos = opts.pop("eos_token_id", None)
        if not config._memory_optim:
            # memory_optim off: skip planner budgeting, take the default pool
            opts.setdefault("num_blocks", 0)
            from ..serving.cache import default_num_blocks

            opts["num_blocks"] = opts["num_blocks"] or default_num_blocks()
        self._engine = _serving.Engine(
            config._generative_model,
            _serving.ServingConfig(**opts) if opts else None,
        )
        self._inputs = {
            "input_ids": Tensor("input_ids", np.int64),
            "prompt_lens": Tensor("prompt_lens", np.int64),
        }
        self._outputs = {"tokens": Tensor("tokens")}

    def get_input_names(self) -> List[str]:
        return ["input_ids", "prompt_lens"]

    def get_output_names(self) -> List[str]:
        return ["tokens"]

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    @property
    def engine(self):
        """The underlying paddle.serving.Engine (stats(), submit(), ...)."""
        return self._engine

    def health(self) -> str:
        """The engine's live health state (warming/ready/degraded/
        draining/dead) — what PredictorPool.acquire routes on."""
        return self._engine.health

    def serviceable(self) -> bool:
        return self._engine.serviceable()

    def run(self, inputs=None):
        if inputs is not None:
            self._inputs["input_ids"].copy_from_cpu(inputs[0])
            if len(inputs) > 1:
                self._inputs["prompt_lens"].copy_from_cpu(inputs[1])
            else:
                # a list-style call without lens must not inherit a stale
                # prompt_lens handle from a previous run
                self._inputs["prompt_lens"]._value = None
        ids_h = self._inputs["input_ids"]
        if ids_h._value is None:
            raise RuntimeError("input 'input_ids' not set; call copy_from_cpu first")
        ids = np.asarray(jax.device_get(ids_h._value))
        if ids.ndim == 1:
            ids = ids[None, :]
        lens_h = self._inputs["prompt_lens"]
        lens = (
            np.asarray(jax.device_get(lens_h._value)).reshape(-1).astype(int)
            if lens_h._value is not None
            else np.full((ids.shape[0],), ids.shape[1], int)
        )
        if lens.shape[0] != ids.shape[0]:
            raise ValueError(
                f"prompt_lens has {lens.shape[0]} entries for a batch of "
                f"{ids.shape[0]} prompts"
            )
        if ((lens < 1) | (lens > ids.shape[1])).any():
            raise ValueError(
                f"prompt_lens entries must be in [1, {ids.shape[1]}] "
                f"(the input_ids width); got {lens.tolist()}"
            )
        prompts = [ids[i, : int(lens[i])] for i in range(ids.shape[0])]
        resps = self._engine.serve(
            prompts, max_new_tokens=self._max_new, eos_token_id=self._eos)
        # fixed documented shape [b, max_new], -1-padded past each row's
        # completion (EOS can end a row early)
        width = self._max_new or self._engine._default_max_new
        out = np.full((len(resps), max(1, width)), -1, np.int64)
        for i, r in enumerate(resps):
            if not r.ok:
                raise RuntimeError(
                    f"serving request {r.request_id} failed: {r.status}: "
                    f"{r.error}"
                )
            out[i, : len(r.tokens)] = r.tokens
        self._outputs["tokens"]._value = jnp.asarray(out)
        if inputs is not None:
            return [out]
        return True

    def clone(self) -> "GenerativePredictor":
        """Share the engine (a serving engine is already a concurrent
        multiplexer); fresh IO handles — the Predictor.clone()/PredictorPool
        contract."""
        p = object.__new__(GenerativePredictor)
        p._config = self._config
        p._max_new = self._max_new
        p._eos = self._eos
        p._engine = self._engine
        p._inputs = {
            "input_ids": Tensor("input_ids", np.int64),
            "prompt_lens": Tensor("prompt_lens", np.int64),
        }
        p._outputs = {"tokens": Tensor("tokens")}
        return p

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config):
    """reference: paddle_infer::CreatePredictor (inference/api/paddle_inference_api.h).
    A Config with ``enable_generative_serving(model)`` routes onto the
    paddle.serving continuous-batching engine; otherwise the StableHLO
    artifact predictor loads as before."""
    if config.is_generative():
        return GenerativePredictor(config)
    return Predictor(config)


class DataType:
    """reference: paddle_infer.DataType enum."""

    FLOAT32 = "float32"
    FLOAT16 = "float16"
    INT8 = "int8"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    BOOL = "bool"


_DTYPE_BYTES = {
    DataType.FLOAT32: 4, DataType.FLOAT16: 2, DataType.INT8: 1,
    DataType.INT32: 4, DataType.INT64: 8, DataType.UINT8: 1, DataType.BOOL: 1,
}


def get_num_bytes_of_data_type(dtype) -> int:
    return _DTYPE_BYTES[dtype]


def get_version() -> str:
    from .. import __version__

    return f"paddle_tpu inference {__version__} (StableHLO/XLA)"


def get_trt_compile_version():
    """No TensorRT in an XLA/TPU build (reference returns the linked TRT
    version; the portable artifact here is StableHLO)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


class PredictorPool:
    """Pool of predictors for concurrent serving (reference:
    paddle_infer.PredictorPool over AnalysisPredictor::Clone).

    ``clone=True`` (the default, the reference contract) shares the
    loaded program/engine across the pool; ``clone=False`` builds
    independent replicas via ``create_predictor`` — for generative
    serving configs that means one Engine each, which is what makes the
    health-aware routing in :meth:`acquire` meaningful (clones of one
    engine get sick together)."""

    def __init__(self, config: Config, size: int = 1, clone: bool = True):
        if size < 1:
            raise ValueError("pool size must be >= 1")
        first = create_predictor(config)
        if clone:
            rest = [first.clone() for _ in range(size - 1)]
        else:
            rest = [create_predictor(config) for _ in range(size - 1)]
        self._predictors = [first] + rest
        self._rr = 0

    def retrieve(self, idx: int) -> Predictor:
        return self._predictors[idx]

    def acquire(self) -> Predictor:
        """The next predictor that will accept work, round-robin, routing
        around unhealthy replicas: draining/dead engines are skipped, and
        'ready'/'warming' replicas are preferred over 'degraded' ones (a
        degraded replica still serves when it is all that's left). The
        policy is the serving FrontDoor's health-preference rule
        (serving.frontdoor.pick_serviceable) — the pool is a thin shim
        over the fleet router's routing, not a second copy of it. Raises
        when every replica is dead/draining — fail loud, never hang."""
        from ..serving.frontdoor import pick_serviceable

        idx = pick_serviceable(self._predictors, rr=self._rr)
        if idx is None:
            raise RuntimeError(
                "PredictorPool.acquire: no serviceable replica "
                f"(healths: {[p.health() for p in self._predictors]})")
        self._rr = (idx + 1) % len(self._predictors)
        return self._predictors[idx]

    def healths(self) -> List[str]:
        return [p.health() for p in self._predictors]

    def __len__(self):
        return len(self._predictors)
