"""paddle.device — device management + memory statistics facade.

Reference analogue: python/paddle/device/ (set_device/get_device,
device/cuda/ memory APIs over memory/stats.cc + allocator facade). On TPU
the PJRT runtime owns allocation; the stats facade reads
Device.memory_stats() so users get the reference's memory introspection
surface (SURVEY §1 L1) without a custom allocator.
"""
from __future__ import annotations

import jax

from ..core.place import Place, get_device, set_device  # noqa: F401

__all__ = [
    "set_device",
    "get_device",
    "get_all_device_type",
    "get_available_device",
    "memory_allocated",
    "max_memory_allocated",
    "memory_reserved",
    "cuda",
]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_available_device():
    return [f"{d.platform}:{i}" for i, d in enumerate(jax.devices())]


def _device(device_id=None):
    devs = jax.devices()
    return devs[device_id or 0]


def _stat(name: str, device_id=None, default=0):
    stats = _device(device_id).memory_stats() or {}
    return int(stats.get(name, default))


def memory_allocated(device=None) -> int:
    """Live bytes on the device (reference: paddle.device.cuda.memory_allocated
    over memory/stats.cc Allocated stat)."""
    return _stat("bytes_in_use", device)


def max_memory_allocated(device=None) -> int:
    return _stat("peak_bytes_in_use", device)


def memory_reserved(device=None) -> int:
    """Total reservable pool (PJRT preallocates; falls back to bytes_limit)."""
    stats = _device(device).memory_stats() or {}
    return int(
        stats.get("bytes_reserved", stats.get("bytes_limit", 0))
    )


def max_memory_reserved(device=None) -> int:
    return _stat("peak_bytes_reserved", device, memory_reserved(device))


class _CudaNamespace:
    """paddle.device.cuda API-parity shim — maps to the default accelerator."""

    @staticmethod
    def device_count():
        return len(jax.devices())

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)

    @staticmethod
    def empty_cache():
        # PJRT owns the pool; nothing to drop eagerly
        pass

    @staticmethod
    def synchronize(device=None):
        for d in jax.live_arrays():
            d.block_until_ready()


cuda = _CudaNamespace()
