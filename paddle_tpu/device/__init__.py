"""paddle.device — device management + memory statistics facade.

Reference analogue: python/paddle/device/ (set_device/get_device,
device/cuda/ memory APIs over memory/stats.cc + allocator facade). On TPU
the PJRT runtime owns allocation; the stats facade reads
Device.memory_stats() so users get the reference's memory introspection
surface (SURVEY §1 L1) without a custom allocator.
"""
from __future__ import annotations

import jax

from ..core.place import (  # noqa: F401
    CUDAPlace,
    IPUPlace,
    MLUPlace,
    Place,
    XPUPlace,
    get_device,
    set_device,
)

__all__ = [
    "set_device",
    "get_device",
    "get_all_device_type",
    "get_available_device",
    "memory_allocated",
    "max_memory_allocated",
    "memory_reserved",
    "synchronize",
    "cuda",
]


def synchronize(device=None):
    """Block until all queued device work is done.

    Also a lazy-dispatch materialization point: any pending deferred-eager
    segment (FLAGS_eager_lazy_dispatch) is flushed as one program first, and
    every in-flight background compile (FLAGS_eager_async_compile) is
    joined, so after synchronize() every live Tensor holds a concrete,
    ready array and no host-pipeline work remains outstanding.
    """
    from ..core import lazy

    lazy.flush_if_pending("explicit_sync")
    lazy.drain_async()
    for arr in jax.live_arrays():
        arr.block_until_ready()


# "compiled with" probes (reference: python/paddle/device/__init__.py) —
# this is an XLA/TPU build, so every vendor-specific probe answers False
# honestly rather than raising.
def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


def get_cudnn_version():
    """None — no cuDNN in an XLA/TPU build (reference returns None when
    CUDA is absent)."""
    return None


def get_all_custom_device_type():
    """Non-(cpu|gpu) PJRT platforms play the CustomDevice role here."""
    return sorted(
        {d.platform for d in jax.devices()} - {"cpu", "gpu", "cuda"}
    )


def get_available_custom_device():
    return [
        f"{d.platform}:{d.id}"
        for d in jax.devices()
        if d.platform not in ("cpu", "gpu", "cuda")
    ]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_available_device():
    return [f"{d.platform}:{i}" for i, d in enumerate(jax.devices())]


def _device_index(device):
    """Accept int, 'platform:idx' string, or Place-like with _device_id."""
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    if isinstance(device, str):
        tail = device.rsplit(":", 1)[-1]
        return int(tail) if tail.isdigit() else 0
    return int(getattr(device, "_device_id", 0))


def _device(device_id=None):
    devs = jax.devices()
    return devs[_device_index(device_id)]


def _stat(name: str, device_id=None, default=0):
    stats = _device(device_id).memory_stats() or {}
    return int(stats.get(name, default))


def memory_allocated(device=None) -> int:
    """Live bytes on the device (reference: paddle.device.cuda.memory_allocated
    over memory/stats.cc Allocated stat)."""
    return _stat("bytes_in_use", device)


def max_memory_allocated(device=None) -> int:
    return _stat("peak_bytes_in_use", device)


def memory_reserved(device=None) -> int:
    """Total reservable pool (PJRT preallocates; falls back to bytes_limit)."""
    stats = _device(device).memory_stats() or {}
    return int(
        stats.get("bytes_reserved", stats.get("bytes_limit", 0))
    )


def max_memory_reserved(device=None) -> int:
    return _stat("peak_bytes_reserved", device, memory_reserved(device))


class Stream:
    """API-parity stream object (reference: device/cuda/streams.py Stream).

    XLA owns scheduling on TPU — there is one logical compute stream per
    device — so streams are identity objects: recordable, waitable,
    synchronizable, but not reorderable."""

    def __init__(self, device=None, priority=2):
        self.device = device
        self.priority = priority

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def synchronize(self):
        _CudaNamespace.synchronize(self.device)

    def query(self):
        return True


class Event:
    """API-parity event (reference: device/cuda/streams.py Event)."""

    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self):
        return True

    def synchronize(self):
        _CudaNamespace.synchronize()


_default_stream = Stream()


def current_stream(device=None):
    return _default_stream


class stream_guard:
    """Context manager selecting a stream (no-op under XLA scheduling)."""

    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


def get_device_name(device=None):
    d = _device(device)
    return getattr(d, "device_kind", d.platform)


def get_device_capability(device=None):
    """No CUDA compute capability on TPU; report (0, 0) like non-CUDA builds."""
    return (0, 0)


def get_device_properties(device=None):
    d = _device(device)
    stats = d.memory_stats() or {}

    class _Props:
        name = getattr(d, "device_kind", d.platform)
        major, minor = 0, 0
        total_memory = int(stats.get("bytes_limit", 0))
        multi_processor_count = 1

        def __repr__(self):
            return (
                f"_CudaDeviceProperties(name='{self.name}', "
                f"total_memory={self.total_memory})"
            )

    return _Props()


class _CudaNamespace:
    """paddle.device.cuda API-parity shim — maps to the default accelerator."""

    Stream = Stream
    Event = Event
    current_stream = staticmethod(current_stream)
    stream_guard = stream_guard
    get_device_name = staticmethod(get_device_name)
    get_device_capability = staticmethod(get_device_capability)
    get_device_properties = staticmethod(get_device_properties)

    @staticmethod
    def device_count():
        return len(jax.devices())

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)

    @staticmethod
    def empty_cache():
        # PJRT owns the pool; nothing to drop eagerly
        pass

    @staticmethod
    def synchronize(device=None):
        synchronize(device)


cuda = _CudaNamespace()
