"""python -m paddle_tpu.distributed.launch — multi-process/multi-host launcher.

Reference analogue: python/paddle/distributed/launch/ (Context
context/__init__.py:24, CollectiveController controllers/collective.py:23
build_pod:32, master KV controllers/master.py).
"""
from .main import launch, main  # noqa: F401
