"""Launcher implementation.

Reference analogue: launch/main.py:18 + controllers/collective.py.

TPU-native topology note: ONE process drives all chips of a host
(single-controller JAX), so `--nproc_per_node` defaults to 1 (the reference
spawns one process per GPU). Multi-host jobs launch one controller per host;
rendezvous uses the JAX coordination service at --master (the TCPStore
replacement). Env contract kept verbatim: PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS, PADDLE_CURRENT_ENDPOINT,
PADDLE_MASTER.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


class Container:
    """One managed child process (reference: launch/job/container.py)."""

    def __init__(self, cmd: List[str], env: dict, log_path: Optional[str] = None):
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._log_f = None

    def start(self):
        out = None
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
            # append: an elastic relaunch must not truncate the previous
            # attempt's crash log
            self._log_f = open(self.log_path, "a")
            out = self._log_f
        self.proc = subprocess.Popen(
            self.cmd, env=self.env, stdout=out, stderr=subprocess.STDOUT
        )

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    @property
    def exit_code(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self._log_f:
            self._log_f.close()


class Pod:
    """The set of containers this node runs (reference: launch/job/pod.py)."""

    def __init__(self):
        self.containers: List[Container] = []

    def add(self, c: Container):
        self.containers.append(c)

    def deploy(self):
        for c in self.containers:
            c.start()

    def watch(self, restart: bool = False) -> int:
        """Watch children; on failure kill the pod (elastic relaunch is the
        manager's job — fleet/elastic)."""
        try:
            while True:
                codes = [c.exit_code for c in self.containers]
                if all(code == 0 for code in codes):
                    return 0
                bad = [code for code in codes if code not in (None, 0)]
                if bad:
                    self.stop()
                    return bad[0]
                time.sleep(1)
        except KeyboardInterrupt:
            self.stop()
            return 1

    def stop(self):
        for c in self.containers:
            c.terminate()


class Context:
    """reference: launch/context/__init__.py:24 — args + env + device info."""

    def __init__(self, args=None):
        self.args = args
        self.envs = dict(os.environ)


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a (multi-host) paddle_tpu training job",
    )
    p.add_argument("--master", default=None,
                   help="coordination address: ip:port (JAX coordination "
                        "service), kv://ip:port (TCP lease/KV master — "
                        "pods DISCOVER each other's endpoints through it, "
                        "reference launch/controllers/master.py), or "
                        "'auto' (this node starts the KV master)")
    p.add_argument("--nnodes", type=int, default=int(os.getenv("PADDLE_NNODES", "1")))
    p.add_argument("--rank", type=int, default=int(os.getenv("PADDLE_RANK", "-1")),
                   help="node rank; -1 = from env/auto")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 = single controller for all local chips)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", "--tpus", "--gpus", dest="devices", default=None)
    p.add_argument("--run_mode", default="collective", choices=["collective", "ps"])
    p.add_argument("--max_restart", type=int,
                   default=int(os.getenv("PADDLE_ELASTIC_MAX_RESTART", "0")),
                   help=">0 enables elastic fault recovery (whole-pod relaunch)")
    p.add_argument("--elastic_level", type=int,
                   default=int(os.getenv("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1")))
    p.add_argument("--server_num", type=int, default=0)
    p.add_argument("--trainer_num", type=int, default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


# a node's discovery key stays alive only while ITS sync loop refreshes
# it: a dead or relaunched build's stale endpoints age out within this
# window instead of being adopted by a rebuilding peer
_SYNC_TTL_S = 15.0


def _sync_endpoints_via_master(kv_ep: str, args, node_rank: int,
                               nproc: int, timeout: float = 60.0):
    """Endpoint discovery through the TCP KV master (reference:
    launch/controllers/master.py sync_peers over etcd/http): every node
    publishes its real endpoints under launch/<job>/<rank> with a SHORT
    lease it keeps refreshing while waiting, and completes when all
    nnodes ranks are simultaneously alive — no pre-agreed port scheme,
    and no cross-host build counters to drift (a crashed build stops
    refreshing, so its stale ports expire within _SYNC_TTL_S; a
    rebuilding node simply waits for its peers' fresh keys)."""
    from ..compat import find_free_ports
    from ..ps import PsClient

    kv = PsClient([kv_ep])
    host = os.getenv("POD_IP", "127.0.0.1")
    ports = find_free_ports(nproc)
    if not ports:
        raise RuntimeError("launch master sync: no free ports")
    my_eps = [f"{host}:{p}" for p in sorted(ports)]
    key_prefix = f"launch/{args.job_id}/"
    my_key = f"{key_prefix}{node_rank}"
    t0 = time.time()
    while True:
        kv.kv_lease(my_key, ",".join(my_eps), ttl_s=_SYNC_TTL_S)
        seen = kv.kv_alive(key_prefix)
        if all(f"{key_prefix}{r}" in seen for r in range(args.nnodes)):
            break
        if time.time() - t0 > timeout:
            raise TimeoutError(
                f"launch master sync: {len(seen)}/{args.nnodes} nodes "
                f"registered within {timeout}s: {sorted(seen)}"
            )
        time.sleep(0.2)
    endpoints = []
    for r in range(args.nnodes):
        endpoints.extend(seen[f"{key_prefix}{r}"].split(","))
    return endpoints


def _build_pod_collective(args) -> Pod:
    """reference: controllers/collective.py:32 build_pod."""
    pod = Pod()
    nnodes = args.nnodes
    node_rank = args.rank if args.rank >= 0 else 0
    nproc = args.nproc_per_node
    world = nnodes * nproc
    kv_ep = getattr(args, "_kv_master", None)
    if kv_ep:
        endpoints = _sync_endpoints_via_master(kv_ep, args, node_rank, nproc)
        # process-0's endpoint doubles as the JAX coordination address
        master = endpoints[0]
    else:
        master = args.master or "127.0.0.1:49170"
        base_port = 49171
        endpoints = []
        for node in range(nnodes):
            host = "127.0.0.1" if nnodes == 1 else f"node{node}"
            for i in range(nproc):
                endpoints.append(f"{host}:{base_port + i}")

    for local in range(nproc):
        rank = node_rank * nproc + local
        env = dict(os.environ)
        env.update(
            {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
                "PADDLE_MASTER": master,
                "PADDLE_JOB_ID": args.job_id,
                "FLAGS_selected_tpus": str(local),
            }
        )
        cmd = [sys.executable, "-u", args.training_script] + list(
            args.training_script_args or []
        )
        log = os.path.join(args.log_dir, f"workerlog.{rank}")
        pod.add(Container(cmd, env, log))
    return pod


def _build_pod_ps(args) -> Pod:
    """reference: controllers/ps.py — servers + trainers on one node."""
    pod = Pod()
    server_num = args.server_num or 1
    trainer_num = args.trainer_num or 1
    base = 49300
    server_eps = [f"127.0.0.1:{base + i}" for i in range(server_num)]
    trainer_eps = [f"127.0.0.1:{base + 100 + i}" for i in range(trainer_num)]
    for role, count, eps in (
        ("PSERVER", server_num, server_eps),
        ("TRAINER", trainer_num, trainer_eps),
    ):
        for i in range(count):
            env = dict(os.environ)
            env.update(
                {
                    "TRAINING_ROLE": role,
                    "PADDLE_PORT": eps[i].split(":")[1],
                    "POD_IP": "127.0.0.1",
                    "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(server_eps),
                    "PADDLE_TRAINER_ENDPOINTS": ",".join(trainer_eps),
                    "PADDLE_TRAINERS_NUM": str(trainer_num),
                    "PADDLE_TRAINER_ID": str(i),
                }
            )
            cmd = [sys.executable, "-u", args.training_script] + list(
                args.training_script_args or []
            )
            log = os.path.join(args.log_dir, f"{role.lower()}log.{i}")
            pod.add(Container(cmd, env, log))
    return pod


def launch(argv=None) -> int:
    args = _parse_args(argv)

    # --master auto | kv://host:port: the TCP lease/KV master serves
    # endpoint discovery (and elastic membership when --max_restart > 0)
    kv_server = None
    args._kv_master = None
    if args.master == "auto":
        from ..fleet.elastic import start_master

        kv_server = start_master(0)
        args._kv_master = f"127.0.0.1:{kv_server.port}"
        print(f"launch: KV master at {args._kv_master}")
    elif args.master and args.master.startswith("kv://"):
        args._kv_master = args.master[len("kv://"):]

    def build():
        return (
            _build_pod_collective(args)
            if args.run_mode == "collective"
            else _build_pod_ps(args)
        )

    if args.max_restart > 0:
        from ..fleet.elastic import ElasticManager

        mgr = ElasticManager(
            build,
            job_id=args.job_id,
            max_restarts=args.max_restart,
            fault_tolerance_level=args.elastic_level,
            master=args._kv_master,
        )
        mgr.launch()

        def _sig_e(*_):
            mgr.pod.stop()
            sys.exit(1)

        signal.signal(signal.SIGTERM, _sig_e)
        return mgr.watch()

    pod = build()
    pod.deploy()

    def _sig(*_):
        pod.stop()
        sys.exit(1)

    signal.signal(signal.SIGTERM, _sig)
    return pod.watch()


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
