"""Parallel environment + DataParallel.

Reference analogue: python/paddle/distributed/parallel.py
(init_parallel_env:91, ParallelEnv) and python/paddle/fluid/dygraph/
parallel.py:413 (DataParallel with C++ Reducer bucketed allreduce).

TPU-native: a single controller drives all local devices, so
init_parallel_env maps to (a) jax.distributed.initialize for multi-host
(rendezvous via the JAX coordination service — the TCPStore replacement,
SURVEY.md §2.C) and (b) installing the global device mesh. DataParallel
keeps the wrapper API; gradient synchronization is the mesh's job — the
compiled train step shards the batch over `dp` and XLA inserts the gradient
all-reduce (the Reducer's bucketing/overlap is XLA latency-hiding now).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ..nn.layer_base import Layer
from ..parallel.topology import init_mesh

__all__ = ["ParallelEnv", "init_parallel_env", "get_rank", "get_world_size", "DataParallel", "spawn"]


class ParallelEnv:
    """reference: parallel.py ParallelEnv — env-var view of the launch."""

    def __init__(self):
        self._rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._device_id = int(os.getenv("FLAGS_selected_tpus", os.getenv("FLAGS_selected_gpus", "0")).split(",")[0])
        self._trainer_endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    @property
    def current_endpoint(self):
        return self._current_endpoint

    # legacy names
    local_rank = rank
    nranks = world_size
    dev_id = device_id


def get_rank(group=None) -> int:
    """Process index (multi-host) — single-controller SPMD is process 0."""
    try:
        return jax.process_index()
    except RuntimeError:
        return int(os.getenv("PADDLE_TRAINER_ID", "0"))


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    try:
        return jax.process_count()
    except RuntimeError:
        return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def init_parallel_env():
    """reference: parallel.py:91 — env checks, device binding, TCPStore
    rendezvous, default NCCL group. TPU: initialize the JAX distributed
    service if a multi-host env contract is present, then install a
    data-parallel mesh over all visible devices."""
    env = ParallelEnv()
    # is_initialized() (not process_count()) — a backend-touching probe here
    # would make the subsequent initialize() impossible
    already = getattr(jax.distributed, "is_initialized", lambda: False)()
    if env.world_size > 1 and os.getenv("PADDLE_MASTER") and not already:
        # CPU cross-process collectives ride Gloo (the reference's CPU
        # ProcessGroupGloo role); TPU rides ICI/DCN natively. Set it
        # unconditionally (it only affects the cpu backend) and before the
        # backend comes up.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        # multi-host rendezvous: coordination service replaces TCPStore
        jax.distributed.initialize(
            coordinator_address=os.environ["PADDLE_MASTER"],
            num_processes=env.world_size,
            process_id=env.rank,
        )
    init_mesh(dp=len(jax.devices()))
    from .collective import _ensure_default

    _ensure_default()
    return ParallelEnv()


class DataParallel(Layer):
    """reference: fluid/dygraph/parallel.py:413.

    Wrapping keeps script parity; the gradient all-reduce happens in the
    compiled step via batch sharding over `dp` (see
    parallel/sharding.py ShardedTrainStep). In pure-eager single-process
    mode there is nothing to synchronize, matching reference behavior with
    world_size 1.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    @property
    def parameters_(self):
        return self._layers.parameters()

    def no_sync(self):
        import contextlib

        return contextlib.nullcontext()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: distributed/spawn.py — single-controller SPMD drives all
    local devices from one process, so spawn degenerates to a direct call
    (kept for script parity; multi-host uses paddle.distributed.launch)."""
    func(*args)
