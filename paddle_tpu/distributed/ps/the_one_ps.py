"""paddle.distributed.ps.the_one_ps — PS table model (reference:
python/paddle/distributed/ps/the_one_ps.py:816 TheOnePSRuntime + table
class hierarchy). The working runtime/table live in this package's
__init__ (C++ MemorySparseTable + TheOnePSRuntime); these classes carry
the reference's table-proto configuration surface.
"""
from __future__ import annotations

from . import MemorySparseTable, TheOnePSRuntime  # noqa: F401

__all__ = [
    "Table", "SparseTable", "GeoSparseTable", "DenseTable", "TensorTable",
    "BarrierTable",
]


class Table:
    """Base table config (reference: the_one_ps.py Table)."""

    def __init__(self):
        self.table_class = None
        self.shard_num = -1
        self.type = None
        self.accessor = None
        self.common = None
        self.tensor = None

    def _set(self, table_proto):
        for k, v in self.__dict__.items():
            if v is not None and hasattr(table_proto, k):
                setattr(table_proto, k, v)


class SparseTable(Table):
    """reference: the_one_ps.py SparseTable (MemorySparseTable config)."""

    def __init__(self, context=None, send_ctx=None):
        super().__init__()
        self.table_class = "MemorySparseTable"
        self.type = "PS_SPARSE_TABLE"
        self.context = context
        self.send_ctx = send_ctx
        self.shard_num = 32

    def instantiate(self, emb_dim, **kwargs):
        return MemorySparseTable(emb_dim, shard_num=self.shard_num, **kwargs)


class GeoSparseTable(SparseTable):
    """reference: the_one_ps.py GeoSparseTable (geo-async sparse)."""

    def __init__(self, context=None, send_ctx=None):
        super().__init__(context, send_ctx)
        self.table_class = "MemorySparseGeoTable"


class DenseTable(Table):
    """reference: the_one_ps.py DenseTable."""

    def __init__(self, context=None, send_ctx=None):
        super().__init__()
        self.table_class = "MemoryDenseTable"
        self.type = "PS_DENSE_TABLE"
        self.shard_num = 256


class TensorTable(Table):
    """reference: the_one_ps.py TensorTable."""

    def __init__(self, idx=0, tensor_dict=None, role_maker=None):
        super().__init__()
        self.table_class = "TensorTable"
        self.type = "PS_OTHER_TABLE"
        self.idx = idx
        self.tensor_dict = tensor_dict or {}


class BarrierTable(Table):
    """reference: the_one_ps.py BarrierTable (trainer sync)."""

    def __init__(self, context=None, idx=0):
        super().__init__()
        self.table_class = "BarrierTable"
        self.type = "PS_OTHER_TABLE"
        self.idx = idx
