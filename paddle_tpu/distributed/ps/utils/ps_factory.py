"""paddle.distributed.ps.utils.ps_factory — PS program-builder selection.

Reference analogue: python/paddle/distributed/ps/utils/ps_factory.py — the
builders rewrite static programs per PS mode (sync/async/geo/gpu/fl).
Program rewriting is GSPMD/XLA's job here, so each builder carries the
mode decision and compiles the attrs into the runtime config the
TheOnePSRuntime consumes.
"""
from __future__ import annotations

__all__ = [
    "PsProgramBuilder", "GeoPsProgramBuilder", "CpuSyncPsProgramBuilder",
    "CpuAsyncPsProgramBuilder", "GpuPsProgramBuilder",
    "HeterAsyncPsProgramBuilder", "FlPsProgramBuilder",
    "PsProgramBuilderFactory",
]


class PsProgramBuilder:
    mode = "sync"

    def __init__(self, pass_ctx):
        self.pass_ctx = pass_ctx
        self.attrs = (pass_ctx.get_attr("attrs", {})
                      if hasattr(pass_ctx, "get_attr") else dict(pass_ctx or {}))

    def _build_trainer_programs(self):
        pass

    def _build_pserver_programs(self):
        pass

    def _build_programs(self):
        self.attrs["ps_mode"] = self.mode
        self._build_trainer_programs()
        self._build_pserver_programs()
        return self.attrs


class CpuSyncPsProgramBuilder(PsProgramBuilder):
    mode = "sync"


class CpuAsyncPsProgramBuilder(PsProgramBuilder):
    mode = "async"


class GeoPsProgramBuilder(PsProgramBuilder):
    mode = "geo"


class GpuPsProgramBuilder(PsProgramBuilder):
    mode = "gpups"


class HeterAsyncPsProgramBuilder(PsProgramBuilder):
    mode = "heter"


class FlPsProgramBuilder(PsProgramBuilder):
    mode = "fl"


class PsProgramBuilderFactory:
    """reference: ps_factory.py — pick the builder from the strategy."""

    def _create_ps_program_builder(self, pass_ctx):
        attrs = (pass_ctx.get_attr("attrs", {})
                 if hasattr(pass_ctx, "get_attr") else dict(pass_ctx or {}))
        mode = str(attrs.get("ps_mode", "sync")).lower()
        cls = {
            "sync": CpuSyncPsProgramBuilder,
            "async": CpuAsyncPsProgramBuilder,
            "geo": GeoPsProgramBuilder,
            "gpups": GpuPsProgramBuilder,
            "heter": HeterAsyncPsProgramBuilder,
            "fl": FlPsProgramBuilder,
        }.get(mode, CpuSyncPsProgramBuilder)
        return cls(pass_ctx)
