"""PsService — multi-host parameter-server transport (ctypes facades).

Reference analogue:
  - paddle/fluid/distributed/ps/service/brpc_ps_server.h  (PsService RPC
    server dispatching pull/push/barrier/save/load onto table shards);
  - paddle/fluid/distributed/ps/service/brpc_ps_client.h  (per-server
    channels, hash key partitioning, fan-out + region reassembly);
  - ps/service/communicator/communicator.h (sync/async/geo push modes).

TPU-native design: the dense model runs on chips under XLA; the sparse/PS
side is host C++ (csrc/ps_server.cc, csrc/ps_client.cc) speaking a framed
binary protocol over TCP — localhost in tests, DCN across hosts. ctypes
calls release the GIL, so trainer compute overlaps RPC.
"""
from __future__ import annotations

import ctypes
import os
import queue
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "PsServer",
    "PsClient",
    "DistributedSparseTable",
    "GeoDistributedSparseTable",
    "DenseTableHandle",
    "Communicator",
    "SparsePipeline",
]

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")
_DEPENDS = [
    os.path.join(_CSRC, "ps_net.h"),
    os.path.join(_CSRC, "ps_sparse_table.h"),
    os.path.join(_CSRC, "ps_dense_table.h"),
]

_server_lib = None
_client_lib = None


def _load_server_lib():
    global _server_lib
    if _server_lib is None:
        from ...utils import cpp_extension

        lib = cpp_extension.load(
            "ps_server", [os.path.join(_CSRC, "ps_server.cc")], depends=_DEPENDS
        )
        lib.ps_server_create.restype = ctypes.c_void_p
        lib.ps_server_create.argtypes = [ctypes.c_int] * 4
        lib.ps_server_port.restype = ctypes.c_int
        lib.ps_server_port.argtypes = [ctypes.c_void_p]
        lib.ps_server_wait.argtypes = [ctypes.c_void_p]
        lib.ps_server_stop.argtypes = [ctypes.c_void_p]
        lib.ps_server_destroy.argtypes = [ctypes.c_void_p]
        _server_lib = lib
    return _server_lib


def _load_client_lib():
    global _client_lib
    if _client_lib is None:
        from ...utils import cpp_extension

        lib = cpp_extension.load(
            "ps_client", [os.path.join(_CSRC, "ps_client.cc")], depends=_DEPENDS
        )
        lib.ps_client_create.restype = ctypes.c_void_p
        lib.ps_client_create.argtypes = [ctypes.c_char_p]
        lib.ps_client_destroy.argtypes = [ctypes.c_void_p]
        lib.ps_client_n_servers.restype = ctypes.c_int
        lib.ps_client_n_servers.argtypes = [ctypes.c_void_p]
        lib.ps_client_ping.restype = ctypes.c_int
        lib.ps_client_ping.argtypes = [ctypes.c_void_p]
        lib.ps_client_create_sparse.restype = ctypes.c_int
        lib.ps_client_create_sparse.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_uint64,
        ]
        lib.ps_client_create_dense.restype = ctypes.c_int
        lib.ps_client_create_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int64, ctypes.c_int,
            ctypes.c_float, ctypes.c_void_p,
        ]
        lib.ps_client_pull_sparse.restype = ctypes.c_int
        lib.ps_client_pull_sparse.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.ps_client_push_sparse.restype = ctypes.c_int
        lib.ps_client_push_sparse.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.ps_client_pull_dense.restype = ctypes.c_int
        lib.ps_client_pull_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.ps_client_push_dense.restype = ctypes.c_int
        lib.ps_client_push_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.ps_client_set_dense.restype = ctypes.c_int
        lib.ps_client_set_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.ps_client_push_pull_dense.restype = ctypes.c_int
        lib.ps_client_push_pull_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.ps_client_barrier.restype = ctypes.c_int
        lib.ps_client_barrier.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ps_client_save.restype = ctypes.c_int
        lib.ps_client_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ps_client_load.restype = ctypes.c_int
        lib.ps_client_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ps_client_stat.restype = ctypes.c_int64
        lib.ps_client_stat.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.ps_client_set_lr.restype = ctypes.c_int
        lib.ps_client_set_lr.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_float,
        ]
        lib.ps_client_stop_servers.restype = ctypes.c_int
        lib.ps_client_stop_servers.argtypes = [ctypes.c_void_p]
        lib.ps_client_set_ctr.restype = ctypes.c_int
        lib.ps_client_set_ctr.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
        ] + [ctypes.c_float] * 5
        lib.ps_client_push_ctr.restype = ctypes.c_int
        lib.ps_client_push_ctr.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.ps_client_shrink.restype = ctypes.c_int64
        lib.ps_client_shrink.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.ps_client_ctr_stats.restype = ctypes.c_int
        lib.ps_client_ctr_stats.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.ps_client_kv_put.restype = ctypes.c_int
        lib.ps_client_kv_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.ps_client_kv_lease.restype = ctypes.c_int
        lib.ps_client_kv_lease.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.ps_client_kv_get.restype = ctypes.c_int64
        lib.ps_client_kv_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.ps_client_kv_del.restype = ctypes.c_int
        lib.ps_client_kv_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ps_client_kv_alive.restype = ctypes.c_int64
        lib.ps_client_kv_alive.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_int64,
        ]
        _client_lib = lib
    return _client_lib


_OPT_IDS = {"sgd": 0, "adagrad": 1, "adam": 2}
_DENSE_OPT_IDS = {"sgd": 0, "adam": 1, "sum": 2}


class PsServer:
    """One parameter-server process (reference: BrpcPsServer)."""

    def __init__(self, port: int = 0, server_id: int = 0, n_servers: int = 1,
                 n_trainers: int = 1):
        self._lib = _load_server_lib()
        self._h = self._lib.ps_server_create(
            int(port), int(server_id), int(n_servers), int(n_trainers)
        )
        if not self._h:
            raise RuntimeError(f"PsServer failed to bind port {port}")
        self.server_id = server_id

    @property
    def port(self) -> int:
        return self._lib.ps_server_port(self._h)

    def wait(self):
        """Block until a STOP arrives (fleet.run_server loop)."""
        self._lib.ps_server_wait(self._h)

    def stop(self):
        if self._h:
            self._lib.ps_server_stop(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ps_server_destroy(self._h)
                self._h = None
        except Exception:
            pass


class PsClient:
    """Trainer-side stub for the whole server fleet (reference: BrpcPsClient)."""

    def __init__(self, endpoints: Sequence[str], trainer_id: int = 0):
        self._lib = _load_client_lib()
        self.endpoints = list(endpoints)
        self.trainer_id = trainer_id
        self._h = self._lib.ps_client_create(",".join(self.endpoints).encode())
        if not self._h:
            raise RuntimeError(f"PsClient: bad endpoints {endpoints}")
        self._dense_meta: Dict[int, int] = {}  # table_id -> length

    # -- lifecycle -----------------------------------------------------------
    def ping(self):
        if self._lib.ps_client_ping(self._h) != 0:
            raise ConnectionError(f"ping failed for {self.endpoints}")

    # -- KV / lease (the etcd replacement: elastic membership + launch
    # master endpoint discovery; all keys live on server 0) -------------------
    def kv_put(self, key: str, value: str):
        v = value.encode()
        if self._lib.ps_client_kv_put(self._h, key.encode(), v,
                                      len(v)) != 0:
            raise ConnectionError(f"kv_put({key}) failed")

    def kv_lease(self, key: str, value: str, ttl_s: float):
        """Register key with a TTL; re-lease to refresh (etcd lease)."""
        v = value.encode()
        if self._lib.ps_client_kv_lease(
                self._h, key.encode(), v, len(v),
                int(ttl_s * 1000)) != 0:
            raise ConnectionError(f"kv_lease({key}) failed")

    def kv_get(self, key: str, cap: int = 1 << 16):
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.ps_client_kv_get(self._h, key.encode(), buf, cap)
        if n == -1:
            return None  # absent or lease expired
        if n < 0:
            raise ConnectionError(f"kv_get({key}) failed ({n})")
        return buf.raw[:n].decode()

    def kv_del(self, key: str):
        if self._lib.ps_client_kv_del(self._h, key.encode()) != 0:
            raise ConnectionError(f"kv_del({key}) failed")

    def kv_alive(self, prefix: str, cap: int = 1 << 20):
        """{key: value} for every unexpired key under prefix."""
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.ps_client_kv_alive(self._h, prefix.encode(), buf, cap)
        if n < 0:
            raise ConnectionError(f"kv_alive({prefix}) failed ({n})")
        parts = buf.raw[:n].split(b"\0")
        out = {}
        for i in range(0, len(parts) - 1, 2):
            out[parts[i].decode()] = parts[i + 1].decode()
        return out

    def stop_servers(self):
        self._lib.ps_client_stop_servers(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ps_client_destroy(self._h)
                self._h = None
        except Exception:
            pass

    # -- tables --------------------------------------------------------------
    def create_sparse_table(self, table_id: int, emb_dim: int,
                            shard_num: int = 16, optimizer: str = "adagrad",
                            learning_rate: float = 0.05,
                            init_range: float = 0.01, seed: int = 0):
        if self._lib.ps_client_create_sparse(
            self._h, table_id, emb_dim, shard_num, _OPT_IDS[optimizer],
            ctypes.c_float(learning_rate), ctypes.c_float(init_range),
            ctypes.c_uint64(seed),
        ) != 0:
            raise RuntimeError("create_sparse_table failed")

    def create_dense_table(self, table_id: int, length: int,
                           optimizer: str = "sgd", learning_rate: float = 0.01,
                           init: Optional[np.ndarray] = None):
        buf = None
        if init is not None:
            buf = np.ascontiguousarray(init, np.float32).reshape(-1)
            if buf.size != length:
                raise ValueError("init length mismatch")
        if self._lib.ps_client_create_dense(
            self._h, table_id, length, _DENSE_OPT_IDS[optimizer],
            ctypes.c_float(learning_rate),
            buf.ctypes.data if buf is not None else None,
        ) != 0:
            raise RuntimeError("create_dense_table failed")
        self._dense_meta[table_id] = length

    # -- sparse verbs --------------------------------------------------------
    def pull_sparse(self, table_id: int, keys: np.ndarray, emb_dim: int,
                    create: bool = True) -> np.ndarray:
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        out = np.empty((keys.size, emb_dim), np.float32)
        if self._lib.ps_client_pull_sparse(
            self._h, table_id, keys.ctypes.data, keys.size, emb_dim,
            out.ctypes.data, 1 if create else 0,
        ) != 0:
            raise ConnectionError("pull_sparse failed")
        return out

    def push_sparse(self, table_id: int, keys: np.ndarray,
                    grads: np.ndarray, raw: bool = False):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, np.float32)
        emb_dim = grads.size // max(keys.size, 1)
        if self._lib.ps_client_push_sparse(
            self._h, table_id, keys.ctypes.data, keys.size, emb_dim,
            grads.ctypes.data, 1 if raw else 0,
        ) != 0:
            raise ConnectionError("push_sparse failed")

    # -- dense verbs ---------------------------------------------------------
    def pull_dense(self, table_id: int, length: Optional[int] = None) -> np.ndarray:
        length = length or self._dense_meta[table_id]
        out = np.empty(length, np.float32)
        if self._lib.ps_client_pull_dense(
            self._h, table_id, out.ctypes.data, length
        ) != 0:
            raise ConnectionError("pull_dense failed")
        return out

    def push_dense(self, table_id: int, grads: np.ndarray):
        grads = np.ascontiguousarray(grads, np.float32).reshape(-1)
        if self._lib.ps_client_push_dense(
            self._h, table_id, grads.ctypes.data, grads.size
        ) != 0:
            raise ConnectionError("push_dense failed")

    def set_dense(self, table_id: int, values: np.ndarray):
        values = np.ascontiguousarray(values, np.float32).reshape(-1)
        if self._lib.ps_client_set_dense(
            self._h, table_id, values.ctypes.data, values.size
        ) != 0:
            raise ConnectionError("set_dense failed")

    def push_pull_dense(self, table_id: int, grads: np.ndarray) -> np.ndarray:
        """Fused round trip: apply grads server-side, return the updated
        values — half the wire latency of push_dense + pull_dense."""
        grads = np.ascontiguousarray(grads, np.float32).reshape(-1)
        out = np.empty(grads.size, np.float32)
        if self._lib.ps_client_push_pull_dense(
            self._h, table_id, grads.ctypes.data, out.ctypes.data, grads.size
        ) != 0:
            raise ConnectionError("push_pull_dense failed")
        return out

    # -- coordination --------------------------------------------------------
    def barrier(self):
        if self._lib.ps_client_barrier(self._h, self.trainer_id) != 0:
            raise ConnectionError("barrier failed")

    def save(self, dirname: str):
        os.makedirs(dirname, exist_ok=True)
        if self._lib.ps_client_save(self._h, dirname.encode()) != 0:
            raise IOError(f"distributed save to {dirname} failed")

    def load(self, dirname: str):
        if self._lib.ps_client_load(self._h, dirname.encode()) != 0:
            raise IOError(f"distributed load from {dirname} failed")

    def stat(self, table_id: int = 0) -> int:
        """Row count of one sparse table, or of the whole fleet (id 0)."""
        n = self._lib.ps_client_stat(self._h, table_id)
        if n < 0:
            raise ConnectionError("stat failed")
        return int(n)

    def set_lr(self, lr: float, table_id: int = 0):
        """Set the optimizer lr of one table, or of every table (id 0)."""
        self._lib.ps_client_set_lr(self._h, table_id, ctypes.c_float(lr))

    # -- CTR accessor (reference: ctr_accessor.h over the wire) --------------
    def set_ctr(self, table_id: int, ctr) -> None:
        """Enable the CTR accessor on a fleet table (CtrAccessorConfig)."""
        if self._lib.ps_client_set_ctr(
            self._h, table_id,
            *[ctypes.c_float(v) for v in ctr.as_floats()],
        ) != 0:
            raise ConnectionError("set_ctr failed")

    def push_ctr(self, table_id: int, keys: np.ndarray, shows: np.ndarray,
                 clicks: np.ndarray, grads: np.ndarray):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        shows = np.ascontiguousarray(shows, np.float32).reshape(-1)
        clicks = np.ascontiguousarray(clicks, np.float32).reshape(-1)
        grads = np.ascontiguousarray(grads, np.float32)
        emb_dim = grads.size // max(keys.size, 1)
        if self._lib.ps_client_push_ctr(
            self._h, table_id, keys.ctypes.data, keys.size, emb_dim,
            shows.ctypes.data, clicks.ctypes.data, grads.ctypes.data,
        ) != 0:
            raise ConnectionError("push_ctr failed")

    def shrink(self, table_id: int) -> int:
        """Fleet-wide decay+eviction pass; returns total evicted."""
        n = self._lib.ps_client_shrink(self._h, table_id)
        if n < 0:
            raise ConnectionError("shrink failed")
        return int(n)

    def ctr_stats(self, table_id: int, key: int):
        out = np.zeros(4, np.float32)
        if self._lib.ps_client_ctr_stats(
            self._h, table_id, int(key), out.ctypes.data
        ) != 0:
            return None
        return tuple(float(v) for v in out)


class DistributedSparseTable:
    """MemorySparseTable-compatible facade over the server fleet, so
    SparseEmbedding(table=...) works unchanged across hosts (reference:
    distributed_lookup_table on the worker side)."""

    def __init__(self, client: PsClient, table_id: int, emb_dim: int,
                 shard_num: int = 16, optimizer: str = "adagrad",
                 learning_rate: float = 0.05, init_range: float = 0.01,
                 seed: int = 0, create: bool = True, ctr=None):
        self.client = client
        self.table_id = table_id
        self.emb_dim = emb_dim
        self.ctr = ctr
        if create:
            client.create_sparse_table(
                table_id, emb_dim, shard_num, optimizer, learning_rate,
                init_range, seed,
            )
        if ctr is not None:
            client.set_ctr(table_id, ctr)

    def pull(self, keys: np.ndarray, create: bool = True) -> np.ndarray:
        return self.client.pull_sparse(self.table_id, keys, self.emb_dim, create)

    def push_ctr(self, keys, shows, clicks, grads):
        self.client.push_ctr(self.table_id, keys, shows, clicks, grads)

    def shrink(self) -> int:
        return self.client.shrink(self.table_id)

    def ctr_stats(self, key: int):
        return self.client.ctr_stats(self.table_id, key)

    def push(self, keys: np.ndarray, grads: np.ndarray):
        self.client.push_sparse(self.table_id, keys, grads)

    def set_lr(self, lr: float):
        self.client.set_lr(lr, table_id=self.table_id)

    def __len__(self):
        return self.client.stat(table_id=self.table_id)

    def save(self, dirname: str):
        self.client.save(dirname)

    def load(self, dirname: str):
        self.client.load(dirname)


class GeoDistributedSparseTable(DistributedSparseTable):
    """Geo-async sparse table (reference: GeoSparseTable +
    communicator GeoCommunicator): the trainer reads AND optimizes a local
    replica; every `geo_steps` pushes the accumulated local deltas
    (raw-added server-side) and refreshes touched rows from the server.
    Deterministic per-key init makes replicas agree on never-synced rows.
    """

    def __init__(self, client: PsClient, table_id: int, emb_dim: int,
                 shard_num: int = 16, optimizer: str = "adagrad",
                 learning_rate: float = 0.05, init_range: float = 0.01,
                 seed: int = 0, geo_steps: int = 10, create: bool = True):
        super().__init__(client, table_id, emb_dim, shard_num, optimizer,
                         learning_rate, init_range, seed, create)
        from . import MemorySparseTable

        self.local = MemorySparseTable(
            emb_dim, shard_num=shard_num, optimizer=optimizer,
            learning_rate=learning_rate, init_range=init_range, seed=seed,
        )
        self.geo_steps = geo_steps
        self._step = 0
        # base snapshot of keys touched SINCE THE LAST SYNC only — entries
        # are evicted after each sync, so host memory and per-sync cost are
        # bounded by the inter-sync working set, not the whole history
        self._base: Dict[int, np.ndarray] = {}

    def pull(self, keys: np.ndarray, create: bool = True) -> np.ndarray:
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        rows = self.local.pull(keys, create=create)
        if create:
            for k, row in zip(keys.tolist(), rows):
                if k not in self._base:
                    self._base[k] = row.copy()
        return rows

    def push(self, keys: np.ndarray, grads: np.ndarray):
        # record bases for keys pushed without a prior pull this interval
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        fresh = [k for k in keys.tolist() if k not in self._base]
        if fresh:
            fk = np.asarray(fresh, np.int64)
            for k, row in zip(fresh, self.local.pull(fk, create=True)):
                self._base[k] = row.copy()
        self.local.push(keys, grads)
        self._step += 1
        if self._step % self.geo_steps == 0:
            self.sync()

    def sync(self):
        """Push local deltas (raw add), adopt the merged server rows, and
        evict the synced bases (next touch re-snapshots)."""
        if not self._base:
            return
        ks = np.fromiter(self._base.keys(), np.int64, len(self._base))
        cur = self.local.pull(ks, create=True)
        base = np.stack([self._base[int(k)] for k in ks])
        delta = cur - base
        touched = np.abs(delta).sum(axis=1) > 0
        if touched.any():
            self.client.push_sparse(
                self.table_id, ks[touched], delta[touched], raw=True
            )
        merged = super(GeoDistributedSparseTable, self).pull(ks, create=True)
        # overwrite the local replica with the authoritative merged rows
        self.local.push_raw(ks, merged - cur)
        self._base.clear()

    def refresh(self, keys: np.ndarray):
        """Adopt the authoritative merged server rows for `keys` without
        pushing anything — the reference geo trainers' periodic pull of
        rows they read but did not recently update."""
        ks = np.ascontiguousarray(keys, np.int64).reshape(-1)
        cur = self.local.pull(ks, create=True)
        merged = super(GeoDistributedSparseTable, self).pull(ks, create=True)
        self.local.push_raw(ks, merged - cur)
        for k in ks.tolist():
            self._base.pop(k, None)  # re-snapshot on next touch


class DenseTableHandle:
    """Server-resident dense parameters for PS-mode training (reference:
    MemoryDenseTable + the pull_dense/push_dense_grad worker loop).

    Registers a list of framework Tensors (parameters); `init()` seeds the
    servers from trainer 0; each step `push_pull(grads)` sends the flat
    grad and installs the post-update values back into the tensors — the
    server is the optimizer, trainers stay stateless (PS division of labor).
    """

    def __init__(self, client: PsClient, table_id: int, params: List,
                 optimizer: str = "sgd", learning_rate: float = 0.01):
        self.client = client
        self.table_id = table_id
        self.params = list(params)
        self.shapes = [tuple(p.shape) for p in self.params]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.total = sum(self.sizes)
        self.optimizer = optimizer
        self.learning_rate = learning_rate

    def _flat(self, arrays) -> np.ndarray:
        return np.concatenate(
            [np.asarray(a, np.float32).reshape(-1) for a in arrays]
        ) if arrays else np.zeros(0, np.float32)

    def init(self, is_first_trainer: bool):
        vals = self._flat([p.numpy() for p in self.params])
        self.client.create_dense_table(
            self.table_id, self.total, self.optimizer, self.learning_rate,
            init=vals if is_first_trainer else None,
        )
        if is_first_trainer:
            # idempotent overwrite in case the table pre-existed (restart)
            self.client.set_dense(self.table_id, vals)

    def pull_into_params(self):
        flat = self.client.pull_dense(self.table_id, self.total)
        self._scatter(flat)

    def _scatter(self, flat: np.ndarray):
        import jax.numpy as jnp

        off = 0
        for p, size, shape in zip(self.params, self.sizes, self.shapes):
            chunk = flat[off:off + size].reshape(shape)
            p._value = jnp.asarray(chunk)
            off += size

    def push(self, grads: Optional[List] = None):
        """Push this trainer's grads (server applies the optimizer). In
        sync-SGD, barrier between push and pull_into_params so every
        trainer's contribution lands before anyone reads."""
        if grads is None:
            grads = [p.grad for p in self.params]
        flat = self._flat(
            [g._value if hasattr(g, "_value") else g for g in grads]
        )
        self.client.push_dense(self.table_id, flat)

    def push_pull(self, grads: Optional[List] = None):
        """FUSED push+pull (one wire round trip per server chunk) — the
        fully-async single-trainer path; multi-trainer sync loops should
        push / barrier / pull so every contribution lands first."""
        if grads is None:
            grads = [p.grad for p in self.params]
        flat = self._flat(
            [g._value if hasattr(g, "_value") else g for g in grads]
        )
        out = self.client.push_pull_dense(self.table_id, flat)
        self._scatter(out)


class Communicator:
    """Sparse-push communicator with sync / async modes (reference:
    ps/service/communicator/communicator.h AsyncCommunicator). In async
    mode pushes enqueue to a background flusher so the trainer never
    blocks on the wire; flush() drains (the reference's barrier point)."""

    def __init__(self, table: DistributedSparseTable, mode: str = "sync",
                 max_queue: int = 64):
        if mode not in ("sync", "async"):
            raise ValueError("mode must be sync|async")
        self.table = table
        self.mode = mode
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._err: Optional[BaseException] = None
        self._thread = None
        if mode == "async":
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                try:
                    self.table.push(*item)
                except BaseException as e:  # surfaced on next push/flush
                    self._err = e
            finally:
                self._q.task_done()

    def push(self, keys: np.ndarray, grads: np.ndarray):
        if self._err:
            raise self._err
        if self.mode == "sync":
            self.table.push(keys, grads)
        else:
            self._q.put((np.array(keys, np.int64), np.array(grads, np.float32)))

    def flush(self):
        if self.mode == "async":
            self._q.join()
        if self._err:
            raise self._err

    def stop(self):
        if self._thread is not None:
            self._q.put(None)
            self._thread.join()
            self._thread = None


class SparsePipeline:
    """Overlap host PS traffic with device compute — the training-loop
    half of the reference's async Communicator (communicator.h: pulls for
    the NEXT minibatch and queued pushes run while the accelerator
    executes the current step; the PSGPU trainer pipelines the same way,
    framework/trainer.h:253).

    Semantics: async-PS — a prefetched pull may miss pushes still in
    flight (staleness ≤ `queue` steps), exactly the reference's async
    mode. `flush()` drains pushes (the barrier point, e.g. before eval
    or checkpoint).

    Works over any table with pull(keys)/push(keys, grads) — the
    in-process MemorySparseTable (SSD-backed or not) or the wire-backed
    DistributedSparseTable."""

    def __init__(self, table, max_queue: int = 8):
        from concurrent.futures import ThreadPoolExecutor

        self.table = table
        # one worker per direction: pulls must not queue behind pushes
        self._pull_pool = ThreadPoolExecutor(1)
        self._push = Communicator(table, mode="async", max_queue=max_queue)

    def prefetch(self, keys: np.ndarray):
        """Start pulling rows for a FUTURE step; returns a future whose
        .result() is the [n, dim] row block."""
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        return self._pull_pool.submit(self.table.pull, keys)

    def push_async(self, keys: np.ndarray, grads: np.ndarray):
        self._push.push(keys, grads)

    def flush(self):
        self._push.flush()

    def stop(self):
        self._push.stop()
        self._pull_pool.shutdown(wait=True)
