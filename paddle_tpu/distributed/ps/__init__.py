"""Parameter-server sparse path: host-RAM embedding tables + TPU dense math.

Reference analogue:
  - paddle/fluid/distributed/ps/table/memory_sparse_table.cc — sharded
    host-RAM embedding store with optimizer-on-push accessors (our C++
    twin: csrc/memory_sparse_table.cc, built JIT via utils.cpp_extension);
  - python/paddle/distributed/ps/the_one_ps.py:816 (TheOnePSRuntime) —
    table lifecycle / init_server / init_worker;
  - paddle/fluid/operators/pscore/distributed_lookup_table_op.cc — the
    lookup op trainers call.

TPU-native design: the reference shards tables across brpc PS server
processes; here the table is an in-process C++ store (single-host worker
first — the multi-host extension shards keys across hosts by the same
shard hash and moves pull/push over the network). The TPU never sees the
full table: each step pulls the minibatch's rows (host→device upload),
computes densely, and pushes the touched-row grads back where the C++
accessor applies SGD/AdaGrad — exactly the reference's split of labor.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ...core.dispatch import GradNode, is_grad_enabled
from ...core.tensor import Tensor
from ...nn.layer_base import Layer

__all__ = ["MemorySparseTable", "SparseEmbedding", "TheOnePSRuntime"]

_lib = None


def _load_lib():
    global _lib
    if _lib is None:
        from ...utils import cpp_extension

        src = os.path.join(os.path.dirname(__file__), "csrc", "memory_sparse_table.cc")
        _lib = cpp_extension.load("ps_table", [src])
        _lib.ps_table_create.restype = ctypes.c_void_p
        _lib.ps_table_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_uint64,
        ]
        _lib.ps_table_destroy.argtypes = [ctypes.c_void_p]
        _lib.ps_table_pull.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int,
        ]
        _lib.ps_table_push.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        _lib.ps_table_size.restype = ctypes.c_int64
        _lib.ps_table_size.argtypes = [ctypes.c_void_p]
        _lib.ps_table_save.restype = ctypes.c_int
        _lib.ps_table_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib.ps_table_load.restype = ctypes.c_int
        _lib.ps_table_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib.ps_table_set_lr.argtypes = [ctypes.c_void_p, ctypes.c_float]
    return _lib


_OPT_IDS = {"sgd": 0, "adagrad": 1}


class MemorySparseTable:
    """ctypes facade over the C++ sharded table."""

    def __init__(self, emb_dim: int, shard_num: int = 16, optimizer: str = "adagrad",
                 learning_rate: float = 0.05, init_range: float = 0.01,
                 seed: int = 0):
        if optimizer not in _OPT_IDS:
            raise ValueError(f"optimizer must be one of {sorted(_OPT_IDS)}")
        self.emb_dim = emb_dim
        self._lib = _load_lib()
        self._h = self._lib.ps_table_create(
            emb_dim, shard_num, _OPT_IDS[optimizer],
            ctypes.c_float(learning_rate), ctypes.c_float(init_range),
            ctypes.c_uint64(seed),
        )

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ps_table_destroy(self._h)
                self._h = None
        except Exception:
            pass

    def pull(self, keys: np.ndarray, create: bool = True) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        out = np.empty((keys.size, self.emb_dim), np.float32)
        self._lib.ps_table_pull(
            self._h, keys.ctypes.data, keys.size, out.ctypes.data,
            1 if create else 0,
        )
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, dtype=np.float32).reshape(
            keys.size, self.emb_dim
        )
        self._lib.ps_table_push(self._h, keys.ctypes.data, keys.size, grads.ctypes.data)

    def set_lr(self, lr: float):
        self._lib.ps_table_set_lr(self._h, ctypes.c_float(lr))

    def __len__(self):
        return int(self._lib.ps_table_size(self._h))

    def save(self, path: str):
        if self._lib.ps_table_save(self._h, path.encode()) != 0:
            raise IOError(f"saving sparse table to {path} failed")

    def load(self, path: str):
        if self._lib.ps_table_load(self._h, path.encode()) != 0:
            raise IOError(f"loading sparse table from {path} failed")


class SparseEmbedding(Layer):
    """Embedding whose weights live in the host PS table, not on the chip.

    reference: paddle.static.nn.sparse_embedding lowering to
    distributed_lookup_table / distributed_push_sparse ops. Forward pulls the
    minibatch rows (create-on-miss) and uploads one [N, dim] block; backward
    pushes the row grads straight into the table, where the C++ accessor
    applies the per-feature optimizer — so `optimizer.step()` never sees
    these weights (exactly the PS division of labor: trainer computes,
    server updates).
    """

    def __init__(self, size, shard_num: int = 16, optimizer: str = "adagrad",
                 learning_rate: float = 0.05, init_range: float = 0.01,
                 seed: int = 0, table: Optional[MemorySparseTable] = None,
                 padding_idx: Optional[int] = None):
        super().__init__()
        # paddle signature: size = [vocab, emb_dim]; vocab is advisory (the
        # table is a hash map — any int64 feature id works, like the ref)
        self.emb_dim = int(size[1])
        self.padding_idx = padding_idx
        self.table = table or MemorySparseTable(
            self.emb_dim, shard_num, optimizer, learning_rate, init_range, seed
        )

    def forward(self, ids: Tensor) -> Tensor:
        import jax as _jax

        if isinstance(ids._value, _jax.core.Tracer):
            raise NotImplementedError(
                "SparseEmbedding pulls rows from the host C++ table and "
                "cannot run under a jit trace; keep the sparse lookup in "
                "eager code (the PS division of labor) and compile only the "
                "dense tail"
            )
        ids_np = np.asarray(ids.numpy(), np.int64)
        flat = ids_np.reshape(-1)
        rows = self.table.pull(flat, create=self.training)
        if self.padding_idx is not None:
            # padding rows embed to zeros and never train (reference
            # sparse_embedding padding_idx contract)
            rows = np.where((flat == self.padding_idx)[:, None], 0.0, rows)
        out_np = rows.reshape(*ids_np.shape, self.emb_dim)
        out = Tensor(out_np, stop_gradient=True)
        if not (is_grad_enabled() and self.training):
            return out

        table = self.table
        pad_idx = self.padding_idx

        def vjp_fn(ct):
            # ct: device grad for the pulled block. Merge duplicate ids
            # first (one optimizer update per feature per step — the
            # trainer-side grad merge the reference does before push) then
            # push to the host table; nothing flows further (ids are ints).
            g = np.asarray(ct, np.float32).reshape(flat.size, table.emb_dim)
            keys, grads_rows = flat, g
            if pad_idx is not None:
                keep = keys != pad_idx
                keys, grads_rows = keys[keep], grads_rows[keep]
            if keys.size == 0:
                return ()
            uniq, inv = np.unique(keys, return_inverse=True)
            merged = np.zeros((uniq.size, table.emb_dim), np.float32)
            np.add.at(merged, inv, grads_rows)
            table.push(uniq, merged)
            return ()

        node = GradNode(
            vjp_fn, [], [(tuple(out_np.shape), np.dtype(np.float32))],
            "sparse_embedding_push",
        )
        out.stop_gradient = False
        out._grad_node = node
        out._out_index = 0
        return out


class TheOnePSRuntime:
    """Single-host TheOnePS runtime (reference: ps/the_one_ps.py:816).

    Owns the named tables; init_server/init_worker collapse to in-process
    setup on one host. save/load persist every table to a directory —
    the reference's save_persistables for sparse tables.
    """

    def __init__(self):
        self._tables = {}

    def create_table(self, name: str, emb_dim: int, **kwargs) -> MemorySparseTable:
        if name in self._tables:
            raise ValueError(f"table {name!r} already exists")
        t = MemorySparseTable(emb_dim, **kwargs)
        self._tables[name] = t
        return t

    def get_table(self, name: str) -> MemorySparseTable:
        return self._tables[name]

    def _init_server(self, *args, **kwargs):
        pass  # in-process tables need no server bootstrap on one host

    def _init_worker(self, *args, **kwargs):
        pass

    def _stop_worker(self):
        pass

    def save_persistables(self, dirname: str):
        os.makedirs(dirname, exist_ok=True)
        for name, t in self._tables.items():
            t.save(os.path.join(dirname, f"{name}.sparse"))

    def load_persistables(self, dirname: str):
        for name, t in self._tables.items():
            t.load(os.path.join(dirname, f"{name}.sparse"))

from . import the_one_ps  # noqa: E402,F401
from . import utils  # noqa: E402,F401
