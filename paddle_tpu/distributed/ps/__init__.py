"""Parameter-server sparse path: host-RAM embedding tables + TPU dense math.

Reference analogue:
  - paddle/fluid/distributed/ps/table/memory_sparse_table.cc — sharded
    host-RAM embedding store with optimizer-on-push accessors (our C++
    twin: csrc/memory_sparse_table.cc, built JIT via utils.cpp_extension);
  - python/paddle/distributed/ps/the_one_ps.py:816 (TheOnePSRuntime) —
    table lifecycle / init_server / init_worker;
  - paddle/fluid/operators/pscore/distributed_lookup_table_op.cc — the
    lookup op trainers call.

TPU-native design: the reference shards tables across brpc PS server
processes; here the table is an in-process C++ store (single-host worker
first — the multi-host extension shards keys across hosts by the same
shard hash and moves pull/push over the network). The TPU never sees the
full table: each step pulls the minibatch's rows (host→device upload),
computes densely, and pushes the touched-row grads back where the C++
accessor applies SGD/AdaGrad — exactly the reference's split of labor.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ...core.dispatch import GradNode, is_grad_enabled
from ...core.tensor import Tensor
from ...nn.layer_base import Layer

__all__ = [
    "MemorySparseTable", "GraphTable", "SparseEmbedding", "TheOnePSRuntime",
    "PsServer", "PsClient", "DistributedSparseTable",
    "GeoDistributedSparseTable", "DenseTableHandle", "Communicator",
    "SparsePipeline",
]

_lib = None


def _load_lib():
    global _lib
    if _lib is None:
        from ...utils import cpp_extension

        csrc = os.path.join(os.path.dirname(__file__), "csrc")
        src = os.path.join(csrc, "memory_sparse_table.cc")
        _lib = cpp_extension.load(
            "ps_table", [src],
            depends=[os.path.join(csrc, "ps_sparse_table.h"),
                     os.path.join(csrc, "graph_table.h")],
        )
        _lib.ps_table_create.restype = ctypes.c_void_p
        _lib.ps_table_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_uint64,
        ]
        _lib.ps_table_destroy.argtypes = [ctypes.c_void_p]
        _lib.ps_table_pull.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int,
        ]
        _lib.ps_table_push.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        _lib.ps_table_push_raw.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        _lib.ps_table_size.restype = ctypes.c_int64
        _lib.ps_table_size.argtypes = [ctypes.c_void_p]
        _lib.ps_table_save.restype = ctypes.c_int
        _lib.ps_table_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib.ps_table_load.restype = ctypes.c_int
        _lib.ps_table_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib.ps_table_set_lr.argtypes = [ctypes.c_void_p, ctypes.c_float]
        _lib.ps_table_set_ctr.argtypes = [ctypes.c_void_p] + [ctypes.c_float] * 5
        _lib.ps_table_push_ctr.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        _lib.ps_table_shrink.restype = ctypes.c_int64
        _lib.ps_table_shrink.argtypes = [ctypes.c_void_p]
        _lib.ps_table_ctr_stats.restype = ctypes.c_int
        _lib.ps_table_ctr_stats.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        _lib.ps_table_enable_ssd.restype = ctypes.c_int
        _lib.ps_table_enable_ssd.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ]
        _lib.ps_table_ram_size.restype = ctypes.c_int64
        _lib.ps_table_ram_size.argtypes = [ctypes.c_void_p]
        _lib.ps_table_disk_size.restype = ctypes.c_int64
        _lib.ps_table_disk_size.argtypes = [ctypes.c_void_p]
        _lib.ps_graph_create.restype = ctypes.c_void_p
        _lib.ps_graph_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
        ]
        _lib.ps_graph_destroy.argtypes = [ctypes.c_void_p]
        _lib.ps_graph_add_edges.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        _lib.ps_graph_set_node_feat.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p,
        ]
        _lib.ps_graph_get_node_feat.restype = ctypes.c_int64
        _lib.ps_graph_get_node_feat.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p,
        ]
        _lib.ps_graph_degree.restype = ctypes.c_int64
        _lib.ps_graph_degree.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        _lib.ps_graph_sample_neighbors.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        _lib.ps_graph_random_sample_nodes.restype = ctypes.c_int64
        _lib.ps_graph_random_sample_nodes.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_void_p,
        ]
        _lib.ps_graph_node_count.restype = ctypes.c_int64
        _lib.ps_graph_node_count.argtypes = [ctypes.c_void_p]
        _lib.ps_graph_edge_count.restype = ctypes.c_int64
        _lib.ps_graph_edge_count.argtypes = [ctypes.c_void_p]
        _lib.ps_graph_save.restype = ctypes.c_int
        _lib.ps_graph_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib.ps_graph_load.restype = ctypes.c_int
        _lib.ps_graph_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return _lib


_OPT_IDS = {"sgd": 0, "adagrad": 1, "adam": 2}


class CtrAccessorConfig:
    """CTR product semantics on a sparse table (reference:
    ps/table/ctr_accessor.h CtrCommonAccessor): show/click counters folded
    in on push, time decay, and score-based feature eviction where
    score = show_coeff*(show-click) + click_coeff*click."""

    def __init__(self, show_coeff: float = 0.25, click_coeff: float = 1.0,
                 decay_rate: float = 0.98, delete_threshold: float = 0.8,
                 delete_after_unseen_days: float = 30.0):
        self.show_coeff = float(show_coeff)
        self.click_coeff = float(click_coeff)
        self.decay_rate = float(decay_rate)
        self.delete_threshold = float(delete_threshold)
        self.delete_after_unseen_days = float(delete_after_unseen_days)

    def as_floats(self):
        return (self.show_coeff, self.click_coeff, self.decay_rate,
                self.delete_threshold, self.delete_after_unseen_days)


class MemorySparseTable:
    """ctypes facade over the C++ sharded table."""

    def __init__(self, emb_dim: int, shard_num: int = 16, optimizer: str = "adagrad",
                 learning_rate: float = 0.05, init_range: float = 0.01,
                 seed: int = 0, ctr: Optional["CtrAccessorConfig"] = None,
                 ssd_path: Optional[str] = None,
                 ram_budget: Optional[int] = None):
        if optimizer not in _OPT_IDS:
            raise ValueError(f"optimizer must be one of {sorted(_OPT_IDS)}")
        self.emb_dim = emb_dim
        self._lib = _load_lib()
        self._h = self._lib.ps_table_create(
            emb_dim, shard_num, _OPT_IDS[optimizer],
            ctypes.c_float(learning_rate), ctypes.c_float(init_range),
            ctypes.c_uint64(seed),
        )
        self.ctr = ctr
        if ctr is not None:
            self._lib.ps_table_set_ctr(
                self._h, *[ctypes.c_float(v) for v in ctr.as_floats()]
            )
        # SSD overflow (reference: ps/table/ssd_sparse_table.h): entries
        # past ram_budget spill to a slot file at ssd_path; pull/push
        # promote on demand — tables larger than host RAM (the 10B-feature
        # ERNIE north star) keep the same API
        self.ssd_path = ssd_path
        if ssd_path is not None:
            if ram_budget is None:
                raise ValueError("ssd_path requires ram_budget (max RAM "
                                 "entries)")
            rc = self._lib.ps_table_enable_ssd(
                self._h, str(ssd_path).encode(), ctypes.c_int64(ram_budget)
            )
            if rc != 0:
                raise OSError(f"cannot create SSD slot file at {ssd_path}")

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ps_table_destroy(self._h)
                self._h = None
        except Exception:
            pass

    def pull(self, keys: np.ndarray, create: bool = True) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        out = np.empty((keys.size, self.emb_dim), np.float32)
        self._lib.ps_table_pull(
            self._h, keys.ctypes.data, keys.size, out.ctypes.data,
            1 if create else 0,
        )
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, dtype=np.float32).reshape(
            keys.size, self.emb_dim
        )
        self._lib.ps_table_push(self._h, keys.ctypes.data, keys.size, grads.ctypes.data)

    def push_raw(self, keys: np.ndarray, deltas: np.ndarray):
        """Raw delta add (no optimizer) — the geo-async merge primitive."""
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        deltas = np.ascontiguousarray(deltas, dtype=np.float32).reshape(
            keys.size, self.emb_dim
        )
        self._lib.ps_table_push_raw(
            self._h, keys.ctypes.data, keys.size, deltas.ctypes.data
        )

    def set_lr(self, lr: float):
        self._lib.ps_table_set_lr(self._h, ctypes.c_float(lr))

    def push_ctr(self, keys: np.ndarray, shows: np.ndarray,
                 clicks: np.ndarray, grads: np.ndarray):
        """CTR push: fold show/click counts in, reset the unseen clock,
        apply the SGD rule (reference ctr_accessor.cc Update)."""
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        shows = np.ascontiguousarray(shows, np.float32).reshape(-1)
        clicks = np.ascontiguousarray(clicks, np.float32).reshape(-1)
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            keys.size, self.emb_dim
        )
        self._lib.ps_table_push_ctr(
            self._h, keys.ctypes.data, keys.size, shows.ctypes.data,
            clicks.ctypes.data, grads.ctypes.data,
        )

    def shrink(self) -> int:
        """One decay+eviction pass (one 'day'); returns evicted count."""
        return int(self._lib.ps_table_shrink(self._h))

    def ctr_stats(self, key: int):
        """(show, click, unseen_days, score) or None when absent."""
        out = np.zeros(4, np.float32)
        if self._lib.ps_table_ctr_stats(self._h, int(key), out.ctypes.data) != 0:
            return None
        return tuple(float(v) for v in out)

    def __len__(self):
        return int(self._lib.ps_table_size(self._h))

    def ram_size(self) -> int:
        """Entries resident in RAM (== len() without SSD overflow)."""
        return int(self._lib.ps_table_ram_size(self._h))

    def disk_size(self) -> int:
        """Entries spilled to the SSD slot file."""
        return int(self._lib.ps_table_disk_size(self._h))

    def save(self, path: str):
        if self._lib.ps_table_save(self._h, path.encode()) != 0:
            raise IOError(f"saving sparse table to {path} failed")

    def load(self, path: str):
        if self._lib.ps_table_load(self._h, path.encode()) != 0:
            raise IOError(f"loading sparse table from {path} failed")


class GraphTable:
    """Sharded host graph store with neighbor sampling — the storage side
    of the GNN pipeline (reference: ps/table/common_graph_table.h +
    the graph service the PSGPU trainer samples from). The compute side
    is paddle.incubate.graph_sample_neighbors / graph_send_recv /
    graph_reindex over the sampled subgraph."""

    def __init__(self, shard_num: int = 16, feat_dim: int = 0,
                 seed: int = 0):
        self.feat_dim = int(feat_dim)
        self._lib = _load_lib()
        self._h = self._lib.ps_graph_create(
            int(shard_num), self.feat_dim, ctypes.c_uint64(seed)
        )
        self._calls = 0

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ps_graph_destroy(self._h)
                self._h = None
        except Exception:
            pass

    def add_edges(self, src, dst, weights=None):
        src = np.ascontiguousarray(src, np.int64).reshape(-1)
        dst = np.ascontiguousarray(dst, np.int64).reshape(-1)
        if src.size != dst.size:
            raise ValueError("src/dst length mismatch")
        wp = 0
        if weights is not None:
            weights = np.ascontiguousarray(weights, np.float32).reshape(-1)
            if weights.size != src.size:
                raise ValueError("weights length mismatch")
            wp = weights.ctypes.data
        self._lib.ps_graph_add_edges(
            self._h, src.ctypes.data, dst.ctypes.data, wp, src.size
        )

    def set_node_feat(self, ids, feats):
        if self.feat_dim <= 0:
            raise ValueError("GraphTable built with feat_dim=0")
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        feats = np.ascontiguousarray(feats, np.float32).reshape(
            ids.size, self.feat_dim
        )
        self._lib.ps_graph_set_node_feat(
            self._h, ids.ctypes.data, ids.size, feats.ctypes.data
        )

    def get_node_feat(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        out = np.empty((ids.size, self.feat_dim), np.float32)
        self._lib.ps_graph_get_node_feat(
            self._h, ids.ctypes.data, ids.size, out.ctypes.data
        )
        return out

    def degree(self, node: int) -> int:
        return int(self._lib.ps_graph_degree(self._h, int(node)))

    def sample_neighbors(self, ids, k: int, weighted: bool = False):
        """(neighbors [n, k] padded with -1, counts [n]). Uniform mode
        samples WITHOUT replacement (k >= degree returns the whole
        neighborhood); weighted mode draws by edge weight with
        replacement — the reference's two sampling modes."""
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        nbrs = np.empty((ids.size, int(k)), np.int64)
        cnt = np.empty(ids.size, np.int32)
        self._calls += 1
        self._lib.ps_graph_sample_neighbors(
            self._h, ids.ctypes.data, ids.size, int(k),
            1 if weighted else 0, ctypes.c_uint64(self._calls),
            nbrs.ctypes.data, cnt.ctypes.data,
        )
        return nbrs, cnt

    def random_sample_nodes(self, count: int) -> np.ndarray:
        out = np.empty(int(count), np.int64)
        self._calls += 1
        m = self._lib.ps_graph_random_sample_nodes(
            self._h, int(count), ctypes.c_uint64(self._calls),
            out.ctypes.data,
        )
        return out[:m]

    def node_count(self) -> int:
        return int(self._lib.ps_graph_node_count(self._h))

    def edge_count(self) -> int:
        return int(self._lib.ps_graph_edge_count(self._h))

    def save(self, path: str):
        if self._lib.ps_graph_save(self._h, str(path).encode()) != 0:
            raise IOError(f"saving graph table to {path} failed")

    def load(self, path: str):
        """Restore replaces the whole graph (same contract as the sparse
        tables); feat_dim must match the checkpoint's."""
        if self._lib.ps_graph_load(self._h, str(path).encode()) != 0:
            raise IOError(f"loading graph table from {path} failed")


class SparseEmbedding(Layer):
    """Embedding whose weights live in the host PS table, not on the chip.

    reference: paddle.static.nn.sparse_embedding lowering to
    distributed_lookup_table / distributed_push_sparse ops. Forward pulls the
    minibatch rows (create-on-miss) and uploads one [N, dim] block; backward
    pushes the row grads straight into the table, where the C++ accessor
    applies the per-feature optimizer — so `optimizer.step()` never sees
    these weights (exactly the PS division of labor: trainer computes,
    server updates).
    """

    def __init__(self, size, shard_num: int = 16, optimizer: str = "adagrad",
                 learning_rate: float = 0.05, init_range: float = 0.01,
                 seed: int = 0, table: Optional[MemorySparseTable] = None,
                 padding_idx: Optional[int] = None):
        super().__init__()
        # paddle signature: size = [vocab, emb_dim]; vocab is advisory (the
        # table is a hash map — any int64 feature id works, like the ref)
        self.emb_dim = int(size[1])
        self.padding_idx = padding_idx
        # identity check, NOT truthiness: tables define __len__, and a
        # freshly created (empty) table is falsy — `table or ...` would
        # silently discard it and train on a private default table
        self.table = (
            table
            if table is not None
            else MemorySparseTable(
                self.emb_dim, shard_num, optimizer, learning_rate, init_range,
                seed,
            )
        )

    def forward(self, ids: Tensor) -> Tensor:
        import jax as _jax

        if isinstance(ids._value, _jax.core.Tracer):
            raise NotImplementedError(
                "SparseEmbedding pulls rows from the host C++ table and "
                "cannot run under a jit trace; keep the sparse lookup in "
                "eager code (the PS division of labor) and compile only the "
                "dense tail"
            )
        ids_np = np.asarray(ids.numpy(), np.int64)
        flat = ids_np.reshape(-1)
        rows = self.table.pull(flat, create=self.training)
        if self.padding_idx is not None:
            # padding rows embed to zeros and never train (reference
            # sparse_embedding padding_idx contract)
            rows = np.where((flat == self.padding_idx)[:, None], 0.0, rows)
        out_np = rows.reshape(*ids_np.shape, self.emb_dim)
        out = Tensor(out_np, stop_gradient=True)
        if not (is_grad_enabled() and self.training):
            return out

        table = self.table
        pad_idx = self.padding_idx

        def vjp_fn(ct):
            # ct: device grad for the pulled block. Merge duplicate ids
            # first (one optimizer update per feature per step — the
            # trainer-side grad merge the reference does before push) then
            # push to the host table; nothing flows further (ids are ints).
            g = np.asarray(ct, np.float32).reshape(flat.size, table.emb_dim)
            keys, grads_rows = flat, g
            if pad_idx is not None:
                keep = keys != pad_idx
                keys, grads_rows = keys[keep], grads_rows[keep]
            if keys.size == 0:
                return ()
            uniq, inv = np.unique(keys, return_inverse=True)
            merged = np.zeros((uniq.size, table.emb_dim), np.float32)
            np.add.at(merged, inv, grads_rows)
            table.push(uniq, merged)
            return ()

        node = GradNode(
            vjp_fn, [], [(tuple(out_np.shape), np.dtype(np.float32))],
            "sparse_embedding_push",
        )
        out.stop_gradient = False
        out._grad_node = node
        out._out_index = 0
        return out


class TheOnePSRuntime:
    """TheOnePS runtime — in-process tables on one host, the networked
    PsService fleet across hosts (reference: ps/the_one_ps.py:816
    TheOnePSRuntime._init_server:1049 / _init_worker:903).

    Roles follow the launch env contract (PaddleCloudRoleMaker):
      - a PSERVER process calls `_init_server()` + `_run_server()`: starts
        the C++ PsService on PADDLE_PORT and blocks until STOP;
      - a TRAINER process calls `_init_worker()`: connects a PsClient to
        PADDLE_PSERVERS_IP_PORT_LIST; `create_table` then yields
        DistributedSparseTable stubs instead of local tables.
    With no server endpoints configured, everything stays in-process
    (single-host mode — tables are local C++ MemorySparseTables).
    """

    def __init__(self):
        self._tables = {}
        self._table_ids = {}
        self._server = None
        self._client = None
        self._endpoints = []

    def _table_id(self, name: str) -> int:
        """Deterministic table id from the table NAME, so trainers that
        create tables in different orders (or only on some ranks) still
        address the same server table — a per-process creation counter
        silently corrupts training in that case."""
        import zlib

        tid = self._table_ids.get(name)
        if tid is None:
            tid = (zlib.crc32(name.encode("utf-8")) & 0x7FFFFFFF) or 1
            clash = next(
                (n for n, t in self._table_ids.items() if t == tid), None
            )
            if clash is not None:
                raise ValueError(
                    f"table name {name!r} hash-collides with {clash!r}; "
                    "rename one of them"
                )
            self._table_ids[name] = tid
        return tid

    # -- role bootstrap ------------------------------------------------------
    def _init_server(self, *args, **kwargs):
        """Start this process's PsService (reference _init_server:1049)."""
        from .service import PsServer

        if self._server is not None:
            return
        eps = os.getenv("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._endpoints = eps.split(",") if eps else []
        port = int(os.getenv("PADDLE_PORT", "0"))
        n_servers = max(len(self._endpoints), 1)
        sid_env = os.getenv("PADDLE_SERVER_ID")
        if sid_env is not None:
            server_id = int(sid_env)
            if port == 0 and server_id < len(self._endpoints):
                # bind the advertised port, not an ephemeral one
                port = int(self._endpoints[server_id].rpartition(":")[2])
        elif len(self._endpoints) <= 1:
            server_id = 0
            if port == 0 and self._endpoints:
                port = int(self._endpoints[0].rpartition(":")[2])
        else:
            # derive id (and port when unset) from this host's position in
            # the endpoint list — the launch CLI sets PADDLE_PORT + POD_IP
            # but no explicit server id. A silent fallback to id 0 would
            # make multiple servers write colliding checkpoint partitions,
            # so an unresolvable identity is an error.
            my = os.getenv("POD_IP", "127.0.0.1")
            server_id = None
            for i, ep in enumerate(self._endpoints):
                ip, _, p = ep.rpartition(":")
                if ip == my and (port == 0 or int(p) == port):
                    server_id, port = i, int(p)
                    break
            if server_id is None:
                raise RuntimeError(
                    f"cannot locate this server (POD_IP={my!r}, "
                    f"PADDLE_PORT={port}) in PADDLE_PSERVERS_IP_PORT_LIST="
                    f"{self._endpoints}; set PADDLE_SERVER_ID explicitly "
                    "(hostname endpoints need it — matching is by IP)"
                )
        n_trainers = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._server = PsServer(
            port=port, server_id=server_id, n_servers=n_servers,
            n_trainers=n_trainers,
        )

    def _run_server(self):
        """Serve until a trainer broadcasts STOP (reference run_server)."""
        if self._server is None:
            self._init_server()
        self._server.wait()

    def _init_worker(self, *args, **kwargs):
        """Connect this trainer to the server fleet (reference
        _init_worker:903). No-op single-host when no endpoints are set."""
        from .service import PsClient

        if self._client is not None:
            return
        eps = os.getenv("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._endpoints = [e for e in eps.split(",") if e]
        if not self._endpoints:
            return  # single-host in-process mode
        trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._client = PsClient(self._endpoints, trainer_id=trainer_id)
        # servers may still be binding — retry the first ping briefly
        import time

        for attempt in range(50):
            try:
                self._client.ping()
                break
            except ConnectionError:
                if attempt == 49:
                    raise
                time.sleep(0.2)

    def _stop_worker(self):
        """Trainer 0 stops the fleet after everyone is done (reference
        stop_worker + the barrier-then-stop shutdown dance)."""
        if self._client is None:
            return
        self._client.barrier()
        if self._client.trainer_id == 0:
            self._client.stop_servers()
        self._client = None

    @property
    def is_distributed(self) -> bool:
        return self._client is not None

    def barrier(self):
        if self._client is not None:
            self._client.barrier()

    # -- tables --------------------------------------------------------------
    def create_table(self, name: str, emb_dim: int, *, geo_steps: int = 0,
                     **kwargs):
        """Local MemorySparseTable on one host; a DistributedSparseTable
        stub (or geo replica when geo_steps>0) against the fleet."""
        if name in self._tables:
            raise ValueError(f"table {name!r} already exists")
        if geo_steps == 0:
            # strategy.a_sync with k_steps>0 selects geo-async push for all
            # sparse tables (reference: a_sync_configs -> geo sgd mode)
            from ..fleet import _state as _fleet_state

            st = _fleet_state.get("strategy")
            if st is not None and getattr(st, "a_sync", False):
                geo_steps = max(0, int(
                    (st.a_sync_configs or {}).get("k_steps", -1)
                ))
        if self._client is not None:
            from .service import DistributedSparseTable, GeoDistributedSparseTable

            tid = self._table_id(name)
            cls = GeoDistributedSparseTable if geo_steps > 0 else DistributedSparseTable
            extra = {"geo_steps": geo_steps} if geo_steps > 0 else {}
            t = cls(self._client, tid, emb_dim, **extra, **kwargs)
        else:
            t = MemorySparseTable(emb_dim, **kwargs)
        self._tables[name] = t
        return t

    def create_dense_table(self, name: str, params, optimizer: str = "sgd",
                           learning_rate: float = 0.01):
        """Server-resident dense parameters (reference MemoryDenseTable)."""
        from .service import DenseTableHandle

        if self._client is None:
            raise RuntimeError(
                "dense tables need the distributed PS (call _init_worker "
                "with PADDLE_PSERVERS_IP_PORT_LIST set)"
            )
        tid = self._table_id(name)
        h = DenseTableHandle(
            self._client, tid, params, optimizer, learning_rate
        )
        self._tables[name] = h
        return h

    def get_table(self, name: str):
        return self._tables[name]

    def save_persistables(self, dirname: str):
        os.makedirs(dirname, exist_ok=True)
        if self._client is not None:
            self._client.save(dirname)
            return
        for name, t in self._tables.items():
            t.save(os.path.join(dirname, f"{name}.sparse"))

    def load_persistables(self, dirname: str):
        if self._client is not None:
            self._client.load(dirname)
            return
        for name, t in self._tables.items():
            t.load(os.path.join(dirname, f"{name}.sparse"))

from . import service  # noqa: E402,F401  (CtrAccessorConfig defined above)
from .service import (  # noqa: E402,F401
    Communicator,
    DenseTableHandle,
    DistributedSparseTable,
    GeoDistributedSparseTable,
    PsClient,
    PsServer,
    SparsePipeline,
)
from . import the_one_ps  # noqa: E402,F401
from . import utils  # noqa: E402,F401
