// PsService server — one process of the sharded parameter-server fleet.
//
// Reference analogue: paddle/fluid/distributed/ps/service/brpc_ps_server.h
// (BrpcPsServer/BrpcPsService dispatching pull/push/barrier/save/load RPCs
// onto table shards) and ps/service/server.cc. This implementation serves
// the same verbs over the dependency-free framed-TCP protocol in ps_net.h:
// thread-per-connection (trainer connections are long-lived and few), with
// table-level shard mutexes providing the concurrency contract brpc gets
// from its task queues.
//
// Each server process owns:
//   - the subset of sparse keys hashing to it (server_of(key) == server_id);
//   - one contiguous chunk of every dense table (client splits by range).
//
// C ABI (ctypes): ps_server_create / ps_server_port / ps_server_wait /
// ps_server_stop / ps_server_destroy.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "ps_dense_table.h"
#include "ps_net.h"
#include "ps_sparse_table.h"

namespace ps {
namespace {

bool save_dense(DenseTable& t, const std::string& path) {
  std::lock_guard<std::mutex> lk(t.mu);
  FILE* f = std::fopen(path.c_str(), "wb");
  if (!f) return false;
  int64_t len = static_cast<int64_t>(t.data.size());
  int32_t has_m = t.m1.empty() ? 0 : 1;
  bool ok = std::fwrite(&len, sizeof(len), 1, f) == 1 &&
            std::fwrite(&has_m, sizeof(has_m), 1, f) == 1 &&
            std::fwrite(&t.beta1_pow, sizeof(double), 1, f) == 1 &&
            std::fwrite(&t.beta2_pow, sizeof(double), 1, f) == 1 &&
            std::fwrite(t.data.data(), sizeof(float), len, f) ==
                static_cast<size_t>(len);
  if (has_m)
    ok = ok &&
         std::fwrite(t.m1.data(), sizeof(float), len, f) ==
             static_cast<size_t>(len) &&
         std::fwrite(t.m2.data(), sizeof(float), len, f) ==
             static_cast<size_t>(len);
  return (std::fclose(f) == 0) && ok;
}

bool load_dense(DenseTable& t, const std::string& path) {
  std::lock_guard<std::mutex> lk(t.mu);
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return false;
  int64_t len = 0;
  int32_t has_m = 0;
  bool ok = std::fread(&len, sizeof(len), 1, f) == 1 &&
            len == static_cast<int64_t>(t.data.size()) &&
            std::fread(&has_m, sizeof(has_m), 1, f) == 1 &&
            std::fread(&t.beta1_pow, sizeof(double), 1, f) == 1 &&
            std::fread(&t.beta2_pow, sizeof(double), 1, f) == 1 &&
            std::fread(t.data.data(), sizeof(float), len, f) ==
                static_cast<size_t>(len);
  if (ok && has_m) {
    if (t.m1.empty()) t.m1.resize(len);
    if (t.m2.empty()) t.m2.resize(len);
    ok = std::fread(t.m1.data(), sizeof(float), len, f) ==
             static_cast<size_t>(len) &&
         std::fread(t.m2.data(), sizeof(float), len, f) ==
             static_cast<size_t>(len);
  }
  std::fclose(f);
  return ok;
}

struct Server {
  int listen_fd = -1;
  int port = 0;
  int server_id = 0;
  int n_servers = 1;
  int n_trainers = 1;
  std::atomic<bool> running{true};

  std::mutex tables_mu;
  std::map<uint32_t, std::unique_ptr<SparseTable>> sparse;
  std::map<uint32_t, std::unique_ptr<DenseTable>> dense;

  // barrier state (reference: BarrierTable) — generation-counted so
  // consecutive barriers can't confuse stragglers
  std::mutex bar_mu;
  std::condition_variable bar_cv;
  int bar_count = 0;
  uint64_t bar_gen = 0;

  std::thread accept_thread;
  std::mutex conns_mu;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;  // live connection sockets, for stop() wakeup

  // wait() support
  std::mutex stop_mu;
  std::condition_variable stop_cv;

  // KV / lease store (the etcd replacement for elastic membership and
  // launch-master endpoint discovery). deadline_ms < 0 = plain put (no
  // expiry); leases expire by steady-clock comparison at read time.
  std::mutex kv_mu;
  struct KvEntry {
    std::string value;
    double deadline_ms = -1.0;
  };
  std::map<std::string, KvEntry> kv;

  static double now_ms() {
    return std::chrono::duration<double, std::milli>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  SparseTable* get_sparse(uint32_t id) {
    std::lock_guard<std::mutex> lk(tables_mu);
    auto it = sparse.find(id);
    return it == sparse.end() ? nullptr : it->second.get();
  }

  DenseTable* get_dense(uint32_t id) {
    std::lock_guard<std::mutex> lk(tables_mu);
    auto it = dense.find(id);
    return it == dense.end() ? nullptr : it->second.get();
  }

  void reply(int fd, const Header& req, uint32_t status, const void* payload,
             int64_t nbytes, int64_t n = 0) {
    Header h{kMagic, req.cmd, req.table_id, status, n, nbytes};
    if (!write_full(fd, &h, sizeof(h))) return;
    if (nbytes > 0) write_full(fd, payload, static_cast<size_t>(nbytes));
  }

  void handle_conn(int fd) {
    std::vector<char> buf;
    while (running.load()) {
      Header h{};
      if (!read_full(fd, &h, sizeof(h)) || h.magic != kMagic) break;
      buf.resize(static_cast<size_t>(h.nbytes));
      if (h.nbytes > 0 && !read_full(fd, buf.data(), buf.size())) break;
      if (!dispatch(fd, h, buf)) break;
    }
    {
      std::lock_guard<std::mutex> lk(conns_mu);
      for (auto it = conn_fds.begin(); it != conn_fds.end(); ++it)
        if (*it == fd) {
          conn_fds.erase(it);
          break;
        }
    }
    ::close(fd);
  }

  // unblock every handler thread parked in recv() so destroy can join —
  // without this, a client that never closes its socket would wedge
  // shutdown (threads block in read_full until the peer closes)
  void shutdown_conns() {
    std::lock_guard<std::mutex> lk(conns_mu);
    for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
  }

  bool dispatch(int fd, const Header& h, std::vector<char>& payload) {
    switch (h.cmd) {
      case CMD_PING: {
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_CREATE_SPARSE: {
        // payload: i32 dim, i32 shard_num, i32 opt, f32 lr, f32 range, u64 seed
        if (payload.size() < 3 * 4 + 2 * 4 + 8) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        const char* p = payload.data();
        int32_t dim, shard_num, opt;
        float lr, range;
        uint64_t seed;
        std::memcpy(&dim, p, 4);
        std::memcpy(&shard_num, p + 4, 4);
        std::memcpy(&opt, p + 8, 4);
        std::memcpy(&lr, p + 12, 4);
        std::memcpy(&range, p + 16, 4);
        std::memcpy(&seed, p + 20, 8);
        std::lock_guard<std::mutex> lk(tables_mu);
        if (!sparse.count(h.table_id)) {
          sparse.emplace(h.table_id,
                         std::make_unique<SparseTable>(dim, shard_num, opt, lr,
                                                       range, seed));
        }
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_CREATE_DENSE: {
        // payload: i32 opt, f32 lr, i64 len, [f32 init[len]]
        if (payload.size() < 16) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        const char* p = payload.data();
        int32_t opt;
        float lr;
        int64_t len;
        std::memcpy(&opt, p, 4);
        std::memcpy(&lr, p + 4, 4);
        std::memcpy(&len, p + 8, 8);
        const float* init = nullptr;
        if (payload.size() >= 16 + sizeof(float) * static_cast<size_t>(len))
          init = reinterpret_cast<const float*>(p + 16);
        std::lock_guard<std::mutex> lk(tables_mu);
        if (!dense.count(h.table_id)) {
          dense.emplace(h.table_id,
                        std::make_unique<DenseTable>(opt, lr, len, init));
        }
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_PULL_SPARSE: {
        SparseTable* t = get_sparse(h.table_id);
        const int64_t n = h.n;
        if (!t || payload.size() < sizeof(int64_t) * static_cast<size_t>(n)) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        // per-thread reusable buffer: chunked pipelined pulls hit this
        // per chunk — a fresh vector would memset MBs on every request
        static thread_local std::vector<float> out;
        const size_t need = static_cast<size_t>(n) * t->emb_dim;
        if (out.size() < need) out.resize(need);
        t->pull(reinterpret_cast<const int64_t*>(payload.data()), n,
                out.data(), (h.flags & kFlagCreate) != 0);
        reply(fd, h, kStatusOk, out.data(),
              static_cast<int64_t>(need * sizeof(float)), n);
        return true;
      }
      case CMD_PUSH_SPARSE: {
        SparseTable* t = get_sparse(h.table_id);
        const int64_t n = h.n;
        if (!t ||
            payload.size() < n * (sizeof(int64_t) +
                                  sizeof(float) * static_cast<size_t>(
                                                      t ? t->emb_dim : 0))) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        const int64_t* keys = reinterpret_cast<const int64_t*>(payload.data());
        const float* grads = reinterpret_cast<const float*>(
            payload.data() + sizeof(int64_t) * static_cast<size_t>(n));
        t->push(keys, n, grads, (h.flags & kFlagRaw) != 0);
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_SET_CTR: {
        // payload: f32 show_coeff, click_coeff, decay, threshold, unseen
        SparseTable* t = get_sparse(h.table_id);
        if (!t || payload.size() < 5 * sizeof(float)) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        const float* p = reinterpret_cast<const float*>(payload.data());
        t->ctr.enabled = true;
        t->ctr.show_coeff = p[0];
        t->ctr.click_coeff = p[1];
        t->ctr.decay_rate = p[2];
        t->ctr.delete_threshold = p[3];
        t->ctr.delete_after_unseen_days = p[4];
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_PUSH_CTR: {
        // payload: i64 keys[n], f32 shows[n], f32 clicks[n], f32 grads[n*dim]
        SparseTable* t = get_sparse(h.table_id);
        const int64_t n = h.n;
        if (!t || payload.size() <
                      static_cast<size_t>(n) *
                          (sizeof(int64_t) + 2 * sizeof(float) +
                           sizeof(float) * t->emb_dim)) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        const char* p = payload.data();
        const int64_t* keys = reinterpret_cast<const int64_t*>(p);
        const float* shows =
            reinterpret_cast<const float*>(p + sizeof(int64_t) * n);
        const float* clicks = shows + n;
        const float* grads = clicks + n;
        t->push_ctr(keys, n, shows, clicks, grads);
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_SHRINK: {
        SparseTable* t = get_sparse(h.table_id);
        if (!t) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        int64_t evicted = t->shrink();
        reply(fd, h, kStatusOk, &evicted, sizeof(evicted));
        return true;
      }
      case CMD_CTR_STATS: {
        SparseTable* t = get_sparse(h.table_id);
        if (!t || payload.size() < sizeof(int64_t)) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        int64_t key;
        std::memcpy(&key, payload.data(), sizeof(key));
        float out[4] = {0, 0, 0, 0};
        if (!t->ctr_stats(key, out)) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        reply(fd, h, kStatusOk, out, sizeof(out));
        return true;
      }
      case CMD_PULL_DENSE: {
        DenseTable* t = get_dense(h.table_id);
        if (!t) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        std::vector<float> out(t->data.size());
        t->pull(out.data());
        reply(fd, h, kStatusOk, out.data(),
              static_cast<int64_t>(out.size() * sizeof(float)),
              static_cast<int64_t>(out.size()));
        return true;
      }
      case CMD_PUSH_DENSE: {
        DenseTable* t = get_dense(h.table_id);
        if (!t || payload.size() < sizeof(float) * t->data.size()) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        t->push(reinterpret_cast<const float*>(payload.data()));
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_PUSH_PULL_DENSE: {
        // fused round trip (the reference communicator's batched
        // send_and_recv): apply this trainer's grads, reply the updated
        // chunk — halves the per-step round trips of push-then-pull
        DenseTable* t = get_dense(h.table_id);
        if (!t || payload.size() < sizeof(float) * t->data.size()) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        t->push(reinterpret_cast<const float*>(payload.data()));
        std::vector<float> out(t->data.size());
        t->pull(out.data());
        reply(fd, h, kStatusOk, out.data(),
              static_cast<int64_t>(out.size() * sizeof(float)),
              static_cast<int64_t>(out.size()));
        return true;
      }
      case CMD_SET_DENSE: {
        DenseTable* t = get_dense(h.table_id);
        if (!t || payload.size() < sizeof(float) * t->data.size()) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        t->set(reinterpret_cast<const float*>(payload.data()));
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_BARRIER: {
        uint64_t gen;
        {
          std::unique_lock<std::mutex> lk(bar_mu);
          gen = bar_gen;
          if (++bar_count >= n_trainers) {
            bar_count = 0;
            ++bar_gen;
            bar_cv.notify_all();
          } else {
            bar_cv.wait(lk, [&] {
              return bar_gen != gen || !running.load();
            });
          }
        }
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_SAVE:
      case CMD_LOAD: {
        std::string dir(payload.data(), payload.size());
        bool ok = true;
        std::lock_guard<std::mutex> lk(tables_mu);
        for (auto& kv : sparse) {
          std::string path = dir + "/sparse_" + std::to_string(kv.first) +
                             ".part" + std::to_string(server_id);
          ok = (h.cmd == CMD_SAVE) ? (ok && kv.second->save(path.c_str()))
                                   : (ok && kv.second->load(path.c_str()));
        }
        // dense tables (values + adam moments) checkpoint too — they ARE
        // the model in DenseTableHandle mode
        for (auto& kv : dense) {
          std::string path = dir + "/dense_" + std::to_string(kv.first) +
                             ".part" + std::to_string(server_id);
          ok = (h.cmd == CMD_SAVE) ? (ok && save_dense(*kv.second, path))
                                   : (ok && load_dense(*kv.second, path));
        }
        reply(fd, h, ok ? kStatusOk : kStatusErr, nullptr, 0);
        return true;
      }
      case CMD_STAT: {
        // table_id 0 → whole fleet; nonzero → that sparse table only
        int64_t total = 0;
        {
          std::lock_guard<std::mutex> lk(tables_mu);
          for (auto& kv : sparse)
            if (h.table_id == 0 || kv.first == h.table_id)
              total += kv.second->size();
        }
        reply(fd, h, kStatusOk, nullptr, 0, total);
        return true;
      }
      case CMD_SET_LR: {
        if (payload.size() < 4) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        float lr;
        std::memcpy(&lr, payload.data(), 4);
        std::lock_guard<std::mutex> lk(tables_mu);
        for (auto& kv : sparse)
          if (h.table_id == 0 || kv.first == h.table_id)
            kv.second->lr = lr;
        for (auto& kv : dense)
          if (h.table_id == 0 || kv.first == h.table_id)
            kv.second->lr = lr;
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_KV_PUT:
      case CMD_KV_LEASE: {
        // payload: i32 klen, key[klen], value[rest]
        if (payload.size() < 4) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        int32_t klen;
        std::memcpy(&klen, payload.data(), 4);
        if (klen < 0 || payload.size() < 4 + static_cast<size_t>(klen)) {
          reply(fd, h, kStatusErr, nullptr, 0);
          return true;
        }
        std::string key(payload.data() + 4, static_cast<size_t>(klen));
        std::string val(payload.data() + 4 + klen,
                        payload.size() - 4 - klen);
        {
          // never hold kv_mu across the reply socket write: a stalled
          // client would block every other node's heartbeat past its TTL
          std::lock_guard<std::mutex> lk(kv_mu);
          KvEntry& e = kv[key];
          e.value = std::move(val);
          e.deadline_ms = h.cmd == CMD_KV_LEASE
                              ? now_ms() + static_cast<double>(h.n)
                              : -1.0;
        }
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_KV_GET: {
        std::string key(payload.data(), payload.size());
        std::string val;
        bool found = false;
        {
          std::lock_guard<std::mutex> lk(kv_mu);
          auto it = kv.find(key);
          if (it != kv.end() && !(it->second.deadline_ms >= 0 &&
                                  now_ms() > it->second.deadline_ms)) {
            val = it->second.value;  // copy; reply happens unlocked
            found = true;
          }
        }
        if (!found) {
          reply(fd, h, kStatusOk, nullptr, 0, /*n=*/-1);  // absent/expired
        } else {
          reply(fd, h, kStatusOk, val.data(),
                static_cast<int64_t>(val.size()), 1);
        }
        return true;
      }
      case CMD_KV_DEL: {
        std::string key(payload.data(), payload.size());
        {
          std::lock_guard<std::mutex> lk(kv_mu);
          kv.erase(key);
        }
        reply(fd, h, kStatusOk, nullptr, 0);
        return true;
      }
      case CMD_KV_ALIVE: {
        // every unexpired key with the prefix: key\0value\0 pairs
        std::string prefix(payload.data(), payload.size());
        std::string out;
        int64_t count = 0;
        {
          std::lock_guard<std::mutex> lk(kv_mu);
          double now = now_ms();
          for (auto it = kv.begin(); it != kv.end();) {
            if (it->second.deadline_ms >= 0 &&
                now > it->second.deadline_ms) {
              it = kv.erase(it);  // lazy expiry compaction
              continue;
            }
            if (it->first.compare(0, prefix.size(), prefix) == 0) {
              out += it->first;
              out.push_back('\0');
              out += it->second.value;
              out.push_back('\0');
              ++count;
            }
            ++it;
          }
        }
        reply(fd, h, kStatusOk, out.data(),
              static_cast<int64_t>(out.size()), count);
        return true;
      }
      case CMD_STOP: {
        reply(fd, h, kStatusOk, nullptr, 0);
        running.store(false);
        {
          std::lock_guard<std::mutex> lk(bar_mu);
          bar_cv.notify_all();
        }
        stop_cv.notify_all();
        // poke the accept loop out of ::accept
        int fd2 = connect_to("127.0.0.1", port);
        if (fd2 >= 0) ::close(fd2);
        return false;
      }
      default:
        reply(fd, h, kStatusErr, nullptr, 0);
        return true;
    }
  }

  void accept_loop() {
    while (running.load()) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (!running.load()) break;
        continue;
      }
      if (!running.load()) {
        ::close(fd);
        break;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      set_bulk_buffers(fd);
      std::lock_guard<std::mutex> lk(conns_mu);
      conn_fds.push_back(fd);
      conns.emplace_back([this, fd] { handle_conn(fd); });
    }
    ::close(listen_fd);
  }
};

}  // namespace
}  // namespace ps

extern "C" {

void* ps_server_create(int port, int server_id, int n_servers,
                       int n_trainers) {
  auto* s = new ps::Server();
  s->server_id = server_id;
  s->n_servers = n_servers;
  s->n_trainers = n_trainers;
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
          0 ||
      ::listen(s->listen_fd, 64) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

int ps_server_port(void* h) { return static_cast<ps::Server*>(h)->port; }

// block until a CMD_STOP arrives (fleet.run_server())
void ps_server_wait(void* h) {
  auto* s = static_cast<ps::Server*>(h);
  std::unique_lock<std::mutex> lk(s->stop_mu);
  s->stop_cv.wait(lk, [&] { return !s->running.load(); });
}

void ps_server_stop(void* h) {
  auto* s = static_cast<ps::Server*>(h);
  s->running.store(false);
  s->stop_cv.notify_all();
  {
    std::lock_guard<std::mutex> lk(s->bar_mu);
    s->bar_cv.notify_all();
  }
  int fd = ps::connect_to("127.0.0.1", s->port);
  if (fd >= 0) ::close(fd);
}

void ps_server_destroy(void* h) {
  auto* s = static_cast<ps::Server*>(h);
  ps_server_stop(h);
  s->shutdown_conns();
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // handler threads may still be erasing from conn_fds — join them without
  // holding conns_mu (they take it on exit), then delete
  std::vector<std::thread> conns;
  {
    std::lock_guard<std::mutex> lk(s->conns_mu);
    conns.swap(s->conns);
  }
  for (auto& t : conns)
    if (t.joinable()) t.join();
  delete s;
}

}  // extern "C"
