// Sharded host graph table: adjacency + node features + neighbor sampling.
//
// Reference analogue: paddle/fluid/distributed/ps/table/common_graph_table.h
// (GraphShard/GraphTable: bucketed nodes, weighted neighbor sampling,
// feature nodes) — the storage side of the GNN pipeline whose compute side
// is paddle.incubate.graph_sample_neighbors / graph_send_recv. Single-host
// in-process here; the multi-host extension shards node ids by the same
// hash over the PS wire, exactly like the sparse tables.
#pragma once

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace ps {

struct GraphNodeEntry {
  // neighbors with LAZY cumulative weights: cumw stays empty for
  // unweighted graphs (the common GNN case — 8 bytes/edge, and weighted
  // sampling degenerates to uniform-with-replacement); the first
  // weighted edge materializes 1.0-prefix sums for what came before.
  // Weighted sampling is one binary search per draw (the reference
  // builds alias tables; cumulative sums are simpler and equally
  // O(log d)).
  std::vector<int64_t> nbrs;
  std::vector<float> cumw;  // inclusive prefix sums; empty = all-1.0
  std::vector<float> feat;  // optional per-node feature vector
};

struct GraphShardT {
  std::unordered_map<int64_t, GraphNodeEntry> map;
  std::vector<int64_t> ids;  // insertion order, for random_sample_nodes
  std::mutex mu;
};

struct GraphTable {
  int shard_num;
  int feat_dim;
  uint64_t seed;
  std::vector<GraphShardT> shards;

  GraphTable(int nshard, int fdim, uint64_t seed_)
      : shard_num(nshard < 1 ? 1 : nshard),
        feat_dim(fdim < 0 ? 0 : fdim),
        seed(seed_),
        shards(static_cast<size_t>(shard_num)) {}

  int shard_of(int64_t id) const {
    uint64_t h = (static_cast<uint64_t>(id) * 0x9E3779B97F4A7C15ULL) >> 32;
    return static_cast<int>(h % static_cast<uint64_t>(shard_num));
  }

  GraphNodeEntry& ensure(GraphShardT& sh, int64_t id) {
    auto it = sh.map.find(id);
    if (it == sh.map.end()) {
      it = sh.map.emplace(id, GraphNodeEntry{}).first;
      sh.ids.push_back(id);
    }
    return it->second;
  }

  // append directed edges src->dst with weights (nullptr = all 1.0,
  // stored weight-free)
  void add_edges(const int64_t* src, const int64_t* dst, const float* w,
                 int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
      GraphShardT& sh = shards[shard_of(src[i])];
      std::lock_guard<std::mutex> lk(sh.mu);
      GraphNodeEntry& e = ensure(sh, src[i]);
      if (w != nullptr && e.cumw.empty() && !e.nbrs.empty()) {
        // first weighted edge after unweighted ones: materialize the
        // implicit all-1.0 prefix for the existing neighbors
        e.cumw.resize(e.nbrs.size());
        for (size_t j = 0; j < e.nbrs.size(); ++j)
          e.cumw[j] = static_cast<float>(j + 1);
      }
      e.nbrs.push_back(dst[i]);
      if (w != nullptr || !e.cumw.empty()) {
        float wi = w ? w[i] : 1.0f;
        float base = e.cumw.empty() ? 0.f : e.cumw.back();
        e.cumw.push_back(base + (wi > 0.f ? wi : 0.f));
      }
    }
  }

  void set_node_feat(const int64_t* ids, int64_t n, const float* feats) {
    for (int64_t i = 0; i < n; ++i) {
      GraphShardT& sh = shards[shard_of(ids[i])];
      std::lock_guard<std::mutex> lk(sh.mu);
      GraphNodeEntry& e = ensure(sh, ids[i]);
      e.feat.assign(feats + i * feat_dim, feats + (i + 1) * feat_dim);
    }
  }

  // out[n * feat_dim]; missing nodes/features read zeros; returns found
  int64_t get_node_feat(const int64_t* ids, int64_t n, float* out) {
    int64_t found = 0;
    for (int64_t i = 0; i < n; ++i) {
      GraphShardT& sh = shards[shard_of(ids[i])];
      std::lock_guard<std::mutex> lk(sh.mu);
      auto it = sh.map.find(ids[i]);
      if (it == sh.map.end() ||
          static_cast<int>(it->second.feat.size()) != feat_dim) {
        std::memset(out + i * feat_dim, 0, sizeof(float) * feat_dim);
      } else {
        std::memcpy(out + i * feat_dim, it->second.feat.data(),
                    sizeof(float) * feat_dim);
        ++found;
      }
    }
    return found;
  }

  int64_t degree(int64_t id) {
    GraphShardT& sh = shards[shard_of(id)];
    std::lock_guard<std::mutex> lk(sh.mu);
    auto it = sh.map.find(id);
    return it == sh.map.end()
               ? 0
               : static_cast<int64_t>(it->second.nbrs.size());
  }

  // sample up to k neighbors per node (reference: graph_neighbor_sample).
  // weighted=true draws by edge weight WITH replacement (cumulative-sum
  // binary search); weighted=false draws uniformly WITHOUT replacement
  // (partial Fisher-Yates over an index scratch). k >= degree returns the
  // whole neighborhood. out_nbrs[n*k] padded with -1; out_cnt[n] real
  // counts.
  void sample_neighbors(const int64_t* ids, int64_t n, int k, bool weighted,
                        uint64_t call_seed, int64_t* out_nbrs,
                        int32_t* out_cnt) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t* row = out_nbrs + i * k;
      std::fill(row, row + k, int64_t(-1));
      GraphShardT& sh = shards[shard_of(ids[i])];
      std::lock_guard<std::mutex> lk(sh.mu);
      auto it = sh.map.find(ids[i]);
      if (it == sh.map.end() || it->second.nbrs.empty()) {
        out_cnt[i] = 0;
        continue;
      }
      const GraphNodeEntry& e = it->second;
      const int d = static_cast<int>(e.nbrs.size());
      std::mt19937_64 gen(seed ^ call_seed ^
                          (static_cast<uint64_t>(ids[i]) * 0x9E3779B9ULL));
      if (d <= k && !weighted) {
        std::memcpy(row, e.nbrs.data(), sizeof(int64_t) * d);
        out_cnt[i] = d;
        continue;
      }
      if (weighted) {
        if (e.cumw.empty()) {
          // unweighted node: weighted semantics = uniform WITH
          // replacement, no prefix array needed
          std::uniform_int_distribution<int> pick(0, d - 1);
          for (int j = 0; j < k; ++j) row[j] = e.nbrs[pick(gen)];
          out_cnt[i] = k;
          continue;
        }
        const float total = e.cumw.back();
        if (total <= 0.f) {
          // every edge weight was <= 0: nothing is samplable (a clamped
          // zero-weight edge must have probability 0, not fallback 1)
          out_cnt[i] = 0;
          continue;
        }
        std::uniform_real_distribution<float> dist(0.f, total);
        for (int j = 0; j < k; ++j) {
          float r = dist(gen);
          auto pos = std::upper_bound(e.cumw.begin(), e.cumw.end(), r);
          int idx = static_cast<int>(pos - e.cumw.begin());
          if (idx >= d) idx = d - 1;
          row[j] = e.nbrs[idx];
        }
        out_cnt[i] = k;
      } else if (k * 4 < d) {
        // hub nodes, k << d: Floyd's distinct-sample — O(k) memory and
        // draws, no O(degree) scratch per call
        std::unordered_set<int> sel;
        sel.reserve(static_cast<size_t>(k) * 2);
        int j2 = 0;
        for (int j = d - k; j < d; ++j) {
          std::uniform_int_distribution<int> pick(0, j);
          int t = pick(gen);
          int chosen = sel.count(t) ? j : t;
          sel.insert(chosen);
          row[j2++] = e.nbrs[chosen];
        }
        out_cnt[i] = k;
      } else {
        // partial Fisher-Yates: k distinct indices of d
        std::vector<int> scratch(d);
        for (int j = 0; j < d; ++j) scratch[j] = j;
        for (int j = 0; j < k; ++j) {
          std::uniform_int_distribution<int> pick(j, d - 1);
          std::swap(scratch[j], scratch[pick(gen)]);
          row[j] = e.nbrs[scratch[j]];
        }
        out_cnt[i] = k;
      }
    }
  }

  // `count` node ids drawn (approximately uniformly) across shards —
  // traversal starts (reference: graph_table random_sample_nodes).
  // Size-weighted shard draws + per-shard indexing: O(count·log) with a
  // small dedup set, never an O(total_nodes) copy per call (10M-node
  // graphs sample seeds every minibatch).
  int64_t random_sample_nodes(int64_t count, uint64_t call_seed,
                              int64_t* out) {
    std::vector<int64_t> prefix(shards.size());
    int64_t total = 0;
    for (size_t s = 0; s < shards.size(); ++s) {
      std::lock_guard<std::mutex> lk(shards[s].mu);
      total += static_cast<int64_t>(shards[s].ids.size());
      prefix[s] = total;
    }
    if (total == 0) return 0;
    std::mt19937_64 gen(seed ^ call_seed);
    const int64_t m = std::min(count, total);
    std::unordered_set<int64_t> taken;  // drawn global indices
    int64_t written = 0;
    // rejection on duplicates: cheap while m << total, and bounded by
    // the classic coupon argument otherwise (m == total degenerates to
    // a full sweep below)
    int64_t attempts = 0;
    const int64_t max_attempts = m * 20 + 64;
    std::uniform_int_distribution<int64_t> pick(0, total - 1);
    while (written < m && attempts < max_attempts) {
      ++attempts;
      int64_t g = pick(gen);
      if (!taken.insert(g).second) continue;
      size_t s = static_cast<size_t>(
          std::upper_bound(prefix.begin(), prefix.end(), g) -
          prefix.begin());
      int64_t local = g - (s == 0 ? 0 : prefix[s - 1]);
      std::lock_guard<std::mutex> lk(shards[s].mu);
      if (local >= static_cast<int64_t>(shards[s].ids.size())) continue;
      out[written++] = shards[s].ids[static_cast<size_t>(local)];
    }
    if (written < m) {
      // duplicate-rejection stalled (m close to total): finish with a
      // deterministic sweep over indices not yet taken
      for (int64_t g = 0; g < total && written < m; ++g) {
        if (taken.count(g)) continue;
        size_t s = static_cast<size_t>(
            std::upper_bound(prefix.begin(), prefix.end(), g) -
            prefix.begin());
        int64_t local = g - (s == 0 ? 0 : prefix[s - 1]);
        std::lock_guard<std::mutex> lk(shards[s].mu);
        if (local >= static_cast<int64_t>(shards[s].ids.size())) continue;
        out[written++] = shards[s].ids[static_cast<size_t>(local)];
      }
    }
    return written;
  }

  // checkpoint format: magic, feat_dim, node count, then per node:
  // id, n_nbrs, nbrs[], has_cumw, [cumw[]], n_feat, [feat[]]
  // (reference: common_graph_table's load/save over edge/feature files)
  bool save(const char* path) {
    // write-to-temp + rename: a failed/interrupted save must never
    // destroy the previous good checkpoint
    std::string tmp = std::string(path) + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return false;
    const uint32_t magic = 0x47545631;  // "GTV1"
    int64_t n = node_count();
    bool ok = std::fwrite(&magic, 4, 1, f) == 1 &&
              std::fwrite(&feat_dim, 4, 1, f) == 1 &&
              std::fwrite(&n, 8, 1, f) == 1;
    for (auto& sh : shards) {
      if (!ok) break;
      std::lock_guard<std::mutex> lk(sh.mu);
      for (int64_t id : sh.ids) {
        const GraphNodeEntry& e = sh.map.at(id);
        int64_t nn = static_cast<int64_t>(e.nbrs.size());
        uint8_t has_w = e.cumw.empty() ? 0 : 1;
        int32_t nf = static_cast<int32_t>(e.feat.size());
        ok = ok && std::fwrite(&id, 8, 1, f) == 1 &&
             std::fwrite(&nn, 8, 1, f) == 1 &&
             (nn == 0 || std::fwrite(e.nbrs.data(), 8, nn, f) ==
                             static_cast<size_t>(nn)) &&
             std::fwrite(&has_w, 1, 1, f) == 1 &&
             (!has_w || std::fwrite(e.cumw.data(), 4, nn, f) ==
                            static_cast<size_t>(nn)) &&
             std::fwrite(&nf, 4, 1, f) == 1 &&
             (nf == 0 || std::fwrite(e.feat.data(), 4, nf, f) ==
                             static_cast<size_t>(nf));
        if (!ok) break;
      }
    }
    ok = (std::fclose(f) == 0) && ok;
    if (ok) ok = std::rename(tmp.c_str(), path) == 0;
    if (!ok) std::remove(tmp.c_str());
    return ok;
  }

  bool load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    // file size bounds every on-disk count: a corrupt header must fail
    // with `false`, never with a bad_alloc escaping the C ABI
    std::fseek(f, 0, SEEK_END);
    const long fsize = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    uint32_t magic = 0;
    int fdim = 0;
    int64_t n = 0;
    if (fsize < 16 || std::fread(&magic, 4, 1, f) != 1 ||
        magic != 0x47545631 || std::fread(&fdim, 4, 1, f) != 1 ||
        fdim != feat_dim || std::fread(&n, 8, 1, f) != 1 || n < 0 ||
        n > fsize) {
      std::fclose(f);
      return false;
    }
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      sh.map.clear();
      sh.ids.clear();
    }
    bool ok = true;
    for (int64_t i = 0; i < n && ok; ++i) {
      int64_t id = 0, nn = 0;
      uint8_t has_w = 0;
      int32_t nf = 0;
      GraphNodeEntry e;
      ok = std::fread(&id, 8, 1, f) == 1 && std::fread(&nn, 8, 1, f) == 1 &&
           nn >= 0 && nn <= fsize / 8;
      if (ok && nn > 0) {
        e.nbrs.resize(static_cast<size_t>(nn));
        ok = std::fread(e.nbrs.data(), 8, nn, f) ==
             static_cast<size_t>(nn);
      }
      ok = ok && std::fread(&has_w, 1, 1, f) == 1;
      if (ok && has_w) {
        e.cumw.resize(static_cast<size_t>(nn));
        ok = std::fread(e.cumw.data(), 4, nn, f) ==
             static_cast<size_t>(nn);
      }
      ok = ok && std::fread(&nf, 4, 1, f) == 1 && nf >= 0 &&
           nf <= fsize / 4;
      if (ok && nf > 0) {
        e.feat.resize(static_cast<size_t>(nf));
        ok = std::fread(e.feat.data(), 4, nf, f) ==
             static_cast<size_t>(nf);
      }
      if (ok) {
        GraphShardT& sh = shards[shard_of(id)];
        std::lock_guard<std::mutex> lk(sh.mu);
        sh.map[id] = std::move(e);
        sh.ids.push_back(id);
      }
    }
    std::fclose(f);
    if (!ok)  // truncated checkpoint: fail loudly with an empty table
      for (auto& sh : shards) {
        std::lock_guard<std::mutex> lk(sh.mu);
        sh.map.clear();
        sh.ids.clear();
      }
    return ok;
  }

  int64_t node_count() {
    int64_t s = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      s += static_cast<int64_t>(sh.map.size());
    }
    return s;
  }

  int64_t edge_count() {
    int64_t s = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      for (auto& kv : sh.map)
        s += static_cast<int64_t>(kv.second.nbrs.size());
    }
    return s;
  }
};

}  // namespace ps
