// Sharded host-RAM sparse embedding table (shared by the in-process facade
// and the networked PsService).
//
// Reference analogue: paddle/fluid/distributed/ps/table/memory_sparse_table.cc
// (sharded unordered_map embedding store with per-shard task parallelism) and
// ps/table/sparse_sgd_rule.cc (per-feature optimizer applied inside the table
// on push — SGD / AdaGrad).
//
// Thread-safety: each shard carries its own mutex, so concurrent pull/push
// calls from different caller threads (multiple trainer connections in the
// PsService) are safe; within one call, run_sharded additionally partitions
// shards across worker threads so a shard's mutex is uncontended in the
// single-caller case.
#pragma once

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace ps {

// pluggable per-feature SGD rules (reference: ps/table/sparse_sgd_rule.h —
// SparseNaiveSGDRule / SparseAdaGradSGDRule / SparseAdamSGDRule)
enum OptType : int32_t { OPT_SGD = 0, OPT_ADAGRAD = 1, OPT_ADAM = 2 };

struct Entry {
  // ONE contiguous block per feature: [emb dim | g2sum dim? | m2 dim?]
  // (g2sum = adagrad accumulator / adam moment1; m2 = adam moment2).
  // A single allocation and a linear touch pattern per row — the split
  // per-state vectors cost an extra heap block and a cache miss each on
  // every push (measured ~20% of the in-process push path)
  std::vector<float> data;
  float b1p = 1.f, b2p = 1.f;  // adam bias-correction powers
  // CTR accessor state (reference: ctr_accessor.h CtrCommonFeatureValue —
  // show/click/unseen_days drive time decay + score-based eviction)
  float show = 0.f, click = 0.f, unseen_days = 0.f;
  // LRU clock for the SSD spill policy (unused without enable_ssd)
  uint64_t tick = 0;
};

// disk-overflow state (reference: ps/table/ssd_sparse_table.h — RAM cache
// in front of a rocksdb store; here: one fixed-record slot file + an
// in-RAM key→slot index per shard, LRU batch spill past a RAM budget)
struct SsdShard {
  std::unordered_map<int64_t, int64_t> index;  // key -> slot
};

struct SsdState {
  int fd = -1;
  std::string path;
  int64_t rec_size = 0;       // bytes per slot (fixed at enable time)
  int64_t ram_budget = 0;     // max RAM entries per TABLE
  std::vector<SsdShard> shards;
  std::vector<int64_t> free_slots;
  int64_t next_slot = 0;
  std::mutex alloc_mu;  // free_slots/next_slot
  std::atomic<uint64_t> clock{1};

  ~SsdState() {
    if (fd >= 0) ::close(fd);
    if (!path.empty()) ::unlink(path.c_str());
  }
};

// reference: CtrCommonAccessor config (table_accessor proto fields
// show_click_decay_rate, delete_threshold, delete_after_unseen_days and
// ShowClickScore's nonclk/click coefficients)
struct CtrParams {
  bool enabled = false;
  float show_coeff = 0.25f;    // reference nonclk_coeff
  float click_coeff = 1.0f;
  float decay_rate = 0.98f;    // per-shrink show/click decay
  float delete_threshold = 0.8f;
  float delete_after_unseen_days = 30.f;
};

struct Shard {
  std::unordered_map<int64_t, Entry> map;
  std::mutex mu;
};

struct SparseTable {
  int emb_dim;
  int shard_num;
  int32_t opt_type;
  float lr;
  float init_range;  // uniform(-init_range, init_range); 0 => zeros
  float adagrad_eps;
  float beta1, beta2;  // adam
  CtrParams ctr;
  std::vector<Shard> shards;
  uint64_t seed;
  std::unique_ptr<SsdState> ssd;  // null = pure-RAM table

  SparseTable(int dim, int nshard, int32_t opt, float lr_, float range,
              uint64_t seed_)
      : emb_dim(dim),
        shard_num(nshard),
        opt_type(opt),
        lr(lr_),
        init_range(range),
        adagrad_eps(1e-6f),
        beta1(0.9f),
        beta2(0.999f),
        shards(nshard),
        seed(seed_) {}

  int shard_of(int64_t key) const {
    uint64_t h = (static_cast<uint64_t>(key) * 0x9E3779B97F4A7C15ULL) >> 32;
    return static_cast<int>(h % static_cast<uint64_t>(shard_num));
  }

  // flat-block accessors (layout depends on the table's optimizer)
  int state_floats() const {
    return emb_dim *
           (1 + (opt_type != OPT_SGD ? 1 : 0) + (opt_type == OPT_ADAM ? 1 : 0));
  }
  float* emb_of(Entry& e) const { return e.data.data(); }
  const float* emb_of(const Entry& e) const { return e.data.data(); }
  float* g2_of(Entry& e) const { return e.data.data() + emb_dim; }
  const float* g2_of(const Entry& e) const { return e.data.data() + emb_dim; }
  float* m2_of(Entry& e) const { return e.data.data() + 2 * emb_dim; }
  const float* m2_of(const Entry& e) const {
    return e.data.data() + 2 * emb_dim;
  }

  void init_entry(int64_t key, Entry* e) const {
    e->data.assign(state_floats(), 0.f);
    if (init_range > 0.f) {
      // per-key deterministic init: same key always gets the same row,
      // independent of insertion order, shard count, or which server/host
      // materializes it (load-bearing for geo replicas)
      std::mt19937_64 gen(seed ^ static_cast<uint64_t>(key));
      std::uniform_real_distribution<float> dist(-init_range, init_range);
      float* emb = e->data.data();
      for (int i = 0; i < emb_dim; ++i) emb[i] = dist(gen);
    }
  }

  // one SGD-rule application on an entry (reference: sparse_sgd_rule.cc
  // UpdateValueWork per rule)
  void apply_rule(Entry& e, const float* g) {
    float* emb = e.data.data();
    if (opt_type == OPT_ADAGRAD) {
      float* g2 = emb + emb_dim;
      for (int i = 0; i < emb_dim; ++i) {
        g2[i] += g[i] * g[i];
        emb[i] -= lr * g[i] / (std::sqrt(g2[i]) + adagrad_eps);
      }
    } else if (opt_type == OPT_ADAM) {
      float* m1 = emb + emb_dim;
      float* m2 = m1 + emb_dim;
      e.b1p *= beta1;
      e.b2p *= beta2;
      for (int i = 0; i < emb_dim; ++i) {
        m1[i] = beta1 * m1[i] + (1.f - beta1) * g[i];
        m2[i] = beta2 * m2[i] + (1.f - beta2) * g[i] * g[i];
        float mh = m1[i] / (1.f - e.b1p);
        float vh = m2[i] / (1.f - e.b2p);
        emb[i] -= lr * mh / (std::sqrt(vh) + adagrad_eps);
      }
    } else {
      for (int i = 0; i < emb_dim; ++i) emb[i] -= lr * g[i];
    }
  }

  float show_click_score(const Entry& e) const {
    return ctr.show_coeff * (e.show - e.click) + ctr.click_coeff * e.click;
  }

  // -- SSD overflow (reference: ps/table/ssd_sparse_table.h) ---------------
  // Entries past `ram_budget` spill to a fixed-record slot file; pull/push
  // transparently promote disk-resident keys back into RAM (LRU batch
  // eviction picks the victims). Call AFTER the optimizer type and CTR
  // accessor are configured — the record layout freezes here.
  bool enable_ssd(const char* path, int64_t ram_budget) {
    auto st = std::make_unique<SsdState>();
    st->fd = ::open(path, O_RDWR | O_CREAT | O_TRUNC, 0600);
    if (st->fd < 0) return false;
    st->path = path;
    st->ram_budget = ram_budget > shard_num ? ram_budget : shard_num;
    st->rec_size = ssd_rec_bytes();
    st->shards.resize(shard_num);
    ssd = std::move(st);
    return true;
  }

  int64_t ssd_rec_bytes() const {
    // key | flat state block [emb|g2|m2] | adam powers | ctr — the state
    // block is byte-identical to the old per-vector layout
    int64_t b = 8 + 4LL * state_floats();
    if (opt_type == OPT_ADAM) b += 8;
    if (ctr.enabled) b += 12;
    return b;
  }

  void ssd_encode(int64_t key, const Entry& e, char* p) const {
    std::memcpy(p, &key, 8);
    p += 8;
    std::memcpy(p, e.data.data(), 4LL * state_floats());
    p += 4LL * state_floats();
    if (opt_type == OPT_ADAM) {
      std::memcpy(p, &e.b1p, 4);
      std::memcpy(p + 4, &e.b2p, 4);
      p += 8;
    }
    if (ctr.enabled) {
      std::memcpy(p, &e.show, 4);
      std::memcpy(p + 4, &e.click, 4);
      std::memcpy(p + 8, &e.unseen_days, 4);
    }
  }

  int64_t ssd_decode(const char* p, Entry* e) const {
    int64_t key;
    std::memcpy(&key, p, 8);
    p += 8;
    e->data.resize(state_floats());
    std::memcpy(e->data.data(), p, 4LL * state_floats());
    p += 4LL * state_floats();
    if (opt_type == OPT_ADAM) {
      std::memcpy(&e->b1p, p, 4);
      std::memcpy(&e->b2p, p + 4, 4);
      p += 8;
    }
    if (ctr.enabled) {
      std::memcpy(&e->show, p, 4);
      std::memcpy(&e->click, p + 4, 4);
      std::memcpy(&e->unseen_days, p + 8, 4);
    }
    return key;
  }

  int64_t ssd_alloc_slot() {
    std::lock_guard<std::mutex> lk(ssd->alloc_mu);
    if (!ssd->free_slots.empty()) {
      int64_t s = ssd->free_slots.back();
      ssd->free_slots.pop_back();
      return s;
    }
    return ssd->next_slot++;
  }

  void ssd_free_slot(int64_t slot) {
    std::lock_guard<std::mutex> lk(ssd->alloc_mu);
    ssd->free_slots.push_back(slot);
  }

  // caller holds the shard lock
  bool ssd_fetch(int shard_id, int64_t key, Entry* e) {
    SsdShard& ss = ssd->shards[shard_id];
    auto it = ss.index.find(key);
    if (it == ss.index.end()) return false;
    std::vector<char> buf(ssd->rec_size);
    if (::pread(ssd->fd, buf.data(), ssd->rec_size,
                it->second * ssd->rec_size) != ssd->rec_size)
      return false;
    ssd_decode(buf.data(), e);
    ssd_free_slot(it->second);
    ss.index.erase(it);
    return true;
  }

  // caller holds the shard lock; spills the coldest ~quarter once the
  // shard's RAM share is exceeded (batching amortizes the tick scan)
  void ssd_spill(int shard_id, Shard& sh) {
    int64_t per_shard = ssd->ram_budget / shard_num;
    if (per_shard < 1) per_shard = 1;
    if (static_cast<int64_t>(sh.map.size()) <= per_shard) return;
    int64_t excess = static_cast<int64_t>(sh.map.size()) - per_shard;
    int64_t batch = excess > per_shard / 4 ? excess : per_shard / 4;
    if (batch < 1) batch = 1;
    if (batch > static_cast<int64_t>(sh.map.size()))
      batch = static_cast<int64_t>(sh.map.size());
    std::vector<std::pair<uint64_t, int64_t>> ages;
    ages.reserve(sh.map.size());
    for (auto& kv : sh.map) ages.push_back({kv.second.tick, kv.first});
    std::nth_element(ages.begin(), ages.begin() + (batch - 1), ages.end());
    std::vector<char> buf(ssd->rec_size);
    SsdShard& ss = ssd->shards[shard_id];
    for (int64_t i = 0; i < batch; ++i) {
      int64_t key = ages[i].second;
      auto it = sh.map.find(key);
      if (it == sh.map.end()) continue;
      int64_t slot = ssd_alloc_slot();
      ssd_encode(key, it->second, buf.data());
      if (::pwrite(ssd->fd, buf.data(), ssd->rec_size,
                   slot * ssd->rec_size) != ssd->rec_size) {
        ssd_free_slot(slot);  // disk full/error: keep the entry in RAM
        continue;
      }
      ss.index[key] = slot;
      sh.map.erase(it);
    }
  }

  // find-or-create with disk promotion; caller holds the shard lock.
  // Returns nullptr when absent and !create.
  Entry* find_entry(Shard& sh, int64_t key, bool create) {
    auto it = sh.map.find(key);
    if (it == sh.map.end() && ssd) {
      Entry e;
      if (ssd_fetch(shard_of(key), key, &e))
        it = sh.map.emplace(key, std::move(e)).first;
    }
    if (it == sh.map.end()) {
      if (!create) return nullptr;
      Entry e;
      init_entry(key, &e);
      it = sh.map.emplace(key, std::move(e)).first;
    }
    Entry& e = it->second;
    if (ssd) {
      e.tick = ssd->clock.fetch_add(1);
      ssd_spill(shard_of(key), sh);
      // the looked-up entry may itself have been spilled when it is the
      // coldest — re-promote so the caller's pointer stays valid. A
      // failed re-read (transient I/O error) falls back to a fresh init:
      // callers write emb_dim floats through the pointer, so an empty
      // data block would be heap corruption, not a recoverable state
      auto again = sh.map.find(key);
      if (again == sh.map.end()) {
        Entry back;
        if (!ssd_fetch(shard_of(key), key, &back)) init_entry(key, &back);
        back.tick = ssd->clock.fetch_add(1);
        again = sh.map.emplace(key, std::move(back)).first;
      }
      return &again->second;
    }
    return &e;
  }

  int64_t ram_size() {
    int64_t s = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      s += static_cast<int64_t>(sh.map.size());
    }
    return s;
  }

  int64_t disk_size() {
    if (!ssd) return 0;
    int64_t s = 0;
    for (int i = 0; i < shard_num; ++i) {
      std::lock_guard<std::mutex> lk(shards[i].mu);
      s += static_cast<int64_t>(ssd->shards[i].index.size());
    }
    return s;
  }

  // gather rows for keys; missing keys are created (reference PullSparse
  // create-on-miss semantics for training; create=false skips creation for
  // inference lookups and returns zeros)
  void pull(const int64_t* keys, int64_t n, float* out, bool create) {
    run_sharded(keys, n, [&](Shard& sh, int64_t idx) {
      int64_t key = keys[idx];
      Entry* e = find_entry(sh, key, create);
      if (e == nullptr) {
        std::memset(out + idx * emb_dim, 0, sizeof(float) * emb_dim);
        return;
      }
      std::memcpy(out + idx * emb_dim, e->data.data(),
                  sizeof(float) * emb_dim);
    });
  }

  // apply optimizer update for grads; raw=true adds the payload directly to
  // the embedding instead (the geo-async delta merge — reference
  // MemorySparseGeoTable's push without an accessor rule)
  void push(const int64_t* keys, int64_t n, const float* grads,
            bool raw = false) {
    run_sharded(keys, n, [&](Shard& sh, int64_t idx) {
      int64_t key = keys[idx];
      Entry& e = *find_entry(sh, key, /*create=*/true);
      const float* g = grads + idx * emb_dim;
      if (raw) {
        float* emb = e.data.data();
        for (int i = 0; i < emb_dim; ++i) emb[i] += g[i];
      } else {
        apply_rule(e, g);
      }
    });
  }

  // CTR push (reference: ctr_accessor.cc Update — fold per-impression
  // show/click counts into the feature value, reset its unseen clock, then
  // apply the SGD rule on the gradient)
  void push_ctr(const int64_t* keys, int64_t n, const float* shows,
                const float* clicks, const float* grads) {
    run_sharded(keys, n, [&](Shard& sh, int64_t idx) {
      int64_t key = keys[idx];
      Entry& e = *find_entry(sh, key, /*create=*/true);
      e.show += shows[idx];
      e.click += clicks[idx];
      e.unseen_days = 0.f;
      apply_rule(e, grads + idx * emb_dim);
    });
  }

  // one decay+eviction pass = one "day" (reference: ctr_accessor.cc
  // UpdateTimeDecay + Shrink): show/click decay, unseen clocks advance,
  // and features whose score fell under delete_threshold — or that were
  // unseen too long — are evicted. Returns the evicted count.
  int64_t shrink() {
    // without the CTR accessor every entry scores 0 — a stray shrink()
    // must not wipe a plain embedding table
    if (!ctr.enabled) return 0;
    int64_t evicted = 0;
    for (int si = 0; si < shard_num; ++si) {
      Shard& sh = shards[si];
      std::lock_guard<std::mutex> lk(sh.mu);
      for (auto it = sh.map.begin(); it != sh.map.end();) {
        Entry& e = it->second;
        e.show *= ctr.decay_rate;
        e.click *= ctr.decay_rate;
        e.unseen_days += 1.f;
        if (e.unseen_days > ctr.delete_after_unseen_days ||
            show_click_score(e) < ctr.delete_threshold) {
          it = sh.map.erase(it);
          ++evicted;
        } else {
          ++it;
        }
      }
      if (!ssd) continue;
      // disk-resident entries age too: read-decay-rewrite (or evict)
      SsdShard& ss = ssd->shards[si];
      std::vector<char> buf(ssd->rec_size);
      for (auto it = ss.index.begin(); it != ss.index.end();) {
        if (::pread(ssd->fd, buf.data(), ssd->rec_size,
                    it->second * ssd->rec_size) != ssd->rec_size) {
          ++it;
          continue;
        }
        Entry e;
        int64_t key = ssd_decode(buf.data(), &e);
        e.show *= ctr.decay_rate;
        e.click *= ctr.decay_rate;
        e.unseen_days += 1.f;
        if (e.unseen_days > ctr.delete_after_unseen_days ||
            show_click_score(e) < ctr.delete_threshold) {
          ssd_free_slot(it->second);
          it = ss.index.erase(it);
          ++evicted;
        } else {
          ssd_encode(key, e, buf.data());
          ::pwrite(ssd->fd, buf.data(), ssd->rec_size,
                   it->second * ssd->rec_size);
          ++it;
        }
      }
    }
    return evicted;
  }

  // out[4] = show, click, unseen_days, score; false when key absent
  bool ctr_stats(int64_t key, float* out) {
    Shard& sh = shards[shard_of(key)];
    std::lock_guard<std::mutex> lk(sh.mu);
    Entry* ep = find_entry(sh, key, /*create=*/false);
    if (ep == nullptr) return false;
    const Entry& e = *ep;
    out[0] = e.show;
    out[1] = e.click;
    out[2] = e.unseen_days;
    out[3] = show_click_score(e);
    return true;
  }

  // shard-parallel execution: keys are bucketed by shard in one pass, each
  // worker thread owns a subset of shards, and the shard mutex is taken
  // ONCE per (shard, call) — amortized locking plus cache-friendly grouped
  // access (reference: shards_task_pool_). fn runs with the lock held.
  template <typename F>
  void run_sharded(const int64_t* keys, int64_t n, F fn) {
    // worker fan-out is capped by the machine: on a single-core host the
    // serial path wins outright (thread spawn is pure overhead), and the
    // pipelined client's per-chunk calls would otherwise each pay it
    static const int hw = [] {
      unsigned c = std::thread::hardware_concurrency();
      return c > 0 ? static_cast<int>(c) : 8;
    }();
    if (n < 1024) {
      for (int64_t i = 0; i < n; ++i) {
        Shard& sh = shards[shard_of(keys[i])];
        std::lock_guard<std::mutex> lk(sh.mu);
        fn(sh, i);
      }
      return;
    }
    std::vector<std::vector<int64_t>> buckets(shard_num);
    for (auto& b : buckets) b.reserve(n / shard_num + 8);
    for (int64_t i = 0; i < n; ++i) buckets[shard_of(keys[i])].push_back(i);
    if (hw <= 1) {
      // single-core host: same amortized one-lock-per-shard pattern,
      // no worker threads
      for (int s = 0; s < shard_num; ++s) {
        if (buckets[s].empty()) continue;
        Shard& sh = shards[s];
        std::lock_guard<std::mutex> lk(sh.mu);
        for (int64_t idx : buckets[s]) fn(sh, idx);
      }
      return;
    }
    int nthreads = std::min<int64_t>(std::min<int64_t>(shard_num, 8), hw);
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
      ts.emplace_back([&, t] {
        for (int s = t; s < shard_num; s += nthreads) {
          if (buckets[s].empty()) continue;
          Shard& sh = shards[s];
          std::lock_guard<std::mutex> lk(sh.mu);
          for (int64_t idx : buckets[s]) fn(sh, idx);
        }
      });
    }
    for (auto& th : ts) th.join();
  }

  int64_t size() { return ram_size() + disk_size(); }

  bool save(const char* path) {
    FILE* f = std::fopen(path, "wb");
    if (!f) return false;
    int64_t n = size();
    // state code: low bits = opt rule (0 sgd / 1 adagrad / 2 adam),
    // +4 = ctr fields present. Codes 0/1 match the pre-ctr format.
    int32_t code = opt_type | (ctr.enabled ? 4 : 0);
    bool ok = std::fwrite(&emb_dim, sizeof(emb_dim), 1, f) == 1 &&
              std::fwrite(&code, sizeof(code), 1, f) == 1 &&
              std::fwrite(&n, sizeof(n), 1, f) == 1;

    auto write_entry = [&](int64_t key, const Entry& e) {
      // the flat [emb|g2|m2] block writes in one call — byte-identical to
      // the historical per-vector format
      const size_t sf = static_cast<size_t>(state_floats());
      ok = ok && std::fwrite(&key, sizeof(int64_t), 1, f) == 1 &&
           std::fwrite(e.data.data(), sizeof(float), sf, f) == sf;
      if (opt_type == OPT_ADAM) {
        ok = ok && std::fwrite(&e.b1p, sizeof(float), 1, f) == 1 &&
             std::fwrite(&e.b2p, sizeof(float), 1, f) == 1;
      }
      if (ctr.enabled) {
        ok = ok && std::fwrite(&e.show, sizeof(float), 1, f) == 1 &&
             std::fwrite(&e.click, sizeof(float), 1, f) == 1 &&
             std::fwrite(&e.unseen_days, sizeof(float), 1, f) == 1;
      }
    };

    for (int si = 0; si < shard_num && ok; ++si) {
      Shard& sh = shards[si];
      std::lock_guard<std::mutex> lk(sh.mu);
      for (const auto& kv : sh.map) {
        write_entry(kv.first, kv.second);
        if (!ok) break;
      }
      if (!ssd || !ok) continue;
      // spilled entries checkpoint in the SAME format: a save/load
      // round-trip is budget-independent
      std::vector<char> buf(ssd->rec_size);
      for (const auto& kv : ssd->shards[si].index) {
        if (::pread(ssd->fd, buf.data(), ssd->rec_size,
                    kv.second * ssd->rec_size) != ssd->rec_size) {
          ok = false;
          break;
        }
        Entry e;
        ssd_decode(buf.data(), &e);
        write_entry(kv.first, e);
        if (!ok) break;
      }
    }
    ok = (std::fclose(f) == 0) && ok;  // disk-full surfaces at flush
    return ok;
  }

  bool load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    int dim = 0;
    int32_t has_g2 = 0;
    int64_t n = 0;
    if (std::fread(&dim, sizeof(dim), 1, f) != 1 || dim != emb_dim ||
        std::fread(&has_g2, sizeof(has_g2), 1, f) != 1 ||
        std::fread(&n, sizeof(n), 1, f) != 1) {
      std::fclose(f);
      return false;
    }
    // restore replaces the whole table (the reference's load contract):
    // stale post-checkpoint rows must not survive a rewind
    clear_all();
    const int32_t file_opt = has_g2 & 3;  // state code: rule bits + ctr bit
    const bool file_ctr = (has_g2 & 4) != 0;
    bool ok = true;
    for (int64_t i = 0; i < n; ++i) {
      int64_t key;
      if (std::fread(&key, sizeof(key), 1, f) != 1) {
        ok = false;  // truncated checkpoint — fail loudly, not partially
        break;
      }
      Entry e;
      e.data.assign(state_floats(), 0.f);
      // file sections read into the table's flat slots when the table's
      // rule has them, else into scratch (rule-mismatch restores keep the
      // embeddings and drop/zero optimizer state, as before)
      std::vector<float> scratch;
      auto read_block = [&](float* dst) {
        float* p = dst;
        if (p == nullptr) {
          scratch.resize(emb_dim);
          p = scratch.data();
        }
        return std::fread(p, sizeof(float), emb_dim, f) ==
               static_cast<size_t>(emb_dim);
      };
      if (!read_block(emb_of(e))) {
        ok = false;
        break;
      }
      if (file_opt != OPT_SGD &&
          !read_block(opt_type != OPT_SGD ? g2_of(e) : nullptr)) {
        ok = false;
        break;
      }
      if (file_opt == OPT_ADAM) {
        if (!read_block(opt_type == OPT_ADAM ? m2_of(e) : nullptr) ||
            std::fread(&e.b1p, sizeof(float), 1, f) != 1 ||
            std::fread(&e.b2p, sizeof(float), 1, f) != 1) {
          ok = false;
          break;
        }
      }
      if (file_ctr) {
        if (std::fread(&e.show, sizeof(float), 1, f) != 1 ||
            std::fread(&e.click, sizeof(float), 1, f) != 1 ||
            std::fread(&e.unseen_days, sizeof(float), 1, f) != 1) {
          ok = false;
          break;
        }
      }
      int si = shard_of(key);
      Shard& sh = shards[si];
      std::lock_guard<std::mutex> lk(sh.mu);
      if (ssd) e.tick = ssd->clock.fetch_add(1);
      sh.map[key] = std::move(e);
      if (ssd) ssd_spill(si, sh);  // budget holds during restore too
    }
    std::fclose(f);
    if (!ok) clear_all();
    return ok;
  }

  void clear_all() {
    for (int si = 0; si < shard_num; ++si) {
      std::lock_guard<std::mutex> lk(shards[si].mu);
      shards[si].map.clear();
      if (ssd) ssd->shards[si].index.clear();
    }
    if (ssd) {
      std::lock_guard<std::mutex> lk(ssd->alloc_mu);
      ssd->free_slots.clear();
      ssd->next_slot = 0;
      if (::ftruncate(ssd->fd, 0) != 0) {
        // truncate failure leaves dead bytes in the slot file; slots are
        // reallocated from 0 so correctness is unaffected
      }
    }
  }
};

}  // namespace ps
