// Sharded host-RAM sparse embedding table (shared by the in-process facade
// and the networked PsService).
//
// Reference analogue: paddle/fluid/distributed/ps/table/memory_sparse_table.cc
// (sharded unordered_map embedding store with per-shard task parallelism) and
// ps/table/sparse_sgd_rule.cc (per-feature optimizer applied inside the table
// on push — SGD / AdaGrad).
//
// Thread-safety: each shard carries its own mutex, so concurrent pull/push
// calls from different caller threads (multiple trainer connections in the
// PsService) are safe; within one call, run_sharded additionally partitions
// shards across worker threads so a shard's mutex is uncontended in the
// single-caller case.
#pragma once

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace ps {

enum OptType : int32_t { OPT_SGD = 0, OPT_ADAGRAD = 1 };

struct Entry {
  std::vector<float> emb;
  std::vector<float> g2sum;  // adagrad accumulator (empty for sgd)
};

struct Shard {
  std::unordered_map<int64_t, Entry> map;
  std::mutex mu;
};

struct SparseTable {
  int emb_dim;
  int shard_num;
  int32_t opt_type;
  float lr;
  float init_range;  // uniform(-init_range, init_range); 0 => zeros
  float adagrad_eps;
  uint64_t seed;
  std::vector<Shard> shards;

  SparseTable(int dim, int nshard, int32_t opt, float lr_, float range,
              uint64_t seed_)
      : emb_dim(dim),
        shard_num(nshard),
        opt_type(opt),
        lr(lr_),
        init_range(range),
        adagrad_eps(1e-6f),
        seed(seed_),
        shards(nshard) {}

  int shard_of(int64_t key) const {
    uint64_t h = (static_cast<uint64_t>(key) * 0x9E3779B97F4A7C15ULL) >> 32;
    return static_cast<int>(h % static_cast<uint64_t>(shard_num));
  }

  void init_entry(int64_t key, Entry* e) const {
    e->emb.resize(emb_dim);
    if (init_range > 0.f) {
      // per-key deterministic init: same key always gets the same row,
      // independent of insertion order, shard count, or which server/host
      // materializes it (load-bearing for geo replicas)
      std::mt19937_64 gen(seed ^ static_cast<uint64_t>(key));
      std::uniform_real_distribution<float> dist(-init_range, init_range);
      for (int i = 0; i < emb_dim; ++i) e->emb[i] = dist(gen);
    }
    if (opt_type == OPT_ADAGRAD) e->g2sum.assign(emb_dim, 0.f);
  }

  // gather rows for keys; missing keys are created (reference PullSparse
  // create-on-miss semantics for training; create=false skips creation for
  // inference lookups and returns zeros)
  void pull(const int64_t* keys, int64_t n, float* out, bool create) {
    run_sharded(keys, n, [&](Shard& sh, int64_t idx) {
      int64_t key = keys[idx];
      auto it = sh.map.find(key);
      if (it == sh.map.end()) {
        if (!create) {
          std::memset(out + idx * emb_dim, 0, sizeof(float) * emb_dim);
          return;
        }
        Entry e;
        init_entry(key, &e);
        it = sh.map.emplace(key, std::move(e)).first;
      }
      std::memcpy(out + idx * emb_dim, it->second.emb.data(),
                  sizeof(float) * emb_dim);
    });
  }

  // apply optimizer update for grads; raw=true adds the payload directly to
  // the embedding instead (the geo-async delta merge — reference
  // MemorySparseGeoTable's push without an accessor rule)
  void push(const int64_t* keys, int64_t n, const float* grads,
            bool raw = false) {
    run_sharded(keys, n, [&](Shard& sh, int64_t idx) {
      int64_t key = keys[idx];
      auto it = sh.map.find(key);
      if (it == sh.map.end()) {
        Entry e;
        init_entry(key, &e);
        it = sh.map.emplace(key, std::move(e)).first;
      }
      Entry& e = it->second;
      const float* g = grads + idx * emb_dim;
      if (raw) {
        for (int i = 0; i < emb_dim; ++i) e.emb[i] += g[i];
      } else if (opt_type == OPT_ADAGRAD) {
        for (int i = 0; i < emb_dim; ++i) {
          e.g2sum[i] += g[i] * g[i];
          e.emb[i] -= lr * g[i] / (std::sqrt(e.g2sum[i]) + adagrad_eps);
        }
      } else {
        for (int i = 0; i < emb_dim; ++i) e.emb[i] -= lr * g[i];
      }
    });
  }

  // shard-parallel execution: keys are bucketed by shard in one pass, each
  // worker thread owns a subset of shards, and the shard mutex is taken
  // ONCE per (shard, call) — amortized locking plus cache-friendly grouped
  // access (reference: shards_task_pool_). fn runs with the lock held.
  template <typename F>
  void run_sharded(const int64_t* keys, int64_t n, F fn) {
    if (n < 1024) {
      for (int64_t i = 0; i < n; ++i) {
        Shard& sh = shards[shard_of(keys[i])];
        std::lock_guard<std::mutex> lk(sh.mu);
        fn(sh, i);
      }
      return;
    }
    std::vector<std::vector<int64_t>> buckets(shard_num);
    for (auto& b : buckets) b.reserve(n / shard_num + 8);
    for (int64_t i = 0; i < n; ++i) buckets[shard_of(keys[i])].push_back(i);
    int nthreads = std::min<int64_t>(shard_num, 8);
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
      ts.emplace_back([&, t] {
        for (int s = t; s < shard_num; s += nthreads) {
          if (buckets[s].empty()) continue;
          Shard& sh = shards[s];
          std::lock_guard<std::mutex> lk(sh.mu);
          for (int64_t idx : buckets[s]) fn(sh, idx);
        }
      });
    }
    for (auto& th : ts) th.join();
  }

  int64_t size() {
    int64_t s = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      s += static_cast<int64_t>(sh.map.size());
    }
    return s;
  }

  bool save(const char* path) {
    FILE* f = std::fopen(path, "wb");
    if (!f) return false;
    int64_t n = size();
    int32_t has_g2 = (opt_type == OPT_ADAGRAD) ? 1 : 0;
    bool ok = std::fwrite(&emb_dim, sizeof(emb_dim), 1, f) == 1 &&
              std::fwrite(&has_g2, sizeof(has_g2), 1, f) == 1 &&
              std::fwrite(&n, sizeof(n), 1, f) == 1;
    for (auto& sh : shards) {
      if (!ok) break;
      std::lock_guard<std::mutex> lk(sh.mu);
      for (const auto& kv : sh.map) {
        ok = ok && std::fwrite(&kv.first, sizeof(int64_t), 1, f) == 1 &&
             std::fwrite(kv.second.emb.data(), sizeof(float), emb_dim, f) ==
                 static_cast<size_t>(emb_dim);
        if (has_g2)
          ok = ok &&
               std::fwrite(kv.second.g2sum.data(), sizeof(float), emb_dim,
                           f) == static_cast<size_t>(emb_dim);
        if (!ok) break;
      }
    }
    ok = (std::fclose(f) == 0) && ok;  // disk-full surfaces at flush
    return ok;
  }

  bool load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    int dim = 0;
    int32_t has_g2 = 0;
    int64_t n = 0;
    if (std::fread(&dim, sizeof(dim), 1, f) != 1 || dim != emb_dim ||
        std::fread(&has_g2, sizeof(has_g2), 1, f) != 1 ||
        std::fread(&n, sizeof(n), 1, f) != 1) {
      std::fclose(f);
      return false;
    }
    // restore replaces the whole table (the reference's load contract):
    // stale post-checkpoint rows must not survive a rewind
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      sh.map.clear();
    }
    bool ok = true;
    for (int64_t i = 0; i < n; ++i) {
      int64_t key;
      if (std::fread(&key, sizeof(key), 1, f) != 1) {
        ok = false;  // truncated checkpoint — fail loudly, not partially
        break;
      }
      Entry e;
      e.emb.resize(emb_dim);
      if (std::fread(e.emb.data(), sizeof(float), emb_dim, f) !=
          static_cast<size_t>(emb_dim)) {
        ok = false;
        break;
      }
      if (has_g2) {
        e.g2sum.resize(emb_dim);
        if (std::fread(e.g2sum.data(), sizeof(float), emb_dim, f) !=
            static_cast<size_t>(emb_dim)) {
          ok = false;
          break;
        }
      } else if (opt_type == OPT_ADAGRAD) {
        e.g2sum.assign(emb_dim, 0.f);
      }
      Shard& sh = shards[shard_of(key)];
      std::lock_guard<std::mutex> lk(sh.mu);
      sh.map[key] = std::move(e);
    }
    std::fclose(f);
    if (!ok)
      for (auto& sh : shards) {
        std::lock_guard<std::mutex> lk(sh.mu);
        sh.map.clear();
      }
    return ok;
  }
};

}  // namespace ps
