// Sharded host-RAM sparse embedding table (shared by the in-process facade
// and the networked PsService).
//
// Reference analogue: paddle/fluid/distributed/ps/table/memory_sparse_table.cc
// (sharded unordered_map embedding store with per-shard task parallelism) and
// ps/table/sparse_sgd_rule.cc (per-feature optimizer applied inside the table
// on push — SGD / AdaGrad).
//
// Thread-safety: each shard carries its own mutex, so concurrent pull/push
// calls from different caller threads (multiple trainer connections in the
// PsService) are safe; within one call, run_sharded additionally partitions
// shards across worker threads so a shard's mutex is uncontended in the
// single-caller case.
#pragma once

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace ps {

// pluggable per-feature SGD rules (reference: ps/table/sparse_sgd_rule.h —
// SparseNaiveSGDRule / SparseAdaGradSGDRule / SparseAdamSGDRule)
enum OptType : int32_t { OPT_SGD = 0, OPT_ADAGRAD = 1, OPT_ADAM = 2 };

struct Entry {
  std::vector<float> emb;
  std::vector<float> g2sum;  // adagrad accumulator / adam moment1
  std::vector<float> m2;     // adam moment2 (empty otherwise)
  float b1p = 1.f, b2p = 1.f;  // adam bias-correction powers
  // CTR accessor state (reference: ctr_accessor.h CtrCommonFeatureValue —
  // show/click/unseen_days drive time decay + score-based eviction)
  float show = 0.f, click = 0.f, unseen_days = 0.f;
};

// reference: CtrCommonAccessor config (table_accessor proto fields
// show_click_decay_rate, delete_threshold, delete_after_unseen_days and
// ShowClickScore's nonclk/click coefficients)
struct CtrParams {
  bool enabled = false;
  float show_coeff = 0.25f;    // reference nonclk_coeff
  float click_coeff = 1.0f;
  float decay_rate = 0.98f;    // per-shrink show/click decay
  float delete_threshold = 0.8f;
  float delete_after_unseen_days = 30.f;
};

struct Shard {
  std::unordered_map<int64_t, Entry> map;
  std::mutex mu;
};

struct SparseTable {
  int emb_dim;
  int shard_num;
  int32_t opt_type;
  float lr;
  float init_range;  // uniform(-init_range, init_range); 0 => zeros
  float adagrad_eps;
  float beta1, beta2;  // adam
  CtrParams ctr;
  std::vector<Shard> shards;
  uint64_t seed;

  SparseTable(int dim, int nshard, int32_t opt, float lr_, float range,
              uint64_t seed_)
      : emb_dim(dim),
        shard_num(nshard),
        opt_type(opt),
        lr(lr_),
        init_range(range),
        adagrad_eps(1e-6f),
        beta1(0.9f),
        beta2(0.999f),
        shards(nshard),
        seed(seed_) {}

  int shard_of(int64_t key) const {
    uint64_t h = (static_cast<uint64_t>(key) * 0x9E3779B97F4A7C15ULL) >> 32;
    return static_cast<int>(h % static_cast<uint64_t>(shard_num));
  }

  void init_entry(int64_t key, Entry* e) const {
    e->emb.resize(emb_dim);
    if (init_range > 0.f) {
      // per-key deterministic init: same key always gets the same row,
      // independent of insertion order, shard count, or which server/host
      // materializes it (load-bearing for geo replicas)
      std::mt19937_64 gen(seed ^ static_cast<uint64_t>(key));
      std::uniform_real_distribution<float> dist(-init_range, init_range);
      for (int i = 0; i < emb_dim; ++i) e->emb[i] = dist(gen);
    }
    if (opt_type == OPT_ADAGRAD) e->g2sum.assign(emb_dim, 0.f);
    if (opt_type == OPT_ADAM) {
      e->g2sum.assign(emb_dim, 0.f);  // moment1
      e->m2.assign(emb_dim, 0.f);
    }
  }

  // one SGD-rule application on an entry (reference: sparse_sgd_rule.cc
  // UpdateValueWork per rule)
  void apply_rule(Entry& e, const float* g) {
    if (opt_type == OPT_ADAGRAD) {
      for (int i = 0; i < emb_dim; ++i) {
        e.g2sum[i] += g[i] * g[i];
        e.emb[i] -= lr * g[i] / (std::sqrt(e.g2sum[i]) + adagrad_eps);
      }
    } else if (opt_type == OPT_ADAM) {
      e.b1p *= beta1;
      e.b2p *= beta2;
      for (int i = 0; i < emb_dim; ++i) {
        e.g2sum[i] = beta1 * e.g2sum[i] + (1.f - beta1) * g[i];
        e.m2[i] = beta2 * e.m2[i] + (1.f - beta2) * g[i] * g[i];
        float mh = e.g2sum[i] / (1.f - e.b1p);
        float vh = e.m2[i] / (1.f - e.b2p);
        e.emb[i] -= lr * mh / (std::sqrt(vh) + adagrad_eps);
      }
    } else {
      for (int i = 0; i < emb_dim; ++i) e.emb[i] -= lr * g[i];
    }
  }

  float show_click_score(const Entry& e) const {
    return ctr.show_coeff * (e.show - e.click) + ctr.click_coeff * e.click;
  }

  // gather rows for keys; missing keys are created (reference PullSparse
  // create-on-miss semantics for training; create=false skips creation for
  // inference lookups and returns zeros)
  void pull(const int64_t* keys, int64_t n, float* out, bool create) {
    run_sharded(keys, n, [&](Shard& sh, int64_t idx) {
      int64_t key = keys[idx];
      auto it = sh.map.find(key);
      if (it == sh.map.end()) {
        if (!create) {
          std::memset(out + idx * emb_dim, 0, sizeof(float) * emb_dim);
          return;
        }
        Entry e;
        init_entry(key, &e);
        it = sh.map.emplace(key, std::move(e)).first;
      }
      std::memcpy(out + idx * emb_dim, it->second.emb.data(),
                  sizeof(float) * emb_dim);
    });
  }

  // apply optimizer update for grads; raw=true adds the payload directly to
  // the embedding instead (the geo-async delta merge — reference
  // MemorySparseGeoTable's push without an accessor rule)
  void push(const int64_t* keys, int64_t n, const float* grads,
            bool raw = false) {
    run_sharded(keys, n, [&](Shard& sh, int64_t idx) {
      int64_t key = keys[idx];
      auto it = sh.map.find(key);
      if (it == sh.map.end()) {
        Entry e;
        init_entry(key, &e);
        it = sh.map.emplace(key, std::move(e)).first;
      }
      Entry& e = it->second;
      const float* g = grads + idx * emb_dim;
      if (raw) {
        for (int i = 0; i < emb_dim; ++i) e.emb[i] += g[i];
      } else {
        apply_rule(e, g);
      }
    });
  }

  // CTR push (reference: ctr_accessor.cc Update — fold per-impression
  // show/click counts into the feature value, reset its unseen clock, then
  // apply the SGD rule on the gradient)
  void push_ctr(const int64_t* keys, int64_t n, const float* shows,
                const float* clicks, const float* grads) {
    run_sharded(keys, n, [&](Shard& sh, int64_t idx) {
      int64_t key = keys[idx];
      auto it = sh.map.find(key);
      if (it == sh.map.end()) {
        Entry e;
        init_entry(key, &e);
        it = sh.map.emplace(key, std::move(e)).first;
      }
      Entry& e = it->second;
      e.show += shows[idx];
      e.click += clicks[idx];
      e.unseen_days = 0.f;
      apply_rule(e, grads + idx * emb_dim);
    });
  }

  // one decay+eviction pass = one "day" (reference: ctr_accessor.cc
  // UpdateTimeDecay + Shrink): show/click decay, unseen clocks advance,
  // and features whose score fell under delete_threshold — or that were
  // unseen too long — are evicted. Returns the evicted count.
  int64_t shrink() {
    // without the CTR accessor every entry scores 0 — a stray shrink()
    // must not wipe a plain embedding table
    if (!ctr.enabled) return 0;
    int64_t evicted = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      for (auto it = sh.map.begin(); it != sh.map.end();) {
        Entry& e = it->second;
        e.show *= ctr.decay_rate;
        e.click *= ctr.decay_rate;
        e.unseen_days += 1.f;
        if (e.unseen_days > ctr.delete_after_unseen_days ||
            show_click_score(e) < ctr.delete_threshold) {
          it = sh.map.erase(it);
          ++evicted;
        } else {
          ++it;
        }
      }
    }
    return evicted;
  }

  // out[4] = show, click, unseen_days, score; false when key absent
  bool ctr_stats(int64_t key, float* out) {
    Shard& sh = shards[shard_of(key)];
    std::lock_guard<std::mutex> lk(sh.mu);
    auto it = sh.map.find(key);
    if (it == sh.map.end()) return false;
    const Entry& e = it->second;
    out[0] = e.show;
    out[1] = e.click;
    out[2] = e.unseen_days;
    out[3] = show_click_score(e);
    return true;
  }

  // shard-parallel execution: keys are bucketed by shard in one pass, each
  // worker thread owns a subset of shards, and the shard mutex is taken
  // ONCE per (shard, call) — amortized locking plus cache-friendly grouped
  // access (reference: shards_task_pool_). fn runs with the lock held.
  template <typename F>
  void run_sharded(const int64_t* keys, int64_t n, F fn) {
    if (n < 1024) {
      for (int64_t i = 0; i < n; ++i) {
        Shard& sh = shards[shard_of(keys[i])];
        std::lock_guard<std::mutex> lk(sh.mu);
        fn(sh, i);
      }
      return;
    }
    std::vector<std::vector<int64_t>> buckets(shard_num);
    for (auto& b : buckets) b.reserve(n / shard_num + 8);
    for (int64_t i = 0; i < n; ++i) buckets[shard_of(keys[i])].push_back(i);
    int nthreads = std::min<int64_t>(shard_num, 8);
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
      ts.emplace_back([&, t] {
        for (int s = t; s < shard_num; s += nthreads) {
          if (buckets[s].empty()) continue;
          Shard& sh = shards[s];
          std::lock_guard<std::mutex> lk(sh.mu);
          for (int64_t idx : buckets[s]) fn(sh, idx);
        }
      });
    }
    for (auto& th : ts) th.join();
  }

  int64_t size() {
    int64_t s = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      s += static_cast<int64_t>(sh.map.size());
    }
    return s;
  }

  bool save(const char* path) {
    FILE* f = std::fopen(path, "wb");
    if (!f) return false;
    int64_t n = size();
    // state code: low bits = opt rule (0 sgd / 1 adagrad / 2 adam),
    // +4 = ctr fields present. Codes 0/1 match the pre-ctr format.
    int32_t code = opt_type | (ctr.enabled ? 4 : 0);
    bool ok = std::fwrite(&emb_dim, sizeof(emb_dim), 1, f) == 1 &&
              std::fwrite(&code, sizeof(code), 1, f) == 1 &&
              std::fwrite(&n, sizeof(n), 1, f) == 1;
    for (auto& sh : shards) {
      if (!ok) break;
      std::lock_guard<std::mutex> lk(sh.mu);
      for (const auto& kv : sh.map) {
        const Entry& e = kv.second;
        ok = ok && std::fwrite(&kv.first, sizeof(int64_t), 1, f) == 1 &&
             std::fwrite(e.emb.data(), sizeof(float), emb_dim, f) ==
                 static_cast<size_t>(emb_dim);
        if (opt_type != OPT_SGD)
          ok = ok && std::fwrite(e.g2sum.data(), sizeof(float), emb_dim,
                                 f) == static_cast<size_t>(emb_dim);
        if (opt_type == OPT_ADAM) {
          ok = ok && std::fwrite(e.m2.data(), sizeof(float), emb_dim, f) ==
                   static_cast<size_t>(emb_dim) &&
               std::fwrite(&e.b1p, sizeof(float), 1, f) == 1 &&
               std::fwrite(&e.b2p, sizeof(float), 1, f) == 1;
        }
        if (ctr.enabled) {
          ok = ok && std::fwrite(&e.show, sizeof(float), 1, f) == 1 &&
               std::fwrite(&e.click, sizeof(float), 1, f) == 1 &&
               std::fwrite(&e.unseen_days, sizeof(float), 1, f) == 1;
        }
        if (!ok) break;
      }
    }
    ok = (std::fclose(f) == 0) && ok;  // disk-full surfaces at flush
    return ok;
  }

  bool load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    int dim = 0;
    int32_t has_g2 = 0;
    int64_t n = 0;
    if (std::fread(&dim, sizeof(dim), 1, f) != 1 || dim != emb_dim ||
        std::fread(&has_g2, sizeof(has_g2), 1, f) != 1 ||
        std::fread(&n, sizeof(n), 1, f) != 1) {
      std::fclose(f);
      return false;
    }
    // restore replaces the whole table (the reference's load contract):
    // stale post-checkpoint rows must not survive a rewind
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> lk(sh.mu);
      sh.map.clear();
    }
    const int32_t file_opt = has_g2 & 3;  // state code: rule bits + ctr bit
    const bool file_ctr = (has_g2 & 4) != 0;
    bool ok = true;
    for (int64_t i = 0; i < n; ++i) {
      int64_t key;
      if (std::fread(&key, sizeof(key), 1, f) != 1) {
        ok = false;  // truncated checkpoint — fail loudly, not partially
        break;
      }
      Entry e;
      e.emb.resize(emb_dim);
      if (std::fread(e.emb.data(), sizeof(float), emb_dim, f) !=
          static_cast<size_t>(emb_dim)) {
        ok = false;
        break;
      }
      if (file_opt != OPT_SGD) {
        e.g2sum.resize(emb_dim);
        if (std::fread(e.g2sum.data(), sizeof(float), emb_dim, f) !=
            static_cast<size_t>(emb_dim)) {
          ok = false;
          break;
        }
      } else if (opt_type != OPT_SGD) {
        e.g2sum.assign(emb_dim, 0.f);
      }
      if (file_opt == OPT_ADAM) {
        e.m2.resize(emb_dim);
        if (std::fread(e.m2.data(), sizeof(float), emb_dim, f) !=
                static_cast<size_t>(emb_dim) ||
            std::fread(&e.b1p, sizeof(float), 1, f) != 1 ||
            std::fread(&e.b2p, sizeof(float), 1, f) != 1) {
          ok = false;
          break;
        }
      } else if (opt_type == OPT_ADAM) {
        e.m2.assign(emb_dim, 0.f);
      }
      if (file_ctr) {
        if (std::fread(&e.show, sizeof(float), 1, f) != 1 ||
            std::fread(&e.click, sizeof(float), 1, f) != 1 ||
            std::fread(&e.unseen_days, sizeof(float), 1, f) != 1) {
          ok = false;
          break;
        }
      }
      Shard& sh = shards[shard_of(key)];
      std::lock_guard<std::mutex> lk(sh.mu);
      sh.map[key] = std::move(e);
    }
    std::fclose(f);
    if (!ok)
      for (auto& sh : shards) {
        std::lock_guard<std::mutex> lk(sh.mu);
        sh.map.clear();
      }
    return ok;
  }
};

}  // namespace ps
