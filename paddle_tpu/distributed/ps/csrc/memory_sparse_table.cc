// MemorySparseTable — host-RAM sharded sparse embedding table.
//
// Reference analogue: paddle/fluid/distributed/ps/table/memory_sparse_table.cc
// (sharded unordered_map embedding store with per-shard task parallelism) and
// ps/table/sparse_sgd_rule.cc (per-feature optimizer rules applied inside the
// table on push — SGD / AdaGrad).
//
// TPU-native role: the TPU holds the dense model; sparse features live in
// host RAM behind this table. PullSparse materializes a minibatch's rows for
// upload to the chip; PushSparse applies the optimizer to the touched rows
// only. Exposed as a C ABI for ctypes (the framework's pybind replacement).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC memory_sparse_table.cc -o libps_table.so -lpthread

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum OptType : int32_t { OPT_SGD = 0, OPT_ADAGRAD = 1 };

struct Entry {
  std::vector<float> emb;
  std::vector<float> g2sum;  // adagrad accumulator (empty for sgd)
};

// Thread-safety model: run_sharded partitions shards across its worker
// threads, so within one pull/push call no shard is touched by two threads.
// Concurrent pull/push calls from DIFFERENT caller threads are NOT
// supported (the reference serializes through per-table task queues; the
// Python layer is effectively single-caller under the GIL + blocking call).
struct Shard {
  std::unordered_map<int64_t, Entry> map;
};

struct Table {
  int emb_dim;
  int shard_num;
  int32_t opt_type;
  float lr;
  float init_range;   // uniform(-init_range, init_range); 0 => zeros
  float adagrad_eps;
  uint64_t seed;
  std::vector<Shard> shards;

  Table(int dim, int nshard, int32_t opt, float lr_, float range, uint64_t seed_)
      : emb_dim(dim),
        shard_num(nshard),
        opt_type(opt),
        lr(lr_),
        init_range(range),
        adagrad_eps(1e-6f),
        seed(seed_),
        shards(nshard) {}

  int shard_of(int64_t key) const {
    uint64_t h = (static_cast<uint64_t>(key) * 0x9E3779B97F4A7C15ULL) >> 32;
    return static_cast<int>(h % static_cast<uint64_t>(shard_num));
  }

  void init_entry(int64_t key, Entry* e) {
    e->emb.resize(emb_dim);
    if (init_range > 0.f) {
      // per-key deterministic init: same key always gets the same row,
      // independent of insertion order or shard count
      std::mt19937_64 gen(seed ^ static_cast<uint64_t>(key));
      std::uniform_real_distribution<float> dist(-init_range, init_range);
      for (int i = 0; i < emb_dim; ++i) e->emb[i] = dist(gen);
    }
    if (opt_type == OPT_ADAGRAD) e->g2sum.assign(emb_dim, 0.f);
  }

  // gather rows for keys; missing keys are created (reference PullSparse
  // create-on-miss semantics for training; pull_only skips creation for
  // inference lookups and returns zeros)
  void pull(const int64_t* keys, int64_t n, float* out, bool create) {
    run_sharded(keys, n, [&](int64_t idx) {
      int64_t key = keys[idx];
      Shard& sh = shards[shard_of(key)];
      auto it = sh.map.find(key);
      if (it == sh.map.end()) {
        if (!create) {
          std::memset(out + idx * emb_dim, 0, sizeof(float) * emb_dim);
          return;
        }
        Entry e;
        init_entry(key, &e);
        it = sh.map.emplace(key, std::move(e)).first;
      }
      std::memcpy(out + idx * emb_dim, it->second.emb.data(),
                  sizeof(float) * emb_dim);
    });
  }

  // apply optimizer update for grads (duplicate keys in one batch fold
  // their updates sequentially, matching the reference's push accumulation)
  void push(const int64_t* keys, int64_t n, const float* grads) {
    run_sharded(keys, n, [&](int64_t idx) {
      int64_t key = keys[idx];
      Shard& sh = shards[shard_of(key)];
      auto it = sh.map.find(key);
      if (it == sh.map.end()) {
        Entry e;
        init_entry(key, &e);
        it = sh.map.emplace(key, std::move(e)).first;
      }
      Entry& e = it->second;
      const float* g = grads + idx * emb_dim;
      if (opt_type == OPT_ADAGRAD) {
        for (int i = 0; i < emb_dim; ++i) {
          e.g2sum[i] += g[i] * g[i];
          e.emb[i] -= lr * g[i] / (std::sqrt(e.g2sum[i]) + adagrad_eps);
        }
      } else {
        for (int i = 0; i < emb_dim; ++i) e.emb[i] -= lr * g[i];
      }
    });
  }

  // shard-parallel execution: each worker owns a subset of shards so no
  // entry is touched by two threads (reference: shards_task_pool_)
  template <typename F>
  void run_sharded(const int64_t* keys, int64_t n, F fn) {
    int nthreads = std::min<int64_t>(shard_num, std::min<int64_t>(n, 8));
    if (nthreads <= 1 || n < 1024) {
      // serialize per shard lock-free
      for (int64_t i = 0; i < n; ++i) fn(i);
      return;
    }
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
      ts.emplace_back([&, t] {
        for (int64_t i = 0; i < n; ++i) {
          if (shard_of(keys[i]) % nthreads == t) fn(i);
        }
      });
    }
    for (auto& th : ts) th.join();
  }

  int64_t size() const {
    int64_t s = 0;
    for (const auto& sh : shards) s += static_cast<int64_t>(sh.map.size());
    return s;
  }

  bool save(const char* path) const {
    FILE* f = std::fopen(path, "wb");
    if (!f) return false;
    int64_t n = size();
    int32_t has_g2 = (opt_type == OPT_ADAGRAD) ? 1 : 0;
    bool ok = std::fwrite(&emb_dim, sizeof(emb_dim), 1, f) == 1 &&
              std::fwrite(&has_g2, sizeof(has_g2), 1, f) == 1 &&
              std::fwrite(&n, sizeof(n), 1, f) == 1;
    for (const auto& sh : shards) {
      if (!ok) break;
      for (const auto& kv : sh.map) {
        ok = ok && std::fwrite(&kv.first, sizeof(int64_t), 1, f) == 1 &&
             std::fwrite(kv.second.emb.data(), sizeof(float), emb_dim, f) ==
                 static_cast<size_t>(emb_dim);
        if (has_g2)
          ok = ok &&
               std::fwrite(kv.second.g2sum.data(), sizeof(float), emb_dim, f) ==
                   static_cast<size_t>(emb_dim);
        if (!ok) break;
      }
    }
    ok = (std::fclose(f) == 0) && ok;  // disk-full surfaces at flush
    return ok;
  }

  bool load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    int dim = 0;
    int32_t has_g2 = 0;
    int64_t n = 0;
    if (std::fread(&dim, sizeof(dim), 1, f) != 1 || dim != emb_dim ||
        std::fread(&has_g2, sizeof(has_g2), 1, f) != 1 ||
        std::fread(&n, sizeof(n), 1, f) != 1) {
      std::fclose(f);
      return false;
    }
    // restore replaces the whole table (the reference's load contract):
    // stale post-checkpoint rows must not survive a rewind
    for (auto& sh : shards) sh.map.clear();
    bool ok = true;
    for (int64_t i = 0; i < n; ++i) {
      int64_t key;
      if (std::fread(&key, sizeof(key), 1, f) != 1) {
        ok = false;  // truncated checkpoint — fail loudly, not partially
        break;
      }
      Entry e;
      e.emb.resize(emb_dim);
      if (std::fread(e.emb.data(), sizeof(float), emb_dim, f) !=
          static_cast<size_t>(emb_dim)) {
        ok = false;
        break;
      }
      if (has_g2) {
        e.g2sum.resize(emb_dim);
        if (std::fread(e.g2sum.data(), sizeof(float), emb_dim, f) !=
            static_cast<size_t>(emb_dim)) {
          ok = false;
          break;
        }
      } else if (opt_type == OPT_ADAGRAD) {
        e.g2sum.assign(emb_dim, 0.f);
      }
      shards[shard_of(key)].map[key] = std::move(e);
    }
    std::fclose(f);
    if (!ok)
      for (auto& sh : shards) sh.map.clear();
    return ok;
  }
};

}  // namespace

extern "C" {

void* ps_table_create(int emb_dim, int shard_num, int opt_type, float lr,
                      float init_range, uint64_t seed) {
  return new Table(emb_dim, shard_num, opt_type, lr, init_range, seed);
}

void ps_table_destroy(void* h) { delete static_cast<Table*>(h); }

void ps_table_pull(void* h, const int64_t* keys, int64_t n, float* out,
                   int create) {
  static_cast<Table*>(h)->pull(keys, n, out, create != 0);
}

void ps_table_push(void* h, const int64_t* keys, int64_t n,
                   const float* grads) {
  static_cast<Table*>(h)->push(keys, n, grads);
}

int64_t ps_table_size(void* h) { return static_cast<Table*>(h)->size(); }

int ps_table_save(void* h, const char* path) {
  return static_cast<Table*>(h)->save(path) ? 0 : -1;
}

int ps_table_load(void* h, const char* path) {
  return static_cast<Table*>(h)->load(path) ? 0 : -1;
}

void ps_table_set_lr(void* h, float lr) { static_cast<Table*>(h)->lr = lr; }

}  // extern "C"
