// MemorySparseTable C ABI — in-process facade over the sharded sparse table
// (table logic lives in ps_sparse_table.h, shared with the networked
// PsService in ps_server.cc / ps_client.cc).
//
// Reference analogue: paddle/fluid/distributed/ps/table/memory_sparse_table.cc
// and ps/table/sparse_sgd_rule.cc. Exposed as a C ABI for ctypes (the
// framework's pybind replacement).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC memory_sparse_table.cc -o libps_table.so -lpthread

#include "graph_table.h"
#include "ps_sparse_table.h"

using ps::GraphTable;
using ps::SparseTable;

extern "C" {

void* ps_table_create(int emb_dim, int shard_num, int opt_type, float lr,
                      float init_range, uint64_t seed) {
  return new SparseTable(emb_dim, shard_num, opt_type, lr, init_range, seed);
}

void ps_table_destroy(void* h) { delete static_cast<SparseTable*>(h); }

void ps_table_pull(void* h, const int64_t* keys, int64_t n, float* out,
                   int create) {
  static_cast<SparseTable*>(h)->pull(keys, n, out, create != 0);
}

void ps_table_push(void* h, const int64_t* keys, int64_t n,
                   const float* grads) {
  static_cast<SparseTable*>(h)->push(keys, n, grads);
}

void ps_table_push_raw(void* h, const int64_t* keys, int64_t n,
                       const float* deltas) {
  static_cast<SparseTable*>(h)->push(keys, n, deltas, /*raw=*/true);
}

int64_t ps_table_size(void* h) { return static_cast<SparseTable*>(h)->size(); }

int ps_table_save(void* h, const char* path) {
  return static_cast<SparseTable*>(h)->save(path) ? 0 : -1;
}

int ps_table_load(void* h, const char* path) {
  return static_cast<SparseTable*>(h)->load(path) ? 0 : -1;
}

void ps_table_set_lr(void* h, float lr) {
  static_cast<SparseTable*>(h)->lr = lr;
}

// -- CTR accessor surface (reference: ctr_accessor.h CtrCommonAccessor) ----
void ps_table_set_ctr(void* h, float show_coeff, float click_coeff,
                      float decay_rate, float delete_threshold,
                      float delete_after_unseen_days) {
  auto* t = static_cast<SparseTable*>(h);
  t->ctr.enabled = true;
  t->ctr.show_coeff = show_coeff;
  t->ctr.click_coeff = click_coeff;
  t->ctr.decay_rate = decay_rate;
  t->ctr.delete_threshold = delete_threshold;
  t->ctr.delete_after_unseen_days = delete_after_unseen_days;
}

void ps_table_push_ctr(void* h, const int64_t* keys, int64_t n,
                       const float* shows, const float* clicks,
                       const float* grads) {
  static_cast<SparseTable*>(h)->push_ctr(keys, n, shows, clicks, grads);
}

int64_t ps_table_shrink(void* h) {
  return static_cast<SparseTable*>(h)->shrink();
}

int ps_table_ctr_stats(void* h, int64_t key, float* out4) {
  return static_cast<SparseTable*>(h)->ctr_stats(key, out4) ? 0 : -1;
}

// -- SSD overflow (reference: ps/table/ssd_sparse_table.h) ------------------
// Entries past ram_budget spill to a fixed-record slot file; all other
// ps_table_* calls work unchanged (pull/push promote from disk). Call after
// ps_table_set_ctr — the record layout freezes here.
int ps_table_enable_ssd(void* h, const char* path, int64_t ram_budget) {
  return static_cast<SparseTable*>(h)->enable_ssd(path, ram_budget) ? 0 : -1;
}

int64_t ps_table_ram_size(void* h) {
  return static_cast<SparseTable*>(h)->ram_size();
}

int64_t ps_table_disk_size(void* h) {
  return static_cast<SparseTable*>(h)->disk_size();
}

// -- graph table (reference: ps/table/common_graph_table.h) -----------------
void* ps_graph_create(int shard_num, int feat_dim, uint64_t seed) {
  return new GraphTable(shard_num, feat_dim, seed);
}

void ps_graph_destroy(void* h) { delete static_cast<GraphTable*>(h); }

void ps_graph_add_edges(void* h, const int64_t* src, const int64_t* dst,
                        const float* w, int64_t n) {
  static_cast<GraphTable*>(h)->add_edges(src, dst, w, n);
}

void ps_graph_set_node_feat(void* h, const int64_t* ids, int64_t n,
                            const float* feats) {
  static_cast<GraphTable*>(h)->set_node_feat(ids, n, feats);
}

int64_t ps_graph_get_node_feat(void* h, const int64_t* ids, int64_t n,
                               float* out) {
  return static_cast<GraphTable*>(h)->get_node_feat(ids, n, out);
}

int64_t ps_graph_degree(void* h, int64_t id) {
  return static_cast<GraphTable*>(h)->degree(id);
}

void ps_graph_sample_neighbors(void* h, const int64_t* ids, int64_t n,
                               int k, int weighted, uint64_t call_seed,
                               int64_t* out_nbrs, int32_t* out_cnt) {
  static_cast<GraphTable*>(h)->sample_neighbors(ids, n, k, weighted != 0,
                                                call_seed, out_nbrs,
                                                out_cnt);
}

int64_t ps_graph_random_sample_nodes(void* h, int64_t count,
                                     uint64_t call_seed, int64_t* out) {
  return static_cast<GraphTable*>(h)->random_sample_nodes(count, call_seed,
                                                          out);
}

int64_t ps_graph_node_count(void* h) {
  return static_cast<GraphTable*>(h)->node_count();
}

int64_t ps_graph_edge_count(void* h) {
  return static_cast<GraphTable*>(h)->edge_count();
}

int ps_graph_save(void* h, const char* path) {
  return static_cast<GraphTable*>(h)->save(path) ? 0 : -1;
}

int ps_graph_load(void* h, const char* path) {
  return static_cast<GraphTable*>(h)->load(path) ? 0 : -1;
}

}  // extern "C"
