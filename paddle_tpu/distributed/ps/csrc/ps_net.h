// Wire protocol + socket helpers shared by the PsService server and client.
//
// Reference analogue: the brpc transport under
// paddle/fluid/distributed/ps/service/brpc_ps_server.h /
// brpc_ps_client.h. This framework replaces brpc with a dependency-free
// length-prefixed binary protocol over TCP (localhost or DCN): every
// request is one framed message and gets exactly one framed response on the
// same connection (connections are per-client-thread serialized).
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace ps {

constexpr uint32_t kMagic = 0x50535631;  // "PSV1"

enum Cmd : uint32_t {
  CMD_PING = 1,
  CMD_CREATE_SPARSE = 2,
  CMD_CREATE_DENSE = 3,
  CMD_PULL_SPARSE = 4,
  CMD_PUSH_SPARSE = 5,
  CMD_PULL_DENSE = 6,
  CMD_PUSH_DENSE = 7,
  CMD_BARRIER = 8,
  CMD_SAVE = 9,
  CMD_LOAD = 10,
  CMD_STAT = 11,
  CMD_SET_LR = 12,
  CMD_STOP = 13,
  CMD_SET_DENSE = 14,
  CMD_SET_CTR = 15,    // configure the CTR accessor on a sparse table
  CMD_PUSH_CTR = 16,   // push with show/click counts (ctr_accessor Update)
  CMD_SHRINK = 17,     // decay + score-based eviction pass
  CMD_CTR_STATS = 18,  // show/click/unseen/score for one key (tests)
  CMD_PUSH_PULL_DENSE = 19,  // fused: apply grads, reply updated values
                             // (one round trip instead of push+pull)
  // KV / lease service (reference: the etcd the elastic manager and the
  // launch master keep membership + endpoint discovery in —
  // fleet/elastic/manager.py:130, launch/controllers/master.py)
  CMD_KV_PUT = 20,    // payload: i32 klen, key, value
  CMD_KV_GET = 21,    // payload: key; resp: value (n = -1 when absent)
  CMD_KV_DEL = 22,    // payload: key
  CMD_KV_LEASE = 23,  // n = ttl_ms; payload: i32 klen, key, value
  CMD_KV_ALIVE = 24,  // payload: prefix; resp: key\0value\0... unexpired
};

// flags bits
constexpr uint32_t kFlagCreate = 1u;  // PULL_SPARSE: create-on-miss
constexpr uint32_t kFlagRaw = 2u;     // PUSH_SPARSE: raw delta add (geo)

struct Header {
  uint32_t magic;
  uint32_t cmd;
  uint32_t table_id;
  uint32_t flags;
  int64_t n;       // element count / trainer id (BARRIER)
  int64_t nbytes;  // payload bytes following this header
};

// status returned in response Header.flags
constexpr uint32_t kStatusOk = 0;
constexpr uint32_t kStatusErr = 1;

inline bool read_full(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t r = ::recv(fd, p, len, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += r;
    len -= static_cast<size_t>(r);
  }
  return true;
}

inline bool write_full(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t r = ::send(fd, p, len, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += r;
    len -= static_cast<size_t>(r);
  }
  return true;
}

// scatter-gather socket IO: rows move straight between the caller's
// strided buffers and the kernel, skipping the gather/scatter memcpy a
// contiguous payload would need (sendmsg/recvmsg keep MSG_NOSIGNAL /
// partial-transfer handling uniform with write_full/read_full)
// MB-scale embedding rows stream through these sockets: default ~208KB
// buffers force a scheduler round trip per fraction of a chunk, which on
// a small host dominates the wire cost. 4MB buffers let a whole pipeline
// chunk sit in flight.
inline void set_bulk_buffers(int fd) {
  int sz = 4 * 1024 * 1024;
  if (const char* env = std::getenv("PS_SOCKBUF")) sz = std::atoi(env);
  if (sz <= 0) return;  // PS_SOCKBUF=0: kernel defaults
  ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &sz, sizeof(sz));
  ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &sz, sizeof(sz));
}

inline bool writev_full(int fd, struct iovec* iov, int cnt) {
  while (cnt > 0) {
    struct msghdr mh {};
    mh.msg_iov = iov;
    mh.msg_iovlen = static_cast<size_t>(cnt);
    ssize_t w = ::sendmsg(fd, &mh, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    while (w > 0 && cnt > 0) {
      if (static_cast<size_t>(w) >= iov->iov_len) {
        w -= static_cast<ssize_t>(iov->iov_len);
        ++iov;
        --cnt;
      } else {
        iov->iov_base = static_cast<char*>(iov->iov_base) + w;
        iov->iov_len -= static_cast<size_t>(w);
        w = 0;
      }
    }
  }
  return true;
}

inline bool readv_full(int fd, struct iovec* iov, int cnt) {
  while (cnt > 0) {
    struct msghdr mh {};
    mh.msg_iov = iov;
    mh.msg_iovlen = static_cast<size_t>(cnt);
    ssize_t r = ::recvmsg(fd, &mh, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    while (r > 0 && cnt > 0) {
      if (static_cast<size_t>(r) >= iov->iov_len) {
        r -= static_cast<ssize_t>(iov->iov_len);
        ++iov;
        --cnt;
      } else {
        iov->iov_base = static_cast<char*>(iov->iov_base) + r;
        iov->iov_len -= static_cast<size_t>(r);
        r = 0;
      }
    }
  }
  return true;
}

inline int connect_to(const std::string& host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  set_bulk_buffers(fd);
  return fd;
}

// "host:port,host:port,..." → endpoint list (shared by the PS client and
// the FleetExecutor MessageBus so the two transports cannot drift)
inline std::vector<std::pair<std::string, int>> parse_endpoints(
    const char* csv) {
  std::vector<std::pair<std::string, int>> peers;
  std::string s(csv);
  size_t pos = 0;
  while (pos < s.size()) {
    size_t comma = s.find(',', pos);
    if (comma == std::string::npos) comma = s.size();
    std::string ep = s.substr(pos, comma - pos);
    pos = comma + 1;
    size_t colon = ep.rfind(':');
    if (colon == std::string::npos) continue;
    peers.emplace_back(ep.substr(0, colon),
                       std::atoi(ep.c_str() + colon + 1));
  }
  return peers;
}

// key → owning server. Distinct finalizer from SparseTable::shard_of so
// server routing and in-server shard routing stay decorrelated.
inline int server_of(int64_t key, int n_servers) {
  uint64_t x = static_cast<uint64_t>(key) + 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  x ^= x >> 31;
  return static_cast<int>(x % static_cast<uint64_t>(n_servers));
}

}  // namespace ps
