// Dense parameter table chunk — the server-resident dense weights of the
// parameter-server training mode.
//
// Reference analogue: paddle/fluid/distributed/ps/table/memory_dense_table.h
// (fixed-size dense param block with an optimizer rule applied on
// push_dense_grad: sgd / adam / summary). Each PsService process owns one
// contiguous chunk of every dense table; the client shards by even ranges.
#pragma once

#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace ps {

enum DenseOptType : int32_t {
  DENSE_OPT_SGD = 0,
  DENSE_OPT_ADAM = 1,
  DENSE_OPT_SUM = 2,  // "summary" rule: value += grad (counters/stats)
};

struct DenseTable {
  int32_t opt_type;
  float lr;
  // adam hypers (reference memory_dense_table defaults)
  float beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f;
  std::vector<float> data;
  std::vector<float> m1, m2;  // adam moments
  double beta1_pow = 1.0, beta2_pow = 1.0;
  std::mutex mu;

  DenseTable(int32_t opt, float lr_, int64_t len, const float* init)
      : opt_type(opt), lr(lr_), data(len, 0.f) {
    if (init) std::memcpy(data.data(), init, sizeof(float) * len);
    if (opt_type == DENSE_OPT_ADAM) {
      m1.assign(len, 0.f);
      m2.assign(len, 0.f);
    }
  }

  void pull(float* out) {
    std::lock_guard<std::mutex> lk(mu);
    std::memcpy(out, data.data(), sizeof(float) * data.size());
  }

  void set(const float* vals) {
    std::lock_guard<std::mutex> lk(mu);
    std::memcpy(data.data(), vals, sizeof(float) * data.size());
  }

  void push(const float* grad) {
    std::lock_guard<std::mutex> lk(mu);
    const int64_t n = static_cast<int64_t>(data.size());
    if (opt_type == DENSE_OPT_ADAM) {
      beta1_pow *= beta1;
      beta2_pow *= beta2;
      const float lr_t =
          lr * std::sqrt(1.0 - beta2_pow) / (1.0 - beta1_pow);
      for (int64_t i = 0; i < n; ++i) {
        m1[i] = beta1 * m1[i] + (1.f - beta1) * grad[i];
        m2[i] = beta2 * m2[i] + (1.f - beta2) * grad[i] * grad[i];
        data[i] -= lr_t * m1[i] / (std::sqrt(m2[i]) + eps);
      }
    } else if (opt_type == DENSE_OPT_SUM) {
      for (int64_t i = 0; i < n; ++i) data[i] += grad[i];
    } else {
      for (int64_t i = 0; i < n; ++i) data[i] -= lr * grad[i];
    }
  }
};

}  // namespace ps
