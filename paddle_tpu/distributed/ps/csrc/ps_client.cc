// PsService client — trainer-side stub talking to every server of the fleet.
//
// Reference analogue: paddle/fluid/distributed/ps/service/brpc_ps_client.h
// (BrpcPsClient: per-server channels, key partitioning by hash, request
// fan-out with region reassembly). Sparse keys route by server_of(key);
// dense tables split into one contiguous chunk per server; requests to the
// involved servers run on parallel threads and results scatter back into
// the caller's buffers in original key order.
//
// C ABI (ctypes): ps_client_create("ip:port,ip:port,...") + verbs below.
// Every call returns 0 on success, -1 on a transport/servers error.

#include <algorithm>
#include <atomic>
#include <cstdlib>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "ps_net.h"

namespace ps {
namespace {

struct Conn {
  std::string host;
  int port = 0;
  int fd = -1;
  std::mutex mu;  // one in-flight request per server connection

  bool ensure() {
    if (fd >= 0) return true;
    fd = connect_to(host, port);
    return fd >= 0;
  }

  void drop() {
    if (fd >= 0) ::close(fd);
    fd = -1;
  }
};

struct Client {
  std::vector<std::unique_ptr<Conn>> conns;

  int n_servers() const { return static_cast<int>(conns.size()); }

  // Commands safe to resend after a mid-request transport failure: the
  // server may or may not have executed the first copy, so only
  // side-effect-free (or overwrite-semantics) verbs retry. PUSH_* would
  // double-apply gradients and BARRIER would double-count an arrival.
  static bool idempotent(uint32_t cmd) {
    switch (cmd) {
      case CMD_PING:
      case CMD_CREATE_SPARSE:
      case CMD_CREATE_DENSE:
      case CMD_PULL_SPARSE:
      case CMD_PULL_DENSE:
      case CMD_SET_DENSE:
      case CMD_STAT:
      case CMD_SET_LR:
      case CMD_SET_CTR:
      case CMD_CTR_STATS:
      case CMD_SAVE:
      case CMD_LOAD:
      case CMD_KV_PUT:    // overwrite semantics
      case CMD_KV_GET:
      case CMD_KV_DEL:
      case CMD_KV_LEASE:  // a re-lease is a refresh
      case CMD_KV_ALIVE:
        return true;
      default:
        return false;
    }
  }

  // one framed request/response on server i
  bool request(int i, Header& h, const void* payload,
               std::vector<char>* resp_payload, int64_t* resp_n = nullptr) {
    Conn& c = *conns[i];
    std::lock_guard<std::mutex> lk(c.mu);
    const int max_attempts = idempotent(h.cmd) ? 2 : 1;
    for (int attempt = 0; attempt < max_attempts; ++attempt) {
      if (!c.ensure()) return false;
      h.magic = kMagic;
      bool ok = write_full(c.fd, &h, sizeof(h)) &&
                (h.nbytes == 0 ||
                 write_full(c.fd, payload, static_cast<size_t>(h.nbytes)));
      Header rh{};
      ok = ok && read_full(c.fd, &rh, sizeof(rh)) && rh.magic == kMagic;
      if (!ok) {
        c.drop();  // stale connection (server restart) — retry once fresh
        continue;
      }
      if (resp_payload) resp_payload->resize(static_cast<size_t>(rh.nbytes));
      if (rh.nbytes > 0) {
        std::vector<char> sink;
        std::vector<char>* dst = resp_payload ? resp_payload : &sink;
        if (!resp_payload) sink.resize(static_cast<size_t>(rh.nbytes));
        if (!read_full(c.fd, dst->data(), static_cast<size_t>(rh.nbytes))) {
          c.drop();
          continue;
        }
      }
      if (resp_n) *resp_n = rh.n;
      return rh.flags == kStatusOk;
    }
    return false;
  }

  // broadcast the same request to all servers (create/save/load/lr/stop)
  bool broadcast(Header h, const void* payload) {
    if (n_servers() == 1) {
      Header hi = h;
      return request(0, hi, payload, nullptr);
    }
    std::atomic<bool> ok{true};
    std::vector<std::thread> ts;
    for (int i = 0; i < n_servers(); ++i) {
      ts.emplace_back([&, i] {
        Header hi = h;
        if (!request(i, hi, payload, nullptr)) ok.store(false);
      });
    }
    for (auto& t : ts) t.join();
    return ok.load();
  }

  // run `work(i)` for each involved server — inline when there is only one
  // (the per-minibatch hot path should not pay thread create/join), fanned
  // out on threads otherwise so per-server RPC latencies overlap
  template <typename W>
  bool fan_out(const std::vector<int>& servers, W work) {
    if (servers.size() == 1) return work(servers[0]);
    std::atomic<bool> ok{true};
    std::vector<std::thread> ts;
    ts.reserve(servers.size());
    for (int s : servers)
      ts.emplace_back([&, s] {
        if (!work(s)) ok.store(false);
      });
    for (auto& t : ts) t.join();
    return ok.load();
  }
};

// dense chunk [start, end) owned by server i
inline void dense_chunk(int64_t len, int n_servers, int i, int64_t* start,
                        int64_t* end) {
  *start = len * i / n_servers;
  *end = len * (i + 1) / n_servers;
}

// -- pipelined sparse transfer (reference: the async Communicator's
// batched, overlapped push/pull — ps/service/communicator/communicator.h).
// One server's batch splits into kChunkKeys-key chunks; a sender thread
// streams the chunk requests while the calling thread consumes the
// responses in order, so serialization, kernel copies, and the server's
// table work overlap instead of running strictly request-by-request. Row
// payloads ride scatter-gather iovecs straight from/to the caller's
// buffers (no gather/scatter copy). Also avoids the pipelining deadlock:
// requests and responses move on independent threads, so a full socket
// buffer in one direction can't wedge the other.
constexpr int64_t kChunkKeys = 8192;
constexpr int kIovBatch = 512;  // rows per sendmsg/recvmsg (< IOV_MAX)

// receive `m` rows into out[idx[j]*emb_dim], batched readv
inline bool recv_rows(int fd, float* out, const int64_t* idx, int64_t m,
                      int emb_dim) {
  const size_t row = sizeof(float) * static_cast<size_t>(emb_dim);
  std::vector<struct iovec> iov(kIovBatch);
  int64_t j = 0;
  while (j < m) {
    int cnt = static_cast<int>(std::min<int64_t>(m - j, kIovBatch));
    for (int k = 0; k < cnt; ++k) {
      iov[k].iov_base = out + idx[j + k] * emb_dim;
      iov[k].iov_len = row;
    }
    if (!readv_full(fd, iov.data(), cnt)) return false;
    j += cnt;
  }
  return true;
}

struct PullPlan {
  const int64_t* keys;
  const std::vector<int64_t>* idx;  // original positions for this server
  uint32_t table_id;
  int emb_dim;
  bool create;
};

// one pull attempt over an (already ensured) connection; caller holds mu
inline bool pull_attempt(Conn& c, const PullPlan& p, float* out) {
  const int64_t total = static_cast<int64_t>(p.idx->size());
  const int64_t nchunks = (total + kChunkKeys - 1) / kChunkKeys;
  std::atomic<bool> send_ok{true};
  std::thread sender([&] {
    std::vector<int64_t> sk;
    for (int64_t ci = 0; ci < nchunks; ++ci) {
      const int64_t b = ci * kChunkKeys;
      const int64_t e = std::min(total, b + kChunkKeys);
      sk.resize(static_cast<size_t>(e - b));
      for (int64_t j = b; j < e; ++j) sk[j - b] = p.keys[(*p.idx)[j]];
      Header h{kMagic, CMD_PULL_SPARSE, p.table_id,
               p.create ? kFlagCreate : 0u, e - b,
               static_cast<int64_t>(sk.size() * sizeof(int64_t))};
      if (!write_full(c.fd, &h, sizeof(h)) ||
          !write_full(c.fd, sk.data(), sk.size() * sizeof(int64_t))) {
        send_ok.store(false);
        return;
      }
    }
  });
  bool ok = true;
  for (int64_t ci = 0; ci < nchunks && ok; ++ci) {
    const int64_t b = ci * kChunkKeys;
    const int64_t e = std::min(total, b + kChunkKeys);
    Header rh{};
    ok = read_full(c.fd, &rh, sizeof(rh)) && rh.magic == kMagic &&
         rh.flags == kStatusOk &&
         rh.nbytes == (e - b) * static_cast<int64_t>(sizeof(float)) *
                          p.emb_dim &&
         recv_rows(c.fd, out, p.idx->data() + b, e - b, p.emb_dim);
  }
  // receiver aborted mid-stream (bad header / desync): the server keeps
  // streaming replies and eventually blocks, which would wedge the sender
  // in write_full forever — kill the socket so sender.join() returns
  if (!ok) ::shutdown(c.fd, SHUT_RDWR);
  sender.join();
  return ok && send_ok.load();
}

// pipelined pull for one server, with the idempotent-retry contract
inline bool pull_server(Client* c, int s, const PullPlan& p, float* out) {
  Conn& conn = *c->conns[s];
  std::lock_guard<std::mutex> lk(conn.mu);
  for (int attempt = 0; attempt < 2; ++attempt) {
    if (!conn.ensure()) return false;
    if (pull_attempt(conn, p, out)) return true;
    conn.drop();  // stale connection (server restart) — retry once fresh
  }
  return false;
}

// pipelined push for one server: chunk frames are written as ONE
// scatter-gather sendmsg (header + keys + rows straight from the caller's
// grads); a reader thread drains the per-chunk ack headers. PUSH is not
// idempotent, so a transport failure is final (single attempt).
inline bool push_server(Client* c, int s, uint32_t table_id,
                        const int64_t* keys, const std::vector<int64_t>& idx,
                        int emb_dim, const float* grads, bool raw) {
  Conn& conn = *c->conns[s];
  std::lock_guard<std::mutex> lk(conn.mu);
  if (!conn.ensure()) return false;
  const int64_t total = static_cast<int64_t>(idx.size());
  const int64_t nchunks = (total + kChunkKeys - 1) / kChunkKeys;
  const size_t row = sizeof(float) * static_cast<size_t>(emb_dim);
  std::atomic<bool> acks_ok{true};
  std::thread reader([&] {
    for (int64_t ci = 0; ci < nchunks; ++ci) {
      Header rh{};
      if (!read_full(conn.fd, &rh, sizeof(rh)) || rh.magic != kMagic ||
          rh.flags != kStatusOk || rh.nbytes != 0) {
        acks_ok.store(false);
        return;
      }
    }
  });
  bool ok = true;
  std::vector<int64_t> sk;
  std::vector<struct iovec> iov;
  for (int64_t ci = 0; ci < nchunks && ok; ++ci) {
    const int64_t b = ci * kChunkKeys;
    const int64_t e = std::min(total, b + kChunkKeys);
    const int64_t m = e - b;
    sk.resize(static_cast<size_t>(m));
    for (int64_t j = b; j < e; ++j) sk[j - b] = keys[idx[j]];
    Header h{kMagic, CMD_PUSH_SPARSE, table_id, raw ? kFlagRaw : 0u, m,
             static_cast<int64_t>(m * sizeof(int64_t) + m * row)};
    iov.resize(2);
    iov[0] = {&h, sizeof(h)};
    iov[1] = {sk.data(), static_cast<size_t>(m) * sizeof(int64_t)};
    ok = writev_full(conn.fd, iov.data(), 2);
    int64_t j = b;
    while (ok && j < e) {
      int cnt = static_cast<int>(std::min<int64_t>(e - j, kIovBatch));
      iov.resize(static_cast<size_t>(cnt));
      for (int k = 0; k < cnt; ++k) {
        iov[k].iov_base =
            const_cast<float*>(grads + idx[j + k] * emb_dim);
        iov[k].iov_len = row;
      }
      ok = writev_full(conn.fd, iov.data(), cnt);
      j += cnt;
    }
  }
  if (!ok) ::shutdown(conn.fd, SHUT_RDWR);  // unstick the ack reader
  reader.join();
  ok = ok && acks_ok.load();
  if (!ok) conn.drop();
  return ok;
}

}  // namespace
}  // namespace ps

extern "C" {

void* ps_client_create(const char* endpoints_csv) {
  auto* c = new ps::Client();
  for (auto& ep : ps::parse_endpoints(endpoints_csv)) {
    auto conn = std::make_unique<ps::Conn>();
    conn->host = ep.first;
    conn->port = ep.second;
    c->conns.push_back(std::move(conn));
  }
  if (c->conns.empty()) {
    delete c;
    return nullptr;
  }
  return c;
}

void ps_client_destroy(void* h) {
  auto* c = static_cast<ps::Client*>(h);
  for (auto& conn : c->conns) conn->drop();
  delete c;
}

int ps_client_n_servers(void* h) {
  return static_cast<ps::Client*>(h)->n_servers();
}

int ps_client_ping(void* h) {
  ps::Header hd{0, ps::CMD_PING, 0, 0, 0, 0};
  return static_cast<ps::Client*>(h)->broadcast(hd, nullptr) ? 0 : -1;
}

int ps_client_create_sparse(void* h, uint32_t table_id, int dim,
                            int shard_num, int opt, float lr, float range,
                            uint64_t seed) {
  char payload[28];
  std::memcpy(payload, &dim, 4);
  std::memcpy(payload + 4, &shard_num, 4);
  std::memcpy(payload + 8, &opt, 4);
  std::memcpy(payload + 12, &lr, 4);
  std::memcpy(payload + 16, &range, 4);
  std::memcpy(payload + 20, &seed, 8);
  ps::Header hd{0, ps::CMD_CREATE_SPARSE, table_id, 0, 0, 28};
  return static_cast<ps::Client*>(h)->broadcast(hd, payload) ? 0 : -1;
}

// init != nullptr seeds every server's chunk from the trainer-0 values
int ps_client_create_dense(void* h, uint32_t table_id, int64_t len, int opt,
                           float lr, const float* init) {
  auto* c = static_cast<ps::Client*>(h);
  std::atomic<bool> ok{true};
  std::vector<std::thread> ts;
  for (int i = 0; i < c->n_servers(); ++i) {
    ts.emplace_back([&, i] {
      int64_t s, e;
      ps::dense_chunk(len, c->n_servers(), i, &s, &e);
      int64_t chunk = e - s;
      std::vector<char> payload(16 + (init ? sizeof(float) * chunk : 0));
      std::memcpy(payload.data(), &opt, 4);
      std::memcpy(payload.data() + 4, &lr, 4);
      std::memcpy(payload.data() + 8, &chunk, 8);
      if (init)
        std::memcpy(payload.data() + 16, init + s, sizeof(float) * chunk);
      ps::Header hd{0, ps::CMD_CREATE_DENSE, table_id, 0, chunk,
                    static_cast<int64_t>(payload.size())};
      if (!c->request(i, hd, payload.data(), nullptr)) ok.store(false);
    });
  }
  for (auto& t : ts) t.join();
  return ok.load() ? 0 : -1;
}

int ps_client_pull_sparse(void* h, uint32_t table_id, const int64_t* keys,
                          int64_t n, int emb_dim, float* out, int create) {
  auto* c = static_cast<ps::Client*>(h);
  const int S = c->n_servers();
  // partition original positions by owning server
  std::vector<std::vector<int64_t>> pos(S);
  std::vector<int> involved;
  for (int64_t i = 0; i < n; ++i)
    pos[ps::server_of(keys[i], S)].push_back(i);
  for (int s = 0; s < S; ++s)
    if (!pos[s].empty()) involved.push_back(s);
  bool ok = c->fan_out(involved, [&](int s) {
    ps::PullPlan p{keys, &pos[s], table_id, emb_dim, create != 0};
    return ps::pull_server(c, s, p, out);
  });
  return ok ? 0 : -1;
}

int ps_client_push_sparse(void* h, uint32_t table_id, const int64_t* keys,
                          int64_t n, int emb_dim, const float* grads,
                          int raw) {
  auto* c = static_cast<ps::Client*>(h);
  const int S = c->n_servers();
  std::vector<std::vector<int64_t>> pos(S);
  std::vector<int> involved;
  for (int64_t i = 0; i < n; ++i)
    pos[ps::server_of(keys[i], S)].push_back(i);
  for (int s = 0; s < S; ++s)
    if (!pos[s].empty()) involved.push_back(s);
  bool ok = c->fan_out(involved, [&](int s) {
    return ps::push_server(c, s, table_id, keys, pos[s], emb_dim, grads,
                           raw != 0);
  });
  return ok ? 0 : -1;
}

static std::vector<int> all_servers(ps::Client* c) {
  std::vector<int> v(c->n_servers());
  for (int i = 0; i < c->n_servers(); ++i) v[i] = i;
  return v;
}

int ps_client_pull_dense(void* h, uint32_t table_id, float* out,
                         int64_t len) {
  auto* c = static_cast<ps::Client*>(h);
  bool ok = c->fan_out(all_servers(c), [&](int i) {
    int64_t s, e;
    ps::dense_chunk(len, c->n_servers(), i, &s, &e);
    if (e == s) return true;
    ps::Header hd{0, ps::CMD_PULL_DENSE, table_id, 0, 0, 0};
    std::vector<char> resp;
    if (!c->request(i, hd, nullptr, &resp) ||
        resp.size() != sizeof(float) * static_cast<size_t>(e - s))
      return false;
    std::memcpy(out + s, resp.data(), resp.size());
    return true;
  });
  return ok ? 0 : -1;
}

static int dense_scatter(void* h, uint32_t table_id, const float* vals,
                         int64_t len, ps::Cmd cmd) {
  auto* c = static_cast<ps::Client*>(h);
  bool ok = c->fan_out(all_servers(c), [&](int i) {
    int64_t s, e;
    ps::dense_chunk(len, c->n_servers(), i, &s, &e);
    if (e == s) return true;
    ps::Header hd{0, static_cast<uint32_t>(cmd), table_id, 0, e - s,
                  static_cast<int64_t>(sizeof(float) * (e - s))};
    return c->request(i, hd, vals + s, nullptr);
  });
  return ok ? 0 : -1;
}

int ps_client_push_dense(void* h, uint32_t table_id, const float* grads,
                         int64_t len) {
  return dense_scatter(h, table_id, grads, len, ps::CMD_PUSH_DENSE);
}

int ps_client_set_dense(void* h, uint32_t table_id, const float* vals,
                        int64_t len) {
  return dense_scatter(h, table_id, vals, len, ps::CMD_SET_DENSE);
}

// fused push+pull: grads out, updated values back, ONE round trip per
// server chunk (reference: the communicator's batched dense sync)
int ps_client_push_pull_dense(void* h, uint32_t table_id,
                              const float* grads, float* out, int64_t len) {
  auto* c = static_cast<ps::Client*>(h);
  bool ok = c->fan_out(all_servers(c), [&](int i) {
    int64_t s, e;
    ps::dense_chunk(len, c->n_servers(), i, &s, &e);
    if (e == s) return true;
    ps::Header hd{0, ps::CMD_PUSH_PULL_DENSE, table_id, 0, e - s,
                  static_cast<int64_t>(sizeof(float) * (e - s))};
    std::vector<char> resp;
    if (!c->request(i, hd, grads + s, &resp) ||
        resp.size() != sizeof(float) * static_cast<size_t>(e - s))
      return false;
    std::memcpy(out + s, resp.data(), resp.size());
    return true;
  });
  return ok ? 0 : -1;
}

// global barrier across trainers, coordinated by server 0 (reference:
// BarrierTable lives on one server)
int ps_client_barrier(void* h, int trainer_id) {
  ps::Header hd{0, ps::CMD_BARRIER, 0, 0, trainer_id, 0};
  return static_cast<ps::Client*>(h)->request(0, hd, nullptr, nullptr) ? 0
                                                                       : -1;
}

int ps_client_save(void* h, const char* dirname) {
  ps::Header hd{0, ps::CMD_SAVE, 0, 0, 0,
                static_cast<int64_t>(std::strlen(dirname))};
  return static_cast<ps::Client*>(h)->broadcast(hd, dirname) ? 0 : -1;
}

int ps_client_load(void* h, const char* dirname) {
  ps::Header hd{0, ps::CMD_LOAD, 0, 0, 0,
                static_cast<int64_t>(std::strlen(dirname))};
  return static_cast<ps::Client*>(h)->broadcast(hd, dirname) ? 0 : -1;
}

// table_id 0 = every table on the fleet; nonzero = that table only
int64_t ps_client_stat(void* h, uint32_t table_id) {
  auto* c = static_cast<ps::Client*>(h);
  int64_t total = 0;
  for (int i = 0; i < c->n_servers(); ++i) {
    ps::Header hd{0, ps::CMD_STAT, table_id, 0, 0, 0};
    int64_t n = 0;
    if (!c->request(i, hd, nullptr, nullptr, &n)) return -1;
    total += n;
  }
  return total;
}

int ps_client_set_lr(void* h, uint32_t table_id, float lr) {
  ps::Header hd{0, ps::CMD_SET_LR, table_id, 0, 0, 4};
  return static_cast<ps::Client*>(h)->broadcast(hd, &lr) ? 0 : -1;
}

// -- CTR accessor (reference: ctr_accessor.h via BrpcPsClient push) --------
int ps_client_set_ctr(void* h, uint32_t table_id, float show_coeff,
                      float click_coeff, float decay_rate,
                      float delete_threshold, float delete_after_unseen) {
  float cfg[5] = {show_coeff, click_coeff, decay_rate, delete_threshold,
                  delete_after_unseen};
  ps::Header hd{0, ps::CMD_SET_CTR, table_id, 0, 0, sizeof(cfg)};
  return static_cast<ps::Client*>(h)->broadcast(hd, cfg) ? 0 : -1;
}

int ps_client_push_ctr(void* h, uint32_t table_id, const int64_t* keys,
                       int64_t n, int emb_dim, const float* shows,
                       const float* clicks, const float* grads) {
  auto* c = static_cast<ps::Client*>(h);
  const int S = c->n_servers();
  std::vector<std::vector<int64_t>> pos(S);
  std::vector<int> involved;
  for (int64_t i = 0; i < n; ++i)
    pos[ps::server_of(keys[i], S)].push_back(i);
  for (int s = 0; s < S; ++s)
    if (!pos[s].empty()) involved.push_back(s);
  bool ok = c->fan_out(involved, [&](int s) {
    const auto& ps_idx = pos[s];
    const size_t m = ps_idx.size();
    std::vector<char> payload(m * sizeof(int64_t) + 2 * m * sizeof(float) +
                              m * sizeof(float) * emb_dim);
    int64_t* sk = reinterpret_cast<int64_t*>(payload.data());
    float* sshow =
        reinterpret_cast<float*>(payload.data() + m * sizeof(int64_t));
    float* sclick = sshow + m;
    float* sg = sclick + m;
    for (size_t j = 0; j < m; ++j) {
      sk[j] = keys[ps_idx[j]];
      sshow[j] = shows[ps_idx[j]];
      sclick[j] = clicks[ps_idx[j]];
      std::memcpy(sg + j * emb_dim, grads + ps_idx[j] * emb_dim,
                  sizeof(float) * emb_dim);
    }
    ps::Header hd{0, ps::CMD_PUSH_CTR, table_id, 0,
                  static_cast<int64_t>(m),
                  static_cast<int64_t>(payload.size())};
    return c->request(s, hd, payload.data(), nullptr);
  });
  return ok ? 0 : -1;
}

// decay + eviction pass on every server; returns total evicted (or -1)
int64_t ps_client_shrink(void* h, uint32_t table_id) {
  auto* c = static_cast<ps::Client*>(h);
  int64_t total = 0;
  for (int i = 0; i < c->n_servers(); ++i) {
    ps::Header hd{0, ps::CMD_SHRINK, table_id, 0, 0, 0};
    std::vector<char> resp;
    if (!c->request(i, hd, nullptr, &resp) || resp.size() < sizeof(int64_t))
      return -1;
    int64_t e;
    std::memcpy(&e, resp.data(), sizeof(e));
    total += e;
  }
  return total;
}

int ps_client_ctr_stats(void* h, uint32_t table_id, int64_t key,
                        float* out4) {
  auto* c = static_cast<ps::Client*>(h);
  int s = ps::server_of(key, c->n_servers());
  ps::Header hd{0, ps::CMD_CTR_STATS, table_id, 0, 1, sizeof(key)};
  std::vector<char> resp;
  if (!c->request(s, hd, &key, &resp) || resp.size() < 4 * sizeof(float))
    return -1;
  std::memcpy(out4, resp.data(), 4 * sizeof(float));
  return 0;
}

// -- KV / lease verbs (the etcd replacement: elastic membership + launch
// master endpoint discovery). All route to server 0 — the KV master.
static int kv_keyed_put(void* h, uint32_t cmd, int64_t n, const char* key,
                        const char* val, int64_t val_len) {
  auto* c = static_cast<ps::Client*>(h);
  int32_t klen = static_cast<int32_t>(std::strlen(key));
  std::vector<char> payload(4 + klen + val_len);
  std::memcpy(payload.data(), &klen, 4);
  std::memcpy(payload.data() + 4, key, klen);
  if (val_len > 0) std::memcpy(payload.data() + 4 + klen, val, val_len);
  ps::Header hd{0, cmd, 0, 0, n, static_cast<int64_t>(payload.size())};
  return c->request(0, hd, payload.data(), nullptr) ? 0 : -1;
}

int ps_client_kv_put(void* h, const char* key, const char* val,
                     int64_t val_len) {
  return kv_keyed_put(h, ps::CMD_KV_PUT, 0, key, val, val_len);
}

int ps_client_kv_lease(void* h, const char* key, const char* val,
                       int64_t val_len, int64_t ttl_ms) {
  return kv_keyed_put(h, ps::CMD_KV_LEASE, ttl_ms, key, val, val_len);
}

// returns value length (copied into out, up to cap), -1 absent/expired,
// -2 transport error, -3 value larger than cap
int64_t ps_client_kv_get(void* h, const char* key, char* out, int64_t cap) {
  auto* c = static_cast<ps::Client*>(h);
  ps::Header hd{0, ps::CMD_KV_GET, 0, 0, 0,
                static_cast<int64_t>(std::strlen(key))};
  std::vector<char> resp;
  int64_t n = 0;
  if (!c->request(0, hd, key, &resp, &n)) return -2;
  if (n < 0) return -1;
  if (static_cast<int64_t>(resp.size()) > cap) return -3;
  std::memcpy(out, resp.data(), resp.size());
  return static_cast<int64_t>(resp.size());
}

int ps_client_kv_del(void* h, const char* key) {
  auto* c = static_cast<ps::Client*>(h);
  ps::Header hd{0, ps::CMD_KV_DEL, 0, 0, 0,
                static_cast<int64_t>(std::strlen(key))};
  return c->request(0, hd, key, nullptr) ? 0 : -1;
}

// unexpired keys with prefix: key\0value\0... copied into out (up to
// cap); returns byte length, -2 transport error, -3 overflow
int64_t ps_client_kv_alive(void* h, const char* prefix, char* out,
                           int64_t cap) {
  auto* c = static_cast<ps::Client*>(h);
  ps::Header hd{0, ps::CMD_KV_ALIVE, 0, 0, 0,
                static_cast<int64_t>(std::strlen(prefix))};
  std::vector<char> resp;
  if (!c->request(0, hd, prefix, &resp)) return -2;
  if (static_cast<int64_t>(resp.size()) > cap) return -3;
  if (!resp.empty()) std::memcpy(out, resp.data(), resp.size());
  return static_cast<int64_t>(resp.size());
}

int ps_client_stop_servers(void* h) {
  ps::Header hd{0, ps::CMD_STOP, 0, 0, 0, 0};
  return static_cast<ps::Client*>(h)->broadcast(hd, nullptr) ? 0 : -1;
}

}  // extern "C"
