"""paddle.distributed.fleet.base.topology — reference module path for the
process topology (reference: fleet/base/topology.py). The implementation
lives in paddle_tpu.parallel.topology (5-axis mesh dp/mp/pp/sharding/sep).
"""
from ....parallel.topology import (  # noqa: F401
    CommunicateTopology,
    HybridCommunicateGroup,
)

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]
