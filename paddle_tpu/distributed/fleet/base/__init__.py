"""paddle.distributed.fleet.base (reference package path)."""
from . import topology  # noqa: F401
