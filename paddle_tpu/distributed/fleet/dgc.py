"""DGC — Deep Gradient Compression momentum optimizer.

Reference analogue: fleet/meta_optimizers/dgc_optimizer.py +
python/paddle/fluid/optimizer.py DGCMomentumOptimizer over the dgc_op CUDA
kernels: momentum correction + residual accumulation locally, top-k
sparsification with momentum-factor masking, and exchange of only the
selected (index, value) pairs — orders of magnitude less gradient traffic
for bandwidth-bound (DCN) data parallelism.

TPU-native: the local math (momentum, residual, static top-k) is jnp; the
exchange allgathers ONE batched payload of all parameters' indices+values
per step (the compressed bytes the reference sends) and scatter-adds into
dense synchronized gradients. Every process applies the same aggregate, so
replicas stay identical like per-step DP.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ...core.dispatch import no_grad
from ...core.tensor import Tensor

__all__ = ["DGCMomentumOptimizer"]


def _topk_sparsify(v, k):
    """Select top-k |v| entries: returns (idx [k], vals [k], v_residual)."""
    flat = v.reshape(-1)
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    vals = flat[idx]
    residual = flat.at[idx].set(0.0).reshape(v.shape)
    return idx, vals, residual


class DGCMomentumOptimizer:
    """Momentum SGD with top-k compressed gradient synchronization.

    Reference signature semantics: `sparsity` is a rampup schedule of DROP
    fractions (e.g. [0.75, 0.9375, 0.984, 0.996, 0.999] keeps 25% -> 0.1%);
    each schedule stage lasts `rampup_step` steps after `rampup_begin_step`
    dense steps. A bare float is accepted as a one-stage schedule.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 rampup_begin_step: int = 0, rampup_step: int = 1,
                 sparsity: Union[float, Sequence[float]] = (0.999,),
                 parameters=None, grad_clip=None, weight_decay=None,
                 name=None):
        self._lr = learning_rate
        # alias the base-Optimizer attribute name so LR-scheduler plumbing
        # (hapi LRSchedulerCallback) finds the schedule through wrappers
        self._learning_rate = learning_rate
        self._mu = momentum
        self._parameters = list(parameters or [])
        self._sched = [float(s) for s in (
            [sparsity] if isinstance(sparsity, (int, float)) else sparsity
        )]
        if not all(0.0 <= s < 1.0 for s in self._sched):
            raise ValueError("sparsity entries are DROP fractions in [0, 1)")
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._grad_clip = grad_clip
        self._wd = (
            float(weight_decay) if isinstance(weight_decay, (int, float))
            else getattr(weight_decay, "_coeff", None) if weight_decay is not None
            else None
        )
        self._count = 0
        # per-param DGC state: momentum-corrected accumulation u, residual v
        self._u = {}
        self._v = {}

    # --- schedule --------------------------------------------------------
    def _drop_ratio(self) -> Optional[float]:
        """None during the dense warmup; else the scheduled drop fraction."""
        if self._count <= self._rampup_begin:
            return None
        stage = (self._count - self._rampup_begin - 1) // self._rampup_step
        return self._sched[min(stage, len(self._sched) - 1)]

    def _lr_value(self):
        return float(self._lr() if callable(self._lr) else self._lr)

    def clear_grad(self):
        for p in self._parameters:
            p.grad = None

    @no_grad()
    def step(self):
        self._count += 1
        params_grads = [
            (p, p.grad) for p in self._parameters
            if not p.stop_gradient and p.grad is not None
        ]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self._lr_value()
        world = jax.process_count()
        drop = self._drop_ratio()

        sparse_payload = []   # (param, idx, vals) for one batched exchange
        dense_payload = []    # (param, v) during warmup
        for p, g in params_grads:
            gv = (g._value if isinstance(g, Tensor) else g).astype(jnp.float32)
            if self._wd:
                gv = gv + self._wd * p._value.astype(jnp.float32)
            u = self._u.get(id(p))
            v = self._v.get(id(p))
            if u is None:
                u = jnp.zeros_like(gv)
                v = jnp.zeros_like(gv)
            # momentum correction then residual accumulation (DGC paper eq. 4)
            u = self._mu * u + gv
            v = v + u
            if drop is None or gv.size < 2:
                dense_payload.append((p, v))
                v = jnp.zeros_like(v)
            else:
                # from the DROP fraction directly (1-drop in float would
                # truncate: int((1-0.8)*10) == 1, not 2)
                k = max(1, gv.size - int(drop * gv.size))
                idx, vals, v = _topk_sparsify(v, k)
                # momentum-factor masking (DGC paper alg. 2): clear the
                # momentum history of SENT coordinates — keeping it
                # double-counts their contribution and destabilizes training
                u = u.reshape(-1).at[idx].set(0.0).reshape(u.shape)
                sparse_payload.append((p, idx, vals))
            self._u[id(p)] = u
            self._v[id(p)] = v

        # ---- ONE cross-process exchange for everything this step
        if world > 1 and (sparse_payload or dense_payload):
            from jax.experimental import multihost_utils

            packet = [
                [(idx, vals) for _, idx, vals in sparse_payload],
                [v for _, v in dense_payload],
            ]
            gathered = multihost_utils.process_allgather(packet)
            g_sparse, g_dense = gathered
        else:
            g_sparse = [(idx[None], vals[None]) for _, idx, vals in sparse_payload]
            g_dense = [v[None] for _, v in dense_payload]

        for (p, _, _), (all_idx, all_vals) in zip(sparse_payload, g_sparse):
            dense = jnp.zeros((p._value.size,), jnp.float32)
            dense = dense.at[jnp.asarray(all_idx).reshape(-1)].add(
                jnp.asarray(all_vals).reshape(-1)
            ) / max(world, 1)
            p._value = p._value - lr * dense.reshape(p._value.shape).astype(
                p._value.dtype
            )
        for (p, _), v_all in zip(dense_payload, g_dense):
            sync = jnp.mean(jnp.asarray(v_all), axis=0)
            p._value = p._value - lr * sync.astype(p._value.dtype)

    # --- checkpointing ----------------------------------------------------
    def state_dict(self):
        """u/v accumulators + step count, keyed by parameter position (id()
        keys don't survive a process restart)."""
        out = {"count": self._count}
        for i, p in enumerate(self._parameters):
            if id(p) in self._u:
                out[f"u_{i}"] = Tensor(self._u[id(p)], stop_gradient=True)
                out[f"v_{i}"] = Tensor(self._v[id(p)], stop_gradient=True)
        return out

    def set_state_dict(self, state):
        self._count = int(state.get("count", 0))
        for i, p in enumerate(self._parameters):
            if f"u_{i}" in state:
                u = state[f"u_{i}"]
                v = state[f"v_{i}"]
                self._u[id(p)] = u._value if isinstance(u, Tensor) else jnp.asarray(u)
                self._v[id(p)] = v._value if isinstance(v, Tensor) else jnp.asarray(v)

    def get_lr(self):
        return self._lr_value()
