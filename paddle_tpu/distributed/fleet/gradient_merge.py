"""GradientMerge — k-step gradient accumulation as a meta-optimizer.

Reference analogue: fleet/meta_optimizers/gradient_merge_optimizer.py:20
(wraps the inner optimizer in a GradientMergeOptimizer program rewrite that
accumulates @GRAD into @GradientMerge vars and applies the inner update
every k_steps, optionally averaging). Here the same contract is an eager
wrapper: `step()` folds the current `.grad`s into float32 accumulators and
only invokes the inner optimizer on every k-th call — between boundaries
parameters (and the LR schedule) do not move, so a k-step merged run is
numerically a k×-batch run (tested in tests/test_gradient_merge.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import no_grad
from ...core.tensor import Tensor

__all__ = ["GradientMergeOptimizer"]


class GradientMergeOptimizer:
    """Wrap any optimizer; apply the merged gradient every `k_steps`.

    avg=True divides the accumulated gradient by k (the reference default),
    making the boundary update identical to one step on the concatenated
    batch for any mean-reduced loss.
    """

    def __init__(self, optimizer, k_steps: int = 1, avg: bool = True):
        if int(k_steps) < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self._inner = optimizer
        self._k = int(k_steps)
        self._avg = bool(avg)
        self._acc = {}          # id(param) -> (param, fp32 accumulator)
        self._micro_count = 0

    @property
    def inner_opt(self):
        return self._inner

    @no_grad()
    def step(self):
        params = [
            p for p in self._inner._param_list()
            if not p.stop_gradient and p.grad is not None
        ]
        self._micro_count += 1
        boundary = self._micro_count % self._k == 0
        for p in params:
            g = p.grad._value if isinstance(p.grad, Tensor) else p.grad
            cur = self._acc.get(id(p))
            acc = g.astype(jnp.float32) if cur is None \
                else cur[1] + g.astype(jnp.float32)
            self._acc[id(p)] = (p, acc)
        if not boundary:
            return
        scale = 1.0 / self._k if self._avg else 1.0
        for p, acc in self._acc.values():
            # a param may have no grad on the boundary micro-step (cleared,
            # or untouched by this micro-batch) — fall back to param dtype
            if isinstance(p.grad, Tensor):
                gd = p.grad._value.dtype
            elif p.grad is not None:
                gd = jnp.asarray(p.grad).dtype
            else:
                gd = p._value.dtype
            p.grad = Tensor((acc * scale).astype(gd), stop_gradient=True)
        self._inner.step()
        self._acc.clear()

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero=set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def state_dict(self):
        out = dict(self._inner.state_dict())
        out["_gm_micro_count"] = self._micro_count
        return out

    def set_state_dict(self, state):
        state = dict(state)
        state.pop("_gm_micro_count", None)
        # accumulators are NOT checkpointed — a restore starts a fresh
        # accumulation window (restoring the count without the partial
        # gradient sum would apply a mis-scaled update at the next boundary)
        self._micro_count = 0
        self._acc.clear()
        self._inner.set_state_dict(state)

    def __getattr__(self, name):
        if name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)
