"""Hybrid-parallel model layers (TP / PP wrappers).

Reference analogue: fleet/meta_parallel/ —
  - mp_layers.py (VocabParallelEmbedding:30, ColumnParallelLinear:97,
    RowParallelLinear:170, ParallelCrossEntropy:249): per-rank weight shards
    with hand-inserted c_identity/c_split/c_concat/mp_allreduce ops;
  - pp_layers.py (LayerDesc:49, SharedLayerDesc:63, PipelineLayer:132) +
    pipeline_parallel.py 1F1B schedule;
  - parallel_layers/random.py RNG tracker for TP-safe dropout.

TPU-native: parameters stay LOGICALLY GLOBAL and carry a `dist_spec`
PartitionSpec (mp dim). The compiled step's GSPMD partitioner materializes
the identical math the reference hand-writes: ColumnParallel forward emits
no collective (output sharded on mp), RowParallel forward ends in the
all-reduce, VocabParallelEmbedding masks+reduces — but derived from specs,
not 143 hand ops. Single-chip eager runs the same code unsharded.
"""
from __future__ import annotations

from typing import Callable, List, Optional

from ... import nn
from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer_base import Layer
from ...parallel.sharding import with_sharding_constraint

__all__ = [
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "ParallelCrossEntropy",
    "LayerDesc",
    "SharedLayerDesc",
    "PipelineLayer",
    "PipelineParallel",
    "TensorParallel",
    "ShardingParallel",
    "get_rng_state_tracker",
    "RNGStatesTracker",
]


class VocabParallelEmbedding(Layer):
    """reference: mp_layers.py:30 — vocab dim sharded over mp."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim],
            attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        self.weight.dist_spec = ("mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return with_sharding_constraint(out, None, None, None)


class ColumnParallelLinear(Layer):
    """reference: mp_layers.py:97 — weight [in, out] with out dim sharded."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features],
            attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        self.weight.dist_spec = (None, "mp")
        self.bias = (
            self.create_parameter(shape=[out_features], is_bias=True)
            if has_bias
            else None
        )
        if self.bias is not None:
            self.bias.dist_spec = ("mp",)

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            # replicate → GSPMD inserts the all-gather (c_concat analogue)
            return with_sharding_constraint(out, *([None] * out.ndim))
        return with_sharding_constraint(out, *([None] * (out.ndim - 1)), "mp")


class RowParallelLinear(Layer):
    """reference: mp_layers.py:170 — weight [in, out] with in dim sharded;
    forward ends in the mp all-reduce (GSPMD emits it when the output is
    constrained to replicated)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features],
            attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        self.weight.dist_spec = ("mp", None)
        self.bias = (
            self.create_parameter(shape=[out_features], is_bias=True)
            if has_bias
            else None
        )

    def forward(self, x):
        if self.input_is_parallel:
            x = with_sharding_constraint(x, *([None] * (x.ndim - 1)), "mp")
        out = F.linear(x, self.weight, None)
        out = with_sharding_constraint(out, *([None] * out.ndim))
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """reference: mp_layers.py:249 → c_softmax_with_cross_entropy: CE over
    vocab-sharded logits without materializing the gathered softmax. The
    spec constraint keeps logits mp-sharded; GSPMD's partitioned
    softmax+gather does the two-pass max/sum reduction internally."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input = with_sharding_constraint(
            input, *([None] * (input.ndim - 1)), "mp"
        )
        return F.cross_entropy(
            input, label, reduction="none", ignore_index=self.ignore_index
        )


# ---------------------------------------------------------------------------
# RNG tracker (reference: parallel_layers/random.py) — TP-safe dropout
# ---------------------------------------------------------------------------
class RNGStatesTracker:
    def __init__(self):
        from ...core.random import Generator

        self._states = {}
        self._gen = Generator(0)

    def add(self, name, seed):
        from ...core.random import Generator

        self._states[name] = Generator(seed)

    def rng_state(self, name="model_parallel_rng"):
        import contextlib

        from ...core import random as _random

        gen = self._states.get(name)
        if gen is None:
            return contextlib.nullcontext()
        return _random.rng_scope(gen.get_key())


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    import os

    seed = seed or 42
    _tracker.add("model_parallel_rng", seed + 1)
    _tracker.add("global_seed", seed)


# ---------------------------------------------------------------------------
# Pipeline structure (reference: pp_layers.py)
# ---------------------------------------------------------------------------
class LayerDesc:
    """reference: pp_layers.py:49 — lazy layer constructor for segmentation."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """reference: pp_layers.py:63 — weight shared across stages (embedding/
    head tying); on TPU the shared weight is simply the same logical param."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """reference: pp_layers.py:132 — sequential model described by
    LayerDescs, segmented into pp stages.

    TPU-native: all stages live in one SPMD program; the stage boundary is a
    scheduling concern (parallel/pipeline.py) rather than a process
    boundary, so the layer builds the FULL model and records segment
    boundaries. seg_method 'uniform' / 'layer:<Class>' supported."""

    def __init__(self, layers: List, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 num_virtual_pipeline_stages=None, **kwargs):
        super().__init__()
        self._descs = list(layers)
        self._loss_fn = loss_fn
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1
        )
        self._recompute_interval = recompute_interval
        self._shared = {}
        built = []
        for i, d in enumerate(self._descs):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(("shared", d))
                    continue
                layer = d.build_layer()
                self._shared[d.layer_name] = layer
                self.add_sublayer(str(i), layer)
                built.append(("own", layer, d))
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
                self.add_sublayer(str(i), layer)
                built.append(("own", layer, None))
            elif isinstance(d, Layer):
                self.add_sublayer(str(i), d)
                built.append(("own", d, None))
            elif callable(d):
                built.append(("fn", d))
            else:
                raise TypeError(f"bad pipeline item {d!r}")
        self._built = built
        self._segment()

    def _segment(self):
        n = len(self._built)
        per = (n + self._num_stages - 1) // self._num_stages
        self.segment_parts = [
            (i * per, min((i + 1) * per, n)) for i in range(self._num_stages)
        ]

    def get_stage_from_index(self, idx):
        for stage, (lo, hi) in enumerate(self.segment_parts):
            if lo <= idx < hi:
                return stage
        return self._num_stages - 1

    # pipeline-partition protocol (parallel/pipeline.py): the longest run of
    # same-class layers is the homogeneous middle; everything before it is
    # the (replicated) pre stage, everything after the post stage
    def _homogeneous_middle(self):
        def sig(item):
            if item[0] != "own":
                return None
            layer = item[1]
            return (
                type(layer),
                tuple(
                    (k, tuple(p.shape))
                    for k, p in sorted(layer.named_parameters(), key=lambda kv: kv[0])
                ),
            )

        items = self._built
        best = (0, 0)  # (start, stop)
        i = 0
        while i < len(items):
            s = sig(items[i])
            if s is None:
                i += 1
                continue
            j = i
            while j < len(items) and sig(items[j]) == s:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        return best

    def _run_items(self, items, x):
        for item in items:
            kind = item[0]
            if kind == "own":
                _, layer, desc = item
                if isinstance(desc, SharedLayerDesc) and desc.forward_func is not None:
                    x = desc.forward_func(layer, x)
                else:
                    x = layer(x)
            elif kind == "shared":
                _, desc = item
                layer = self._shared[desc.layer_name]
                if desc.forward_func is not None:
                    x = desc.forward_func(layer, x)
                else:
                    x = layer(x)
            else:
                x = item[1](x)
        return x

    def pp_embed(self, x):
        lo, _ = self._homogeneous_middle()
        return self._run_items(self._built[:lo], x)

    @property
    def pp_blocks(self):
        lo, hi = self._homogeneous_middle()
        return [it[1] for it in self._built[lo:hi]]

    def pp_head(self, h):
        _, hi = self._homogeneous_middle()
        return self._run_items(self._built[hi:], h)

    def forward(self, x):
        return self._run_items(self._built, x)


class PipelineParallel(Layer):
    """reference: pipeline_parallel.py:30 — train_batch with the 1F1B
    schedule over p2p sends.

    TPU-native: with pp_degree > 1 on the mesh, train_batch runs the
    compiled GPipe-over-ppermute schedule (parallel/pipeline.py) — stage
    weights stacked and pp-sharded, activations rotated by collective
    permute, backward pipelined by XLA's reverse scan. With pp == 1 it
    falls back to microbatched gradient accumulation (no host syncs until
    the final loss read)."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy
        self._hcg = hcg
        self.accumulate_steps = (
            strategy.pipeline_configs.get("accumulate_steps", 1) if strategy else 1
        )
        self._pipelined = None  # compiled schedule, built on first train_batch

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _pp_degree(self):
        from ...parallel.topology import axis_size

        return axis_size("pp")

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        import paddle_tpu as paddle

        x, y = data
        micro = max(1, self.accumulate_steps)
        if self._pp_degree() > 1:
            if self._pipelined is None or self._pipelined.optimizer is not optimizer:
                from ...parallel.pipeline import pipelined_train_step

                loss_fn = getattr(self._layers, "_loss_fn", None)
                stage = (
                    self._strategy.sharding_stage if self._strategy else 0
                )
                self._pipelined = pipelined_train_step(
                    self._layers, loss_fn, optimizer,
                    num_micro=micro, zero_stage=stage,
                )
            loss = self._pipelined(x, y)
            if scaler is not None:
                # grads live in fp32 inside the fused step, so dynamic loss
                # scaling is mathematically a no-op (bf16 AMP); advance the
                # scaler's bookkeeping so its state machine stays consistent
                # (reference: HybridParallelGradScaler wraps the same way)
                scaler.update()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return loss

        bsz = x.shape[0]
        mb = max(1, bsz // micro)
        total = None
        for i in range(micro):
            xi = x[i * mb : (i + 1) * mb]
            yi = y[i * mb : (i + 1) * mb]
            out = self._layers(xi)
            loss_fn = getattr(self._layers, "_loss_fn", None)
            loss = loss_fn(out, yi) if loss_fn is not None else out
            if loss.ndim > 0:
                loss = loss.mean()
            scaled = loss / micro
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            # accumulate on device; a single host read happens at the end
            total = loss.detach() if total is None else (total + loss.detach())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / micro


class TensorParallel(Layer):
    """reference: meta_parallel/tensor_parallel.py — wrapper marker."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)


class ShardingParallel(TensorParallel):
    pass
