"""fleet.utils — recompute re-export + filesystem clients.

Reference analogue: fleet/utils/__init__.py (recompute), fleet/utils/fs.py
(LocalFS, HDFSClient).
"""
from __future__ import annotations

import os
import shutil

from ...incubate.recompute import recompute, recompute_sequential  # noqa: F401

__all__ = ["recompute", "recompute_sequential", "LocalFS", "HDFSClient"]


class ExecuteError(Exception):
    pass


class LocalFS:
    """reference: fleet/utils/fs.py LocalFS."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for f in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, f)):
                dirs.append(f)
            else:
                files.append(f)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise ExecuteError(fs_path)
        open(fs_path, "a").close()

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient(LocalFS):
    """HDFS client facade (reference: fs.py HDFSClient shells out to
    `hadoop fs`). This environment has no Hadoop; paths under hdfs:// raise,
    local paths behave like LocalFS so auto-checkpoint flows run."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000, sleep_inter=1000):
        self._hadoop_home = hadoop_home

    def _check(self, fs_path):
        if str(fs_path).startswith("hdfs://"):
            raise ExecuteError(
                "no hadoop runtime in this environment; HDFSClient operates "
                "on local paths only"
            )

    def is_exist(self, fs_path):
        self._check(fs_path)
        return super().is_exist(fs_path)


# reference path re-exports (fleet/utils/__init__.py exposes these)
from ...incubate.recompute import recompute  # noqa: E402,F401


class DistributedInfer:
    """Hybrid-parallel inference helper (reference:
    fleet/utils/hybrid_parallel_inference.py DistributedInfer): wraps a
    program/layer for sharded inference over the live mesh."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program

    def get_dist_infer_program(self):
        return self._main

    def update_params(self, *args, **kwargs):
        pass
