"""StrategyCompiler — meta-optimizer selection, chaining order, and the
no-silent-no-op guarantee for DistributedStrategy.

Reference analogue: fleet/base/strategy_compiler.py:114 — the reference
generates valid meta-optimizer chains (each meta-optimizer rewrites the
Program and wraps an inner optimizer) and picks the highest-priority valid
one. On TPU most "meta-optimizers" collapse into sharding specs consumed by
the compiled SPMD step; the ones that remain optimizer-level chain here in
a FIXED documented order (outermost first):

    GradientMerge  ->  LocalSGD | DGC  ->  Lars/Lamb-substituted base

 - GradientMerge is outermost so the comm-reducing wrappers (whose step
   counters must track ACTUAL parameter updates) only see boundary steps.
 - LocalSGD and DGC are mutually exclusive (both reduce DP communication).
 - strategy.lars / strategy.lamb SUBSTITUTE the base optimizer the way the
   reference's _can_apply-gated meta-optimizers do (lars_optimizer.py
   requires Momentum; lamb_optimizer.py requires Adam/AdamW).

Every DistributedStrategy field carries a consumption status below; a field
set away from its default that nothing consumes raises a warning at
distributed_optimizer time — a user must never get different training than
they asked for with no signal (the round-3 gradient_merge/fp16_allreduce
silent-no-op bug class).
"""
from __future__ import annotations

import warnings
from typing import List, Tuple

__all__ = ["StrategyCompiler", "FIELD_STATUS"]

# How each DistributedStrategy field is consumed.
#   optimizer : applied by StrategyCompiler.compile (this module)
#   train-step: consumed by fleet.distributed_train_step / the compiled step
#   mesh      : consumed by fleet.init (mesh axes / HybridCommunicateGroup)
#   ps        : consumed by the parameter-server runtime
#   absorbed  : the capability is subsumed by XLA/GSPMD (grad-fusion
#               bucketing, comm-overlap knobs); documented no-op by design
#   unimplemented: accepted but NOT wired — warn loudly when set
FIELD_STATUS = {
    "amp": "train-step",
    "amp_configs": "train-step",
    "recompute": "train-step",
    "recompute_configs": "train-step",
    "gradient_merge": "optimizer",
    "gradient_merge_configs": "optimizer",
    "lamb": "optimizer",
    "lamb_configs": "optimizer",
    "lars": "optimizer",
    "lars_configs": "optimizer",
    "dgc": "optimizer",
    "dgc_configs": "optimizer",
    "localsgd": "optimizer",
    "localsgd_configs": "optimizer",
    "fp16_allreduce": "unimplemented",
    "sharding": "train-step",
    "sharding_configs": "train-step",
    "pipeline": "train-step",
    "pipeline_configs": "train-step",
    "tensor_parallel": "mesh",
    "tensor_parallel_configs": "mesh",
    "hybrid_configs": "mesh",
    "heter_ccl_mode": "unimplemented",
    "auto": "train-step",   # auto_parallel planner (distributed/auto_parallel)
    "auto_configs": "train-step",  # planner tune/topk knobs
    "a_sync": "ps",
    "a_sync_configs": "ps",
    "nccl_comm_num": "absorbed",
    "find_unused_parameters": "absorbed",
    "fuse_grad_size_in_MB": "absorbed",
    "last_comm_group_size_MB": "absorbed",
    "fuse_all_reduce_ops": "absorbed",
}


class StrategyCompiler:
    """Chain optimizer-level meta-optimizers for a DistributedStrategy."""

    # application order: substitutions first, wrappers inside-out
    # (reference: strategy_compiler.py:114 picks by meta-optimizer priority)
    ORDER = ("lars", "lamb", "localsgd", "dgc", "gradient_merge")

    def validate(self, strategy) -> List[str]:
        """Warn for set-but-unwired fields. Unknown fields never get this
        far: DistributedStrategy.__setattr__ rejects them at assignment."""
        from .distributed_strategy import DistributedStrategy

        defaults = DistributedStrategy().__dict__
        issues = []
        for key, value in strategy.__dict__.items():
            if key.startswith("_") or key not in FIELD_STATUS:
                continue
            if FIELD_STATUS[key] == "unimplemented" and value != defaults.get(key):
                issues.append(
                    f"strategy.{key} is set but NOT implemented on the TPU "
                    "build — training proceeds WITHOUT it"
                )
        for msg in issues:
            warnings.warn(msg, stacklevel=3)
        return issues

    def compile(self, strategy, optimizer) -> Tuple[object, List[str]]:
        """Return (wrapped_optimizer, applied_meta_optimizer_names)."""
        self.validate(strategy)
        applied: List[str] = []
        if getattr(strategy, "localsgd", False) and getattr(strategy, "dgc", False):
            raise ValueError(
                "strategy.localsgd and strategy.dgc are mutually exclusive "
                "(both reduce DP communication; pick one)"
            )
        for name in self.ORDER:
            if not getattr(strategy, name, False):
                continue
            optimizer, ok = getattr(self, f"_apply_{name}")(strategy, optimizer)
            if ok:
                applied.append(name)
        return optimizer, applied

    # -- substitutions -------------------------------------------------------
    def _apply_lars(self, strategy, optimizer):
        from ...optimizer import Lars, Momentum

        if not isinstance(optimizer, Momentum):
            warnings.warn(
                "strategy.lars applies only to Momentum (reference "
                f"_can_apply rule); {type(optimizer).__name__} left as-is"
            )
            return optimizer, False
        cfg = getattr(strategy, "lars_configs", {}) or {}
        return Lars(
            learning_rate=optimizer._learning_rate,
            momentum=optimizer._momentum,
            lars_coeff=cfg.get("lars_coeff", 0.001),
            lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
            parameters=optimizer._parameters,
            grad_clip=optimizer._grad_clip,
            exclude_from_weight_decay=cfg.get("exclude_from_weight_decay", None),
            epsilon=cfg.get("epsilon", 0.0),
        ), True

    def _apply_lamb(self, strategy, optimizer):
        from ...optimizer import Adam, AdamW, Lamb

        if not isinstance(optimizer, (Adam, AdamW)):
            warnings.warn(
                "strategy.lamb applies only to Adam/AdamW (reference "
                f"_can_apply rule); {type(optimizer).__name__} left as-is"
            )
            return optimizer, False
        cfg = getattr(strategy, "lamb_configs", {}) or {}
        return Lamb(
            learning_rate=optimizer._learning_rate,
            lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
            beta1=optimizer._beta1,
            beta2=optimizer._beta2,
            epsilon=optimizer._epsilon,
            parameters=optimizer._parameters,
            grad_clip=optimizer._grad_clip,
        ), True

    # -- wrappers ------------------------------------------------------------
    def _apply_localsgd(self, strategy, optimizer):
        from .localsgd import LocalSGDOptimizer

        if getattr(optimizer, "_parameters", None) is None:
            raise ValueError("LocalSGD needs an optimizer with a parameter list")
        cfg = getattr(strategy, "localsgd_configs", {}) or {}
        return LocalSGDOptimizer(
            optimizer,
            k_steps=cfg.get("k_steps", 1),
            begin_step=cfg.get("begin_step", 0),
        ), True

    def _apply_dgc(self, strategy, optimizer):
        from ...optimizer import Momentum
        from .dgc import DGCMomentumOptimizer

        if not isinstance(optimizer, Momentum):
            warnings.warn(
                "strategy.dgc applies only to Momentum (reference _can_apply "
                f"rule); {type(optimizer).__name__} left unwrapped"
            )
            return optimizer, False
        if getattr(optimizer, "_nesterov", False):
            warnings.warn(
                "DGC has no Nesterov variant; momentum applies non-Nesterov"
            )
        if optimizer._parameters is None:
            raise ValueError("DGC needs an optimizer with a parameter list")
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        return DGCMomentumOptimizer(
            learning_rate=optimizer._learning_rate
            if hasattr(optimizer, "_learning_rate") else optimizer.get_lr(),
            momentum=optimizer._momentum,
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            rampup_step=cfg.get("rampup_step", 1),
            sparsity=cfg.get("sparsity", (0.999,)),
            parameters=optimizer._parameters,
            grad_clip=optimizer._grad_clip,
            weight_decay=getattr(optimizer, "_weight_decay", None),
        ), True

    def _apply_gradient_merge(self, strategy, optimizer):
        from .gradient_merge import GradientMergeOptimizer

        cfg = getattr(strategy, "gradient_merge_configs", {}) or {}
        k = int(cfg.get("k_steps", 1))
        if k <= 1:
            return optimizer, False
        return GradientMergeOptimizer(
            optimizer, k_steps=k, avg=bool(cfg.get("avg", True))
        ), True
