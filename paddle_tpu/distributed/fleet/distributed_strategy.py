"""DistributedStrategy — the declarative distributed config.

Reference analogue: fleet/base/distributed_strategy.py wrapping the ~207
field protobuf (paddle/fluid/framework/distributed_strategy.proto:276). The
TPU build keeps the exact user-facing knobs (amp/amp_configs, recompute,
sharding{_configs}, hybrid_configs, pipeline, tensor_parallel, lamb, ...)
as plain Python state; each knob maps to mesh axes / sharding specs / the
amp & recompute modules instead of meta-optimizer program rewrites.
"""
from __future__ import annotations

from typing import Any, Dict


class DistributedStrategy:
    def __init__(self):
        # collective/base
        self.amp = False
        self.amp_configs: Dict[str, Any] = {
            "init_loss_scaling": 32768.0,
            "use_pure_fp16": False,
            "use_pure_bf16": False,
            "custom_white_list": [],
            "custom_black_list": [],
        }
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lamb_configs = {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []}
        self.lars = False
        self.lars_configs = {}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1, "sparsity": [0.999]}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 0}
        self.fp16_allreduce = False
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {
            "sharding_degree": 1,
            "stage": 1,
            "offload": False,
        }
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {
            "accumulate_steps": 1,
            "micro_batch_size": 1,
        }
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {"tensor_parallel_degree": 1}
        self.hybrid_configs: Dict[str, Any] = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.heter_ccl_mode = False
        self.auto = False
        # auto=True planning knobs: tune=True measures the planner's topk
        # candidates on the devices and keeps the fastest (reference:
        # tuner/optimization_tuner.py's measure-then-pick loop); the
        # analytic estimates are calibrated against the first measurement
        self.auto_configs: Dict[str, Any] = {
            "tune": True, "topk": 3, "tune_iters": 2,
        }
        self.a_sync = False
        self.a_sync_configs: Dict[str, Any] = {"k_steps": -1}
        self.nccl_comm_num = 1
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.last_comm_group_size_MB = 1
        self.fuse_all_reduce_ops = True

    @property
    def sharding_stage(self) -> int:
        if not self.sharding and self.hybrid_configs.get("sharding_degree", 1) <= 1:
            return 0
        return int(self.sharding_configs.get("stage", 1))

    def __setattr__(self, key, value):
        # unknown fields fail fast: a typo (`strategy.gradient_merg = True`)
        # must not become a silent no-op (see strategy_compiler.FIELD_STATUS
        # for the consumption map every real field carries)
        if not key.startswith("_") and key not in self.__dict__:
            from .strategy_compiler import FIELD_STATUS

            if key not in FIELD_STATUS:
                raise AttributeError(
                    f"DistributedStrategy has no field {key!r} (unknown "
                    "fields would be silently ignored; check the spelling)"
                )
        # dict-valued configs merge instead of replace (reference setter
        # semantics: distributed_strategy.py assigns proto sub-messages)
        cur = self.__dict__.get(key)
        if isinstance(cur, dict) and isinstance(value, dict):
            merged = dict(cur)
            merged.update(value)
            object.__setattr__(self, key, merged)
        else:
            object.__setattr__(self, key, value)

    def __repr__(self):
        fields = {
            k: v for k, v in self.__dict__.items()
            if not k.startswith("_") and v not in (False, None)
        }
        return f"DistributedStrategy({fields})"
