"""LocalSGD — train locally, periodically average parameters.

Reference analogue: fleet/meta_optimizers/localsgd_optimizer.py (static
program rewriting inserting allreduce every k steps). TPU-native: in
single-controller SPMD, data parallelism already averages gradients every
step inside the compiled program, so LocalSGD's value is the MULTI-PROCESS
mode (one controller per host over DCN): each process steps its own
replica on its own shard and parameters are averaged across processes
every k_steps — k× fewer cross-host syncs than per-step DP.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["LocalSGDOptimizer"]


class LocalSGDOptimizer:
    """Wrap any optimizer; every k_steps, average params across processes."""

    def __init__(self, optimizer, k_steps: int = 1, begin_step: int = 0):
        if int(k_steps) < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self._inner = optimizer
        self._k = int(k_steps)
        self._begin = int(begin_step)
        self._count = 0

    def step(self):
        self._inner.step()
        self._count += 1
        if jax.process_count() <= 1:
            return
        # reference warmup: DENSE per-step sync until begin_step, so the
        # replicas never diverge before local stepping starts; afterwards
        # average only every k steps
        if self._count <= self._begin or self._count % self._k == 0:
            self.sync_params()

    def sync_params(self):
        """Average every trainable parameter across processes — ONE
        collective over the whole parameter pytree, not one per param."""
        from jax.experimental import multihost_utils

        from ...core.dispatch import no_grad

        with no_grad():
            params = list(self._inner._parameters)
            stacked = multihost_utils.process_allgather(
                [p._value for p in params]
            )
            for p, s in zip(params, stacked):
                p._value = jnp.mean(s, axis=0)

    def __getattr__(self, name):
        # recursion guard: _inner itself missing means __init__ never ran
        # (deepcopy/pickle protocols); everything else — including the
        # underscore internals hapi's LRSchedulerCallback reads — delegates
        if name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)
