"""paddle.distributed.fleet — the distributed-training facade.

Reference analogue: fleet/base/fleet_base.py (init:206,
distributed_optimizer:875, distributed_model:932, minimize:1438) +
StrategyCompiler chaining meta-optimizers. On TPU the meta-optimizer chain
(AMP → Recompute → Sharding/TP/PP → RawProgram, each rewriting the proto
Program) collapses into sharding-spec assignment + one compiled SPMD step:
`distributed_model` installs the mesh and parameter specs,
`distributed_optimizer` wraps the optimizer, and the actual collectives are
emitted by GSPMD when the step compiles (parallel/sharding.py).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ...nn.layer_base import Layer
from ...parallel.topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hcg as _topo_get_hcg,
    init_mesh,
)
from .distributed_strategy import DistributedStrategy
from . import meta_parallel  # noqa: F401
from .meta_parallel import (  # noqa: F401
    ColumnParallelLinear,
    PipelineLayer,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker  # noqa: F401
from . import dataset  # noqa: F401
from .dataset import DataGenerator, InMemoryDataset, QueueDataset  # noqa: F401
from . import elastic  # noqa: F401
from . import obs  # noqa: F401
from .obs import FleetAggregator, ObsPublisher  # noqa: F401
from .localsgd import LocalSGDOptimizer  # noqa: F401
from .dgc import DGCMomentumOptimizer  # noqa: F401

__all__ = [
    "init",
    "DistributedStrategy",
    "HybridCommunicateGroup",
    "get_hybrid_communicate_group",
    "distributed_model",
    "distributed_optimizer",
    "distributed_train_step",
    "get_rank",
    "worker_index",
    "worker_num",
    "is_first_worker",
    "barrier_worker",
    "PaddleCloudRoleMaker",
    "UserDefinedRoleMaker",
    "DataGenerator",
    "InMemoryDataset",
    "QueueDataset",
    "LocalSGDOptimizer",
    "DGCMomentumOptimizer",
    "is_server",
    "init_server",
    "run_server",
    "init_worker",
    "stop_worker",
]

_state = {"strategy": None, "hcg": None, "initialized": False, "ps": None}


def _ps_runtime():
    """Lazy TheOnePSRuntime singleton (reference: fleet._runtime_handle)."""
    if _state["ps"] is None:
        from ..ps import TheOnePSRuntime

        _state["ps"] = TheOnePSRuntime()
    return _state["ps"]


def is_server() -> bool:
    import os

    return os.getenv("TRAINING_ROLE", "TRAINER") == "PSERVER"


def init_server(*args, **kwargs):
    """reference: fleet_base.py init_server → TheOnePSRuntime._init_server."""
    _ps_runtime()._init_server(*args, **kwargs)


def run_server():
    """Serve until a trainer stops the fleet (reference: run_server)."""
    _ps_runtime()._run_server()


def init_worker(*args, **kwargs):
    """reference: fleet_base.py init_worker → _init_worker (PS client)."""
    _ps_runtime()._init_worker(*args, **kwargs)


def stop_worker():
    """reference: fleet_base.py stop_worker — barrier, then trainer 0
    broadcasts STOP to the server fleet."""
    _ps_runtime()._stop_worker()


def init(role_maker=None, is_collective=False, strategy: Optional[DistributedStrategy] = None):
    """reference: fleet_base.py:206 fleet.init."""
    strategy = strategy or DistributedStrategy()
    _state["strategy"] = strategy
    if is_server():
        # a PSERVER process never touches the chip mesh — it only hosts
        # tables (reference: server role skips collective init)
        _state["initialized"] = True
        return None
    hybrid = strategy.hybrid_configs
    dp = hybrid.get("dp_degree", 1)
    mp = hybrid.get("mp_degree", 1)
    if strategy.tensor_parallel and mp == 1:
        # reference: tensor_parallel meta-config — an alternative spelling
        # of hybrid mp_degree for pure-TP scripts
        mp = int(strategy.tensor_parallel_configs.get("tensor_parallel_degree", 1))
    pp = hybrid.get("pp_degree", 1)
    sharding = hybrid.get("sharding_degree", 1)
    sep = hybrid.get("sep_degree", 1)
    n_dev = len(jax.devices())
    specified = dp * mp * pp * sharding * sep
    if specified == 1 and n_dev > 1:
        dp = n_dev  # pure data parallel over every visible chip
    elif hybrid.get("dp_degree", 1) == -1 or specified < n_dev and dp == 1:
        dp = max(1, n_dev // (mp * pp * sharding * sep))
    init_mesh(dp=dp, mp=mp, pp=pp, sharding=sharding, sep=sep)
    topo = CommunicateTopology(
        ["pipe", "data", "sharding", "sep", "model"], [pp, dp, sharding, sep, mp]
    )
    _state["hcg"] = HybridCommunicateGroup(topo)
    from ...parallel import topology as _t

    _t._set_hcg(_state["hcg"])
    _state["initialized"] = True
    from ..collective import _ensure_default

    _ensure_default()
    return None


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return _state["hcg"] or _topo_get_hcg()


def _strategy() -> DistributedStrategy:
    return _state["strategy"] or DistributedStrategy()


def distributed_model(model: Layer):
    """reference: fleet_base.py:932 — choose the parallel wrapper. On TPU:
    install parameter sharding specs and physically shard weights over the
    mesh; the returned model is the same Layer, ready for the compiled
    sharded step (or eager use on one chip)."""
    from ...parallel.sharding import shard_params

    strategy = _strategy()
    stage = strategy.sharding_stage
    shard_params(model, zero_stage=stage)
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """reference: fleet_base.py:875 — meta-optimizer selection via the
    StrategyCompiler (strategy_compiler.py): Lars/Lamb substitute the base,
    LocalSGD/DGC wrap it, GradientMerge wraps outermost. TP/ZeRO live in
    sharding specs; amp/recompute are consumed by distributed_train_step."""
    from .gradient_merge import GradientMergeOptimizer
    from .strategy_compiler import StrategyCompiler

    if isinstance(optimizer, (LocalSGDOptimizer, DGCMomentumOptimizer,
                              GradientMergeOptimizer)):
        # idempotent: already wrapped. Refuse a conflicting re-wrap rather
        # than storing a strategy the existing wrapper doesn't reflect.
        if strategy is not None and strategy is not _state["strategy"]:
            raise ValueError(
                "optimizer is already wrapped by "
                f"{type(optimizer).__name__}; call distributed_optimizer "
                "with a new strategy on the UNWRAPPED optimizer (the "
                "wrapper's config cannot be changed in place)"
            )
        return optimizer
    if strategy is not None:
        _state["strategy"] = strategy
    st = _strategy()
    optimizer, applied = StrategyCompiler().compile(st, optimizer)
    optimizer._fleet_strategy = st
    optimizer._fleet_applied_meta_optimizers = applied
    return optimizer


def distributed_train_step(model, loss_fn, optimizer, grad_input_idx=()):
    """Build the compiled hybrid-parallel train step for the current
    strategy/mesh — the single API that replaces the reference's
    fleet.distributed_model(...).train_batch / minimize pipeline.
    With pp_degree > 1 this is the pipelined (GPipe-over-ppermute) step.

    grad_input_idx: batch positions to ALSO differentiate — their grads
    return to the caller (the PS sparse path: pulled rows in, row grads
    out, pushed to the host table). Not supported with pipeline
    parallelism or strategy.auto."""
    from ...parallel.sharding import sharded_train_step
    from ...parallel.topology import axis_size

    strategy = _strategy()
    # a GradientMergeOptimizer unwraps into COMPILED accumulation: the step
    # lax.scans value_and_grad over k microbatch chunks (same numerics as
    # the eager wrapper, one-microbatch activation memory)
    accumulate_steps = 1
    from .gradient_merge import GradientMergeOptimizer

    if isinstance(optimizer, GradientMergeOptimizer):
        accumulate_steps = optimizer._k
        if not optimizer._avg:
            raise ValueError(
                "compiled gradient merge always averages (avg=False only "
                "exists on the eager wrapper path)"
            )
        optimizer = optimizer.inner_opt
    elif strategy.gradient_merge:
        cfg_gm = strategy.gradient_merge_configs or {}
        accumulate_steps = int(cfg_gm.get("k_steps", 1))
        if accumulate_steps > 1 and not cfg_gm.get("avg", True):
            raise ValueError(
                "compiled gradient merge always averages (avg=False only "
                "exists on the eager wrapper path)"
            )
    # the guard must see THROUGH the merge wrapper: GradientMerge(LocalSGD)
    # is a legal eager chain but no compiled step exists for it
    if isinstance(optimizer, (LocalSGDOptimizer, DGCMomentumOptimizer)):
        raise ValueError(
            "LocalSGD/DGC are EAGER multi-process meta-optimizers (their "
            "value is skipping/compressing cross-host sync, which a compiled "
            "dp-sharded step already schedules optimally); call "
            "loss.backward(); opt.step() directly instead of "
            "distributed_train_step"
        )
    forward_ctx = None
    if strategy.amp:
        from ... import amp as _amp

        cfg = strategy.amp_configs or {}
        level = "O2" if (cfg.get("use_pure_fp16") or cfg.get("use_pure_bf16")) \
            else "O1"
        dtype = "float16" if cfg.get("use_pure_fp16") else "bfloat16"

        def forward_ctx(_cfg=cfg, _level=level, _dtype=dtype):
            return _amp.auto_cast(
                enable=True,
                custom_white_list=_cfg.get("custom_white_list") or None,
                custom_black_list=_cfg.get("custom_black_list") or None,
                level=_level, dtype=_dtype,
            )
    if strategy.recompute:
        _apply_strategy_recompute(
            model, (strategy.recompute_configs or {}).get("checkpoints") or []
        )
    if strategy.auto:
        if grad_input_idx:
            raise ValueError(
                "grad_input_idx is not supported with strategy.auto (the "
                "planner may choose a pipeline config, which has no "
                "input-grad contract); build with sharded_train_step "
                "directly"
            )
        return _AutoPlannedStep(model, loss_fn, optimizer, strategy,
                                forward_ctx, accumulate_steps)
    pp = axis_size("pp")
    if pp > 1 and grad_input_idx:
        raise ValueError(
            "grad_input_idx is not supported with pp_degree > 1 (the "
            "pipelined step has no input-grad contract)"
        )
    if pp > 1:
        from ...parallel.pipeline import pipelined_train_step

        if accumulate_steps > 1:
            raise ValueError(
                "with pp_degree > 1, gradient accumulation IS the pipeline "
                "microbatching — set pipeline_configs['accumulate_steps'] "
                "instead of strategy.gradient_merge (the reference's "
                "GradientMergeOptimizer likewise excludes the pipeline path)"
            )
        _check_pp_loss_scale(strategy)
        target = model._layers if hasattr(model, "_layers") else model
        return pipelined_train_step(
            target, loss_fn, optimizer,
            num_micro=strategy.pipeline_configs.get("accumulate_steps", pp),
            zero_stage=strategy.sharding_stage,
            forward_ctx=forward_ctx,
        )
    return sharded_train_step(
        model, loss_fn, optimizer, zero_stage=strategy.sharding_stage,
        forward_ctx=forward_ctx, accumulate_steps=accumulate_steps,
        loss_scale=_static_loss_scale(strategy),
        grad_input_idx=grad_input_idx,
    )


class _AutoPlannedStep:
    """strategy.auto=True: defer mesh choice to the cost-model Planner.

    The first batch supplies (global_batch, seq_len); the Planner picks the
    dp/mp/pp/zero factorization (auto_parallel/planner.py — the reference's
    planner.py:826 search), the mesh is re-initialised to the plan, params
    are re-sharded, and the matching compiled step is built. The chosen
    spec is logged once."""

    def __init__(self, model, loss_fn, optimizer, strategy, forward_ctx,
                 accumulate_steps):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.strategy = strategy
        self.forward_ctx = forward_ctx
        self.accumulate_steps = accumulate_steps
        self.plan = None
        self._inner = None
        self.tuner_records = []
        self.calibration_scale = None
        self._tuned_step = None

    def _build(self, batch):
        from ..auto_parallel.planner import mesh_degrees_for, plan_for_model
        from ...core.tensor import Tensor as _T
        from ...parallel.sharding import sharded_train_step, shard_params

        x = batch[0]
        shape = (x._value if isinstance(x, _T) else x).shape
        gb = int(shape[0])
        seq = int(shape[1]) if len(shape) > 1 else 1
        # gradient accumulation composes with pp only as pipeline
        # microbatching (same rule as the explicit path below)
        allow_pp = None if self.accumulate_steps == 1 else False
        cfg = self.strategy.auto_configs or {}
        topk = int(cfg.get("topk", 3)) if cfg.get("tune", True) else 1
        plans = plan_for_model(self.model, seq_len=seq, global_batch=gb,
                               allow_pp=allow_pp, topk=topk)
        if topk == 1:
            plans = [plans]
        if len(plans) > 1:
            self.plan = self._measure_and_pick(plans, batch, cfg)
        else:
            self.plan = plans[0]
        c = self.plan.candidate
        init_mesh(**mesh_degrees_for(c))
        shard_params(self.model, zero_stage=c.zero_stage)
        # the tuner's winning trial already compiled this exact program —
        # reuse it (state was reset) instead of paying the compile twice
        self._inner = self._tuned_step or self._make_step(c)

    def _make_step(self, c):
        from ...parallel.sharding import sharded_train_step

        if c.pp > 1:
            from ...parallel.pipeline import pipelined_train_step

            _check_pp_loss_scale(self.strategy)
            target = self.model._layers if hasattr(self.model, "_layers") \
                else self.model
            return pipelined_train_step(
                target, self.loss_fn, self.optimizer,
                num_micro=c.micro_batches, zero_stage=c.zero_stage,
                forward_ctx=self.forward_ctx,
            )
        return sharded_train_step(
            self.model, self.loss_fn, self.optimizer,
            zero_stage=c.zero_stage, forward_ctx=self.forward_ctx,
            accumulate_steps=self.accumulate_steps,
            loss_scale=_static_loss_scale(self.strategy),
        )

    def _measure_and_pick(self, plans, batch, cfg):
        """Profile the planner's shortlist on the real devices and keep
        the measured winner (reference: tuner/optimization_tuner.py's
        measure-then-pick loop). Also runs the one-probe CALIBRATION: the
        analytic roofline is scaled by measured/estimated on the first
        candidate, so the logged estimates are meaningful on any backend
        (the raw roofline assumes the ClusterSpec's TPU numbers)."""
        import warnings

        from ..auto_parallel.planner import mesh_degrees_for
        from ..auto_parallel.tuner import ProfileTuner, TrialStateGuard
        from ...parallel.sharding import shard_params

        # trial steps donate param/opt buffers — snapshot to HOST memory
        # and restore between trials so every candidate starts identical
        guard = TrialStateGuard(self.model, self.optimizer)

        def model_fn(cand):
            guard.restore()
            init_mesh(**mesh_degrees_for(cand))
            shard_params(self.model, zero_stage=cand.zero_stage)
            return self._make_step(cand), batch

        from ..auto_parallel.tuner import calibration_scale

        tuner = ProfileTuner(model_fn, [p.candidate for p in plans],
                             iters=int(cfg.get("tune_iters", 2)))
        best_c = None
        try:
            best_c = tuner.tune(verbose=True)
        except RuntimeError as e:
            warnings.warn(
                f"auto-plan profile tuning failed ({e}); keeping the "
                "analytic plan"
            )
        finally:
            guard.restore()
        self.tuner_records = tuner.records
        self.calibration_scale, line = calibration_scale(
            tuner.records, plans)
        if line:
            print(line)
        # reuse the winner's already-compiled step: its optimizer state is
        # trial-mutated, so drop it — the next call re-inits from the
        # RESTORED accumulators without recompiling
        if tuner.best_step is not None and hasattr(tuner.best_step,
                                                   "_opt_state"):
            tuner.best_step._opt_state = None
            self._tuned_step = tuner.best_step
        for p in plans:
            if p.candidate is best_c:
                return p
        return plans[0]

    def __call__(self, *batch):
        if self._inner is None:
            self._build(batch)
        return self._inner(*batch)


def _static_loss_scale(strategy) -> float:
    """Pure-fp16 compiled training needs loss scaling (bf16 — the TPU
    default — does not): apply amp_configs.init_loss_scaling as a static
    scale inside the compiled step (grads are unscaled before clipping)."""
    cfg = strategy.amp_configs or {}
    if strategy.amp and cfg.get("use_pure_fp16"):
        return float(cfg.get("init_loss_scaling", 32768.0))
    return 1.0


def _check_pp_loss_scale(strategy):
    """The pipelined step has no loss-scaling hook; running pure fp16
    through it unscaled would silently underflow small gradients."""
    if _static_loss_scale(strategy) != 1.0:
        raise ValueError(
            "pure-fp16 loss scaling is not wired into the pipeline-parallel "
            "step; use bfloat16 (use_pure_bf16 — the TPU-native choice, no "
            "scaling needed) or pp_degree=1"
        )


def _apply_strategy_recompute(model, checkpoints):
    """Consume strategy.recompute: wrap each named sublayer's forward in
    jax.checkpoint (reference: RecomputeOptimizer rewrites the program to
    drop+recompute activations at the checkpoint vars; here the checkpoint
    granularity is the named sublayer). Idempotent per layer."""
    from ...incubate.recompute import recompute as _rc

    target = model._layers if hasattr(model, "_layers") else model
    layers = dict(target.named_sublayers()) if checkpoints else {}
    for name in checkpoints:
        layer = layers.get(name)
        if layer is None:
            raise ValueError(
                f"recompute checkpoint {name!r} is not a named sublayer of "
                f"the model (have: {sorted(layers)[:20]}...)"
            )
        if getattr(layer, "_fleet_recompute_wrapped", False):
            continue
        orig = layer.forward
        layer.forward = (lambda *a, _orig=orig, **k: _rc(_orig, *a, **k))
        layer._fleet_recompute_wrapped = True


# role/worker queries (reference: fleet_base.py worker_index etc.)
def get_rank():
    from ..parallel import get_rank as _r

    return _r()


def worker_index():
    return get_rank()


def worker_num():
    from ..parallel import get_world_size as _w

    return _w()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from ..collective import barrier

    barrier()


class UtilBase:
    """reference: fleet/base/util_factory.py UtilBase — cross-worker helper
    collectives + fs access for user scripts."""

    def __init__(self):
        from .utils import LocalFS

        self._fs = LocalFS()

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from ..collective import ReduceOp, all_reduce as _ar
        from ...core.tensor import to_tensor

        t = input if hasattr(input, "_value") else to_tensor(np.asarray(input))
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        out = _ar(t, op=op)
        return out.numpy() if not hasattr(input, "_value") else out

    def barrier(self, comm_world="worker"):
        from ..collective import barrier as _b

        _b()

    def all_gather(self, input, comm_world="worker"):
        import numpy as np

        from ...parallel.topology import get_mesh

        mesh = get_mesh()
        if mesh is None or mesh.devices.size == 1:
            return [input]
        from ..collective import all_gather as _ag
        from ...core.tensor import to_tensor

        out = []
        _ag(out, to_tensor(np.asarray(input)))
        return [o.numpy() for o in out]

    def get_file_shard(self, files):
        """Split a file list across workers (reference: UtilBase
        get_file_shard)."""
        import jax

        n = jax.process_count()
        rank = jax.process_index()
        per = len(files) // n
        rem = len(files) % n
        start = rank * per + min(rank, rem)
        end = start + per + (1 if rank < rem else 0)
        return list(files)[start:end]

    def print_on_rank(self, message, rank_id=0):
        import jax

        if jax.process_index() == rank_id:
            print(message)


_util = UtilBase()


class Fleet:
    """Class form of the fleet facade (reference: fleet_base.py:206 Fleet).
    The module-level functions (fleet.init etc.) are the canonical API;
    this class binds them for scripts instantiating Fleet()."""

    def __init__(self):
        self.util = _util

    def init(self, role_maker=None, is_collective=False, strategy=None):
        return init(role_maker=role_maker, is_collective=is_collective,
                    strategy=strategy)

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy=strategy)

    def is_first_worker(self):
        import jax

        return jax.process_index() == 0

    def worker_index(self):
        import jax

        return jax.process_index()

    def worker_num(self):
        import jax

        return jax.process_count()

    def is_worker(self):
        return not is_server()

    def is_server(self):
        return is_server()

    def init_server(self, *args, **kwargs):
        return init_server(*args, **kwargs)

    def run_server(self):
        return run_server()

    def init_worker(self, *args, **kwargs):
        return init_worker(*args, **kwargs)

    def barrier_worker(self):
        ps = _state["ps"]
        if ps is not None and ps.is_distributed:
            ps.barrier()
        else:
            self.util.barrier()

    def stop_worker(self):
        return stop_worker()


from .role_maker import Role  # noqa: E402,F401
from .dataset import (  # noqa: E402,F401
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
)
from . import utils  # noqa: E402,F401
from . import base  # noqa: E402,F401
