"""RoleMaker — parses the launch environment contract.

Reference analogue: fleet/base/role_maker.py (PaddleCloudRoleMaker parsing
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS /
TRAINING_ROLE ...).
"""
from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role_is_generated = False

    def worker_index(self):
        raise NotImplementedError

    def worker_num(self):
        raise NotImplementedError

    def is_worker(self):
        raise NotImplementedError

    def is_first_worker(self):
        return self.is_worker() and self.worker_index() == 0


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._generate_role()

    def _generate_role(self):
        self._trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._trainers_num = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = eps.split(",") if eps else []
        seps = os.getenv("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = seps.split(",") if seps else []
        self._role = (
            Role.SERVER
            if os.getenv("TRAINING_ROLE", "TRAINER") == "PSERVER"
            else Role.WORKER
        )
        self._role_is_generated = True

    def worker_index(self):
        return self._trainer_id

    def worker_num(self):
        return self._trainers_num

    def server_num(self):
        return len(self._server_endpoints)

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        self._kwargs = kwargs
        super().__init__(is_collective)

    def _generate_role(self):
        self._trainer_id = self._kwargs.get("current_id", 0)
        self._trainers_num = self._kwargs.get("worker_num", 1)
        self._worker_endpoints = self._kwargs.get("worker_endpoints", [])
        self._server_endpoints = self._kwargs.get("server_endpoints", [])
        role = self._kwargs.get("role", Role.WORKER)
        self._role = role
        self._role_is_generated = True
