"""Fleet dataset pipeline — InMemoryDataset / QueueDataset / DataGenerator.

Reference analogue: python/paddle/distributed/fleet/dataset/dataset.py
(InMemoryDataset:341 with load_into_memory/local_shuffle/global_shuffle,
QueueDataset:1240 streaming) backed by the C++ DataFeed/Dataset
(framework/data_feed.cc, data_set.cc), fed by the user data_generator
protocol (fleet/data_generator/data_generator.py) through pipe commands.

TPU-native design: the pipe-command subprocess protocol is replaced by an
in-process DataGenerator (same generate_sample contract) — the reference
pipes exist to feed C++ trainer threads, but here batches feed a
single-controller compiled step, so parsing runs in the dataloader's
process (set_pipe_command still accepted: it warns and is treated as
documentation). Batches come out as {slot: np.ndarray} dicts.
"""
from __future__ import annotations

import random
import warnings
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

__all__ = ["DataGenerator", "DatasetBase", "InMemoryDataset", "QueueDataset"]


class DataGenerator:
    """User parsing protocol (reference: data_generator.py DataGenerator).

    Subclass and implement generate_sample(line) returning an iterator (or
    generator) that yields one sample: a list of (slot_name, list-of-values)
    pairs. batch-level hooks follow the reference contract.
    """

    def generate_sample(self, line: str):
        raise NotImplementedError(
            "implement generate_sample(line) yielding [(slot, values), ...]"
        )

    def generate_batch(self, samples):
        """Optional batch-level rewrite (reference: generate_batch)."""
        return samples

    # reference API parity: run_from_stdin drives the pipe protocol; here
    # files are parsed in-process via Dataset classes
    def run_from_stdin(self):  # pragma: no cover - pipe-mode parity stub
        import sys

        for line in sys.stdin:
            for sample in self.generate_sample(line):
                print(sample)


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread = 1
        self._use_vars: List[str] = []
        self._filelist: List[str] = []
        self._generator: Optional[DataGenerator] = None
        self._drop_last = False

    # --- reference config surface ---------------------------------------
    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command=None,
             input_type=0, fs_name="", fs_ugi="", **kwargs):
        self.set_batch_size(batch_size)
        self.set_thread(thread_num)
        if use_var:
            self.set_use_var(use_var)
        if pipe_command:
            self.set_pipe_command(pipe_command)

    def set_batch_size(self, batch_size: int):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        self._thread = int(thread_num)

    def set_use_var(self, var_list):
        # accepts static Variables or plain slot names
        self._use_vars = [getattr(v, "name", v) for v in var_list]

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_pipe_command(self, pipe_command: str):
        warnings.warn(
            "pipe commands feed the reference's C++ DataFeed; on paddle_tpu "
            "register the parser in-process with set_generator(DataGenerator)"
        )
        self._pipe_command = pipe_command

    def set_generator(self, generator: DataGenerator):
        self._generator = generator

    # --- parsing ----------------------------------------------------------
    def _parse_file(self, path: str) -> Iterator[dict]:
        if self._generator is None:
            raise RuntimeError("call set_generator(DataGenerator) first")
        with open(path) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                for sample in self._generator.generate_sample(line):
                    # MultiSlot(String)DataGenerator shape their output via
                    # _format (reference data_generator protocol)
                    fmt = getattr(self._generator, "_format", None)
                    if fmt is not None:
                        sample = fmt(sample)
                    yield dict(sample)

    def _batched(self, samples: Iterator[dict]) -> Iterator[Dict[str, np.ndarray]]:
        slots = self._use_vars
        buf: List[dict] = []
        for s in samples:
            buf.append(s)
            if len(buf) == self._batch_size:
                yield self._to_batch(buf, slots)
                buf = []
        if buf and not self._drop_last:
            yield self._to_batch(buf, slots)

    @staticmethod
    def _to_batch(buf: List[dict], slots: List[str]) -> Dict[str, np.ndarray]:
        keys = slots or list(buf[0].keys())
        out = {}
        for k in keys:
            vals = [s[k] for s in buf]
            lens = {len(v) for v in vals}
            if len(lens) == 1:
                out[k] = np.asarray(vals)
            else:
                # ragged sparse slot (variable ids per line — the normal CTR
                # input): right-pad with 0 to the batch max. The reference
                # carries LoD instead; XLA needs static shapes, so padding +
                # the explicit <slot>.lens vector is the TPU form.
                width = max(lens)
                arr = np.zeros((len(vals), width), np.asarray(vals[0]).dtype)
                for i, v in enumerate(vals):
                    arr[i, : len(v)] = v
                out[k] = arr
                out[k + ".lens"] = np.asarray([len(v) for v in vals])
        return out


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference: dataset.py:341).

    global_shuffle on one controller equals local_shuffle (the reference
    shuffles across PS instances; the single-controller TPU job holds the
    whole memory pool)."""

    def __init__(self):
        super().__init__()
        self._memory: List[dict] = []

    def load_into_memory(self):
        self._memory = []
        for path in self._filelist:
            self._memory.extend(self._parse_file(path))

    def get_memory_data_size(self) -> int:
        return len(self._memory)

    def local_shuffle(self, seed: Optional[int] = None):
        rng = random.Random(seed)
        rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=None, seed: Optional[int] = None):
        self.local_shuffle(seed)

    def release_memory(self):
        self._memory = []

    def __iter__(self):
        return self._batched(iter(self._memory))


class QueueDataset(DatasetBase):
    """Streaming dataset (reference: dataset.py:1240): files are parsed on
    the fly, nothing resides in memory beyond one batch."""

    def __iter__(self):
        def stream():
            for path in self._filelist:
                yield from self._parse_file(path)

        return self._batched(stream())


class MultiSlotDataGenerator(DataGenerator):
    """reference: fleet/data_generator/data_generator.py
    MultiSlotDataGenerator — emits (slot_name, int/float list) pairs."""

    def _format(self, sample):
        if isinstance(sample, dict):
            return list(sample.items())
        return list(sample)


class MultiSlotStringDataGenerator(DataGenerator):
    """reference: data_generator.py MultiSlotStringDataGenerator — string
    slot values."""

    def _format(self, sample):
        out = []
        for name, vals in (sample.items() if isinstance(sample, dict) else sample):
            out.append((name, [str(v) for v in vals]))
        return out
