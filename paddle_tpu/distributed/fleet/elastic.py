"""Elastic training manager — fault detection, relaunch, rescale.

Reference analogue: python/paddle/distributed/fleet/elastic/manager.py:130
(ElasticManager): pods register in etcd with TTL leases; watchers detect
dead/new pods, rebuild endpoint lists within [np_min, np_max], kill local
trainers and re-exec. Env contract kept: PADDLE_ELASTIC_JOB_ID,
PADDLE_ELASTIC_NP, PADDLE_ELASTIC_TIMEOUT,
PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL.

TPU-native design: membership lives in a TCP lease/KV service (`master=`
— the PS server's KV verbs over the ps_net.h framing, r5) with the same
register/TTL/watch semantics as the reference's etcd leases; a shared
registry DIRECTORY (one heartbeat file per node, mtime = TTL refresh)
remains as the no-network fallback single-host CI exercises. A JAX
collective job cannot re-admit a single process into a running
coordination service, so fault recovery is whole-pod: on any worker death
the manager stops the pod, rebuilds it (new endpoints if membership
changed), and redeploys — the reference does the same for collective mode.
"""
from __future__ import annotations

import json
import os
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = [
    "ElasticManager",
    "ElasticStatus",
    "LateJoiner",
    "RescaleCoordinator",
    "RescaleEvent",
    "RescaleFallback",
    "WorldView",
    "deterministic_tree_sum",
    "read_serve_scale",
    "serve_scale_key",
    "start_master",
    "state",
]


def start_master(port: int = 0):
    """Start the TCP lease/KV master (one per job — the etcd replacement).
    Returns the server; its endpoint is 127.0.0.1:server.port locally, or
    <host-ip>:port across hosts."""
    from ..ps import PsServer

    return PsServer(port=port, server_id=0, n_servers=1, n_trainers=0)


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    RESTARTING = "restarting"
    EXIT = "exit"


class ElasticManager:
    """Watches a Pod of trainer Containers; relaunches on faults.

    pod_builder: () -> Pod (fresh containers with current membership env);
    called again on every relaunch so a changed node set produces new
    endpoint lists.
    """

    def __init__(
        self,
        pod_builder: Callable,
        job_id: Optional[str] = None,
        np_min: int = 1,
        np_max: Optional[int] = None,
        max_restarts: int = 3,
        watch_interval: float = 0.5,
        registry_dir: Optional[str] = None,
        heartbeat_ttl: float = 10.0,
        fault_tolerance_level: Optional[int] = None,
        master: Optional[str] = None,
        on_rescale: Optional[Callable] = None,
    ):
        self.pod_builder = pod_builder
        self.job_id = job_id or os.getenv("PADDLE_ELASTIC_JOB_ID", "default")
        self.np_min = np_min
        self.np_max = np_max or int(os.getenv("PADDLE_ELASTIC_NP", str(np_min)))
        self.max_restarts = max_restarts
        self.watch_interval = watch_interval
        self.heartbeat_ttl = heartbeat_ttl
        self.level = (
            fault_tolerance_level
            if fault_tolerance_level is not None
            else int(os.getenv("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1"))
        )
        self.registry_dir = registry_dir
        # networked membership: "host:port" of the TCP lease/KV master
        # (start_master) — true cross-host registry, no shared FS needed
        self.master = master or os.getenv("PADDLE_ELASTIC_MASTER") or None
        self._kv = None
        self.restarts = 0
        self.inplace_rescales = 0
        self.pod = None
        # on_rescale(members) -> bool: called INSTEAD of the whole-pod
        # rebuild when membership changes within [np_min, np_max]; return
        # True when the running pod rebound in place (endpoint lists
        # rebuilt, collectives re-formed). False / an exception falls back
        # to the whole-pod restart — the reference semantics stay the
        # safety net for unbarrierable states.
        self.on_rescale = on_rescale
        self._node_id = os.getenv("PADDLE_CURRENT_ENDPOINT", f"node-{os.getpid()}")

    def _kv_client(self):
        if self._kv is None:
            from ..ps import PsClient

            self._kv = PsClient([self.master])
        return self._kv

    def _lease_key(self):
        return f"elastic/{self.job_id}/{self._node_id}"

    # --- membership registry (etcd replacement) -------------------------
    def _beat_path(self):
        return os.path.join(self.registry_dir, f"{self.job_id}.{self._node_id}.beat")

    def _master_error(self, what: str):
        """Transient master hiccups are survivable; PERSISTENT failure
        (wrong address) must be visible — warn after 3 consecutive
        failures and at most once a minute after that."""
        self._kv_fails = getattr(self, "_kv_fails", 0) + 1
        now = time.time()
        last = getattr(self, "_kv_warned_at", 0.0)
        if self._kv_fails >= 3 and now - last > 60.0:
            import warnings

            warnings.warn(
                f"elastic: {self._kv_fails} consecutive {what} failures "
                f"against KV master {self.master} — membership/rescale is "
                "inert until it becomes reachable"
            )
            self._kv_warned_at = now

    def register(self):
        if self.master:
            try:
                self._kv_client().kv_lease(
                    self._lease_key(), str(os.getpid()), self.heartbeat_ttl
                )
                self._kv_fails = 0
            except ConnectionError:
                # transient master hiccup: the fault-tolerance manager
                # must not die of one — the next heartbeat retries over a
                # fresh connection (the client reconnects on demand)
                self._master_error("lease")
        elif self.registry_dir:
            os.makedirs(self.registry_dir, exist_ok=True)
            with open(self._beat_path(), "w") as f:
                f.write(str(os.getpid()))

    def heartbeat(self):
        if self.master:
            self.register()  # re-lease = TTL refresh
        elif self.registry_dir:
            try:
                os.utime(self._beat_path())
            except FileNotFoundError:
                self.register()

    def deregister(self):
        if self.master:
            try:
                self._kv_client().kv_del(self._lease_key())
            except ConnectionError:
                pass
        elif self.registry_dir:
            try:
                os.remove(self._beat_path())
            except FileNotFoundError:
                pass

    def alive_nodes(self):
        """Nodes whose lease/heartbeat is fresher than the TTL. Master
        mode returns None when the master is unreachable AND no poll ever
        succeeded — 'no signal yet' must be distinguishable from empty
        membership, or a slow-starting master reads as a rescale."""
        if self.master:
            prefix = f"elastic/{self.job_id}/"
            try:
                alive = self._kv_client().kv_alive(prefix)
            except ConnectionError:
                self._master_error("membership poll")
                # transient outage: last-known membership (None = never
                # successfully polled)
                return getattr(self, "_last_members", None)
            self._kv_fails = 0
            self._last_members = sorted(k[len(prefix):] for k in alive)
            return self._last_members
        if not self.registry_dir or not os.path.isdir(self.registry_dir):
            return []
        now = time.time()
        out = []
        prefix = f"{self.job_id}."
        for fn in os.listdir(self.registry_dir):
            if fn.startswith(prefix) and fn.endswith(".beat"):
                p = os.path.join(self.registry_dir, fn)
                try:
                    if now - os.path.getmtime(p) <= self.heartbeat_ttl:
                        out.append(fn[len(prefix) : -len(".beat")])
                except FileNotFoundError:
                    pass
        return sorted(out)

    # --- fault watch loop ----------------------------------------------
    def launch(self):
        self.register()
        self.pod = self.pod_builder()
        self.pod.deploy()
        return self.pod

    def watch(self, timeout: Optional[float] = None) -> int:
        """Run until the job completes (rc 0), fails permanently, or times
        out. Dead workers trigger a whole-pod relaunch up to max_restarts
        (fault-tolerance level >= 1; level 0 fails fast like the ref)."""
        if self.pod is None:
            self.launch()
        try:
            return self._watch_loop(timeout)
        except KeyboardInterrupt:
            self.pod.stop()
            self.deregister()
            return 1

    def _watch_loop(self, timeout: Optional[float]) -> int:
        t0 = time.time()
        membership = self.alive_nodes()
        while True:
            if timeout is not None and time.time() - t0 > timeout:
                self.pod.stop()
                return 124
            self.heartbeat()
            codes = [c.exit_code for c in self.pod.containers]
            if all(code == 0 for code in codes):
                self.deregister()
                return 0
            failed = [code for code in codes if code not in (None, 0)]
            now_members = self.alive_nodes()
            if now_members is None:
                # master unreachable and never successfully polled: no
                # membership signal — treat as unchanged, never a rescale
                now_members = membership
            elif membership is None:
                membership = now_members  # first successful poll baselines
            rescale = (self.registry_dir or self.master) \
                and now_members is not None \
                and now_members != membership and (
                    self.np_min <= max(len(now_members), 1) <= self.np_max
                )
            if rescale and not failed and self.on_rescale is not None:
                # in-place rescale: survivors barrier on the membership
                # epoch bump and rebind without killing the pod (the
                # RescaleCoordinator path); any failure falls through to
                # the whole-pod restart below on the next loop turn
                try:
                    if self.on_rescale(now_members):
                        membership = now_members
                        self.inplace_rescales += 1
                        time.sleep(self.watch_interval)
                        continue
                except Exception as e:
                    import warnings

                    warnings.warn(
                        f"elastic: in-place rescale failed ({e}); falling "
                        "back to whole-pod restart")
            if failed or rescale:
                if self.level == 0 and failed:
                    self.pod.stop()
                    return failed[0]
                if self.restarts >= self.max_restarts:
                    self.pod.stop()
                    return failed[0] if failed else 1
                self.restarts += 1
                membership = now_members
                self.pod.stop()
                try:
                    self.pod = self.pod_builder()
                    self.pod.deploy()
                except Exception as e:
                    # a failed rebuild (e.g. endpoint-discovery timeout
                    # while peers are still coming back) consumes this
                    # restart and retries on the next loop turn — it must
                    # not kill the fault-tolerance manager itself
                    import warnings

                    warnings.warn(f"elastic: pod rebuild failed ({e}); "
                                  f"retry {self.restarts}/{self.max_restarts}")
            time.sleep(self.watch_interval)


# ---------------------------------------------------------------------------
# Elastic rescale as a first-class training mode (RESILIENCE.md "Elastic
# rescale"): membership epochs + an in-place shrink/grow barrier protocol.
#
# The manager above recovers faults the reference way — kill the pod,
# rebuild, redeploy. The RescaleCoordinator below is the worker-side
# alternative for world-size changes within [np_min, np_max]: leases gain a
# monotonically increasing MEMBERSHIP EPOCH (one kv_put document per job,
# outside the lease namespace so it never reads as a member); on a lease
# expiry or a new node's register, survivors propose a bumped epoch, then
# barrier on it — every member of the proposed world writes an
# epoch-scoped barrier lease and waits until all are present — and install
# the new WorldView (members, rank, world) without a restart. Everything
# is deadline-bounded: a barrier that cannot complete (partitioned master,
# wedged peers, world outside the np bounds) raises RescaleFallback so the
# caller escalates to the whole-pod path; it can never hang.
# ---------------------------------------------------------------------------
def _epoch_key(job_id: str) -> str:
    # deliberately OUTSIDE the elastic/<job>/ lease prefix: kv_alive over
    # the member prefix must never list the epoch document as a node
    return f"elastic-epoch/{job_id}"


def serve_scale_key(job_id: str) -> str:
    """KV key of the serving-fleet scale proposal document — like the
    membership epoch, a kv_put document OUTSIDE every lease prefix (it is
    a request, not a member)."""
    return f"serve-scale/{job_id}"


def read_serve_scale(kv, job_id: str) -> Optional[Dict[str, Any]]:
    """The replica manager's half of the serving autoscale loop: read the
    current scale proposal (``{proposal, target, kind, reason, node,
    acked}``), or None when there is none / the document is torn. The
    manager acts on un-acked proposals (spawn or retire a replica) and
    acks via :meth:`RescaleCoordinator.ack_serve_scale` so a proposal is
    acted on exactly once."""
    raw = kv.kv_get(serve_scale_key(job_id))
    if not raw:
        return None
    try:
        doc = json.loads(raw)
        return {
            "proposal": int(doc["proposal"]),
            "target": int(doc["target"]),
            "kind": str(doc.get("kind", "")),
            "reason": str(doc.get("reason", "")),
            "node": doc.get("node"),
            "acked": bool(doc.get("acked", False)),
        }
    except (ValueError, KeyError, TypeError):
        return None  # torn/corrupt document: treated as absent


def _barrier_prefix(job_id: str, epoch: int) -> str:
    return f"elastic-barrier/{job_id}/{int(epoch)}/"


class RescaleFallback(RuntimeError):
    """In-place rescale is impossible (barrier timeout, master outage
    mid-rescale, world outside [np_min, np_max]): the caller must fall
    back to the whole-pod restart path."""


class LateJoiner(RuntimeError):
    """This node is not in the epoch's membership snapshot (it registered
    mid-barrier, or was evicted): it must not join this barrier — rejoin
    via join(), which proposes a follow-up epoch that includes it."""

    def __init__(self, epoch: int, members: Sequence[str], node_id: str):
        super().__init__(
            f"node {node_id!r} is not a member of epoch {epoch} "
            f"({list(members)}); rejoin for the next epoch")
        self.epoch = int(epoch)
        self.members = tuple(members)


class WorldView:
    """One membership epoch's world: sorted members, my rank, world size."""

    __slots__ = ("epoch", "members", "rank", "world")

    def __init__(self, epoch: int, members: Sequence[str], node_id: str):
        self.epoch = int(epoch)
        self.members = tuple(sorted(members))
        self.world = len(self.members)
        self.rank = (self.members.index(node_id)
                     if node_id in self.members else -1)

    def __repr__(self):
        return (f"WorldView(epoch={self.epoch}, world={self.world}, "
                f"rank={self.rank}, members={list(self.members)})")


class RescaleEvent:
    """One installed epoch bump. `kind` is 'form' (first view), 'shrink',
    'grow', or 'reshape' (same size, different members). `peer_steps` maps
    each member to the last training step it reported committed at barrier
    time — joiners use it to find the most-advanced peer to catch up
    from; survivors roll back to their own last committed boundary."""

    __slots__ = ("kind", "old", "new", "peer_steps")

    def __init__(self, old: Optional[WorldView], new: WorldView,
                 peer_steps: Dict[str, Optional[int]]):
        if old is None:
            self.kind = "form"
        elif new.world < old.world:
            self.kind = "shrink"
        elif new.world > old.world:
            self.kind = "grow"
        else:
            self.kind = "reshape"
        self.old = old
        self.new = new
        self.peer_steps = dict(peer_steps)

    def __repr__(self):
        return (f"RescaleEvent({self.kind}: "
                f"{self.old.world if self.old else 0}->{self.new.world} "
                f"@epoch {self.new.epoch})")


def deterministic_tree_sum(parts: List[Any]):
    """Pairwise (balanced-binary-tree) sum with a FIXED association shape.

    The accumulation-compensation contract needs gradient reduction whose
    floating-point association does not depend on the world size: rank r
    of world W owns a contiguous aligned block of the global microbatch
    list, tree-sums its block locally, and the cross-rank combine
    tree-sums the rank partials — producing bitwise the same result as one
    rank tree-summing all microbatches, PROVIDED the microbatch count and
    every world size are powers of two (aligned blocks are then exact
    subtrees of the global tree). GlobalStepSampler.set_world validates
    that invariant."""
    parts = list(parts)
    if not parts:
        raise ValueError("deterministic_tree_sum of no parts")
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            nxt.append(parts[i] + parts[i + 1])
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


_coordinators: "weakref.WeakSet" = weakref.WeakSet()


def state() -> List[Dict[str, Any]]:
    """Detached snapshots of every live RescaleCoordinator in this process
    (what /statusz's elastic section and the obs lease payload render)."""
    return [c.state() for c in
            sorted(_coordinators, key=lambda c: c.node_id)]


class RescaleCoordinator:
    """Worker-side membership-epoch protocol over the TCP lease/KV master
    (or any kv_* duck — MemoryKv in tests).

    Lifecycle::

        coord = RescaleCoordinator(manager)        # or kv=/master=+job_id
        view = coord.form(expected=np)             # initial barrier
        for step in ...:
            train_one_step()
            coord.note_commit(step)                # checkpoint boundary
            event = coord.poll()                   # heartbeat + detect
            if event is not None:
                rollback_to_last_committed_boundary()
                # sampler.set_world already applied if attached

    `poll()` returns a RescaleEvent when an epoch bump installed (in-place
    shrink/grow), None otherwise. RescaleFallback means the caller must
    escalate to whole-pod restart; LateJoiner means this node was left
    out of the new world (evicted, or raced a barrier) and should rejoin.
    """

    def __init__(self, manager: Optional[ElasticManager] = None, *,
                 kv=None, master: Optional[str] = None,
                 job_id: Optional[str] = None,
                 node_id: Optional[str] = None,
                 np_min: Optional[int] = None, np_max: Optional[int] = None,
                 heartbeat_ttl: Optional[float] = None,
                 barrier_timeout_s: Optional[float] = None,
                 debounce: Optional[int] = None,
                 poll_interval: float = 0.05):
        from ...core import flags as _flags

        if manager is not None:
            master = master or manager.master
            job_id = job_id or manager.job_id
            node_id = node_id or manager._node_id
            np_min = np_min if np_min is not None else manager.np_min
            np_max = np_max if np_max is not None else manager.np_max
            heartbeat_ttl = (heartbeat_ttl if heartbeat_ttl is not None
                             else manager.heartbeat_ttl)
        if kv is None and not master:
            raise ValueError("RescaleCoordinator needs manager=, kv= or "
                             "master=")
        self._kv = kv
        self._master = master
        self.job_id = job_id or "default"
        self.node_id = node_id or os.getenv(
            "PADDLE_CURRENT_ENDPOINT", f"node-{os.getpid()}")
        self.np_min = int(np_min if np_min is not None else 1)
        self.np_max = int(np_max) if np_max else 1 << 30
        self.heartbeat_ttl = float(heartbeat_ttl
                                   if heartbeat_ttl is not None else 10.0)
        self.barrier_timeout_s = float(
            barrier_timeout_s if barrier_timeout_s is not None
            else _flags.flag("elastic_barrier_timeout_s"))
        self.debounce = int(debounce if debounce is not None
                            else _flags.flag("elastic_rescale_debounce"))
        self.poll_interval = float(poll_interval)
        self.view: Optional[WorldView] = None
        self.last_event: Optional[RescaleEvent] = None
        self.rescales = 0
        self.fallbacks = 0
        self.evicted = False
        self._last_committed: Optional[int] = None
        self._pending_members: Optional[tuple] = None
        self._pending_count = 0
        self._sampler = None
        _coordinators.add(self)

    # -- plumbing --------------------------------------------------------
    def _client(self):
        if self._kv is None:
            from ..ps import PsClient

            self._kv = PsClient([self._master])
        return self._kv

    def _member_key(self) -> str:
        return f"elastic/{self.job_id}/{self.node_id}"

    def _member_prefix(self) -> str:
        return f"elastic/{self.job_id}/"

    def _alive(self) -> List[str]:
        prefix = self._member_prefix()
        alive = self._client().kv_alive(prefix)
        return sorted(k[len(prefix):] for k in alive)

    def _read_epoch(self) -> Optional[Dict[str, Any]]:
        raw = self._client().kv_get(_epoch_key(self.job_id))
        if not raw:
            return None
        try:
            doc = json.loads(raw)
            return {"epoch": int(doc["epoch"]),
                    "members": [str(m) for m in doc["members"]]}
        except (ValueError, KeyError, TypeError):
            return None  # torn/corrupt doc: treated as absent this poll

    def _propose(self, members: Sequence[str]) -> int:
        """Publish a bumped epoch with the observed member set. Racing
        proposers converge: both read the same stored epoch and write the
        same bump; a conflicting member list settles last-writer-wins and
        every barrier loop re-reads the stored document, so all nodes
        adopt the same final (epoch, members)."""
        stored = self._read_epoch()
        base = max(stored["epoch"] if stored else 0,
                   self.view.epoch if self.view else 0)
        epoch = base + 1
        doc = json.dumps({"epoch": epoch, "members": sorted(members)})
        self._client().kv_put(_epoch_key(self.job_id), doc)
        self._emit("propose", epoch=epoch, members=sorted(members))
        # racing same-epoch proposers settle last-writer-wins; adopt the
        # STORED document if ours lost so this node barriers on the same
        # (epoch, members) the winner published (the barrier loop re-reads
        # too — this just converges one turn earlier)
        echo = self._read_epoch()
        if echo and (echo["epoch"] != epoch
                     or sorted(echo["members"]) != sorted(members)):
            return echo["epoch"]
        return epoch

    def register(self):
        if self.evicted:
            return  # a deregistered lease must STAY gone (evict_self);
            # join() lifts the latch for a deliberate rejoin
        self._client().kv_lease(self._member_key(), str(os.getpid()),
                                self.heartbeat_ttl)

    def heartbeat(self):
        self.register()

    def note_commit(self, step: int):
        """Record the last durably committed training step — the value the
        barrier publishes so peers can agree on the resume boundary."""
        self._last_committed = int(step)

    def attach_sampler(self, sampler):
        """Auto-reshard: every installed epoch calls
        ``sampler.set_world(rank, world)`` (GlobalStepSampler /
        DistributedBatchSampler duck) so the data stream and accumulation
        factor follow the world with no caller wiring."""
        self._sampler = sampler
        if self.view is not None and hasattr(sampler, "set_world"):
            sampler.set_world(self.view.rank, self.view.world)
        return sampler

    # -- membership protocol ---------------------------------------------
    def form(self, expected: Optional[int] = None,
             timeout: Optional[float] = None) -> WorldView:
        """Initial formation: register, wait for `expected` members (or
        np_min), propose/adopt the first epoch and barrier on it."""
        return self._join(expected=expected, timeout=timeout)

    def join(self, timeout: Optional[float] = None) -> WorldView:
        """(Re)join a running job: register and propose an epoch whose
        membership includes this node — survivors observe the bump and
        barrier into the grown world (one epoch bump per join). Clears a
        prior evict_self latch: rejoining is the one deliberate way back
        in after an eviction."""
        self.evicted = False
        return self._join(expected=None, timeout=timeout)

    def _join(self, expected: Optional[int],
              timeout: Optional[float]) -> WorldView:
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else self.barrier_timeout_s)
        want = int(expected) if expected else None
        last_err: Optional[BaseException] = None
        while time.monotonic() < deadline:
            try:
                self.register()
                doc = self._read_epoch()
                if doc and self.node_id in doc["members"] and (
                        self.view is None or doc["epoch"] > self.view.epoch):
                    try:
                        return self._barrier_and_install(doc, deadline).new
                    except LateJoiner:
                        # superseded mid-barrier by a document that omits
                        # us (we lost a propose race): fall through and
                        # propose a follow-up epoch that includes us —
                        # join() owns the deadline budget for exactly this
                        pass
                alive = self._alive()
                # never PROPOSE a world outside [np_min, np_max]: an
                # over-max joiner keeps waiting (a seat may free up) and
                # times out alone rather than bumping the survivors into
                # an epoch they would have to fall back from
                if (self.node_id in alive
                        and (want is None or len(alive) >= want)
                        and self.np_min <= len(alive) <= self.np_max):
                    self._propose(alive)
            except ConnectionError as e:
                last_err = e  # master hiccup: retry within the deadline
            time.sleep(self.poll_interval)
        self.fallbacks += 1
        raise RescaleFallback(
            f"formation/join for job {self.job_id!r} timed out "
            f"(expected={expected}, last_err={last_err!r}) — escalate to "
            "whole-pod restart")

    def poll(self) -> Optional[RescaleEvent]:
        """Step-boundary tick: refresh the lease, detect epoch bumps or
        membership drift, run the barrier when a rescale is due. Master
        outages fail SOFT (None) — training continues, like
        ElasticManager.heartbeat; only an in-progress barrier that cannot
        complete raises RescaleFallback."""
        if self.evicted:
            return None  # no heartbeat, no barriers: survivors must see
            # the lease stay gone so the shrink actually lands
        try:
            self.heartbeat()
            doc = self._read_epoch()
        except ConnectionError:
            return None  # transient outage: next boundary retries
        if doc and self.view is not None and (
                doc["epoch"] > self.view.epoch
                or (doc["epoch"] == self.view.epoch
                    and tuple(sorted(doc["members"])) != self.view.members)):
            # epoch bump — or a same-epoch document that superseded the
            # member list this node installed (it lost a propose race
            # after its confirm read): the stored document is
            # authoritative, converge onto it
            if self.node_id not in doc["members"]:
                raise LateJoiner(doc["epoch"], doc["members"], self.node_id)
            deadline = time.monotonic() + self.barrier_timeout_s
            return self._barrier_and_install(doc, deadline)
        try:
            alive = self._alive()
        except ConnectionError:
            return None
        if self.view is None or not alive:
            return None
        observed = tuple(sorted(alive))
        if observed == self.view.members:
            self._pending_members, self._pending_count = None, 0
            return None
        # debounce: the SAME changed set must hold for consecutive polls
        if observed == self._pending_members:
            self._pending_count += 1
        else:
            self._pending_members, self._pending_count = observed, 1
        if self._pending_count < self.debounce:
            return None
        self._pending_members, self._pending_count = None, 0
        new_world = len(observed)
        if not (self.np_min <= new_world <= self.np_max):
            self.fallbacks += 1
            raise RescaleFallback(
                f"membership changed to world={new_world}, outside "
                f"[{self.np_min}, {self.np_max}] — escalate to whole-pod "
                "restart")
        epoch = self._propose(observed)
        doc = {"epoch": epoch, "members": sorted(observed)}
        deadline = time.monotonic() + self.barrier_timeout_s
        return self._barrier_and_install(doc, deadline)

    def _check_bounds(self, world: int, epoch: int):
        """An adopted epoch document outside [np_min, np_max] cannot be
        barriered into in place — the same escalation as the drift-detect
        path, enforced on EVERY install route (adopt, join, supersede)."""
        if not (self.np_min <= world <= self.np_max):
            self.fallbacks += 1
            raise RescaleFallback(
                f"epoch {epoch} proposes world={world}, outside "
                f"[{self.np_min}, {self.np_max}] — escalate to whole-pod "
                "restart")

    def _barrier_and_install(self, doc: Dict[str, Any],
                             deadline: float) -> RescaleEvent:
        """Barrier on `doc`'s epoch: every member writes an epoch-scoped
        barrier lease and waits for all. Re-reads the stored epoch each
        turn — a newer proposal supersedes this barrier mid-flight (the
        member set changed again), and the final stored document is what
        every node converges on. Deadline-bounded: raises RescaleFallback
        rather than hanging."""
        epoch, members = doc["epoch"], list(doc["members"])
        if self.node_id not in members:
            raise LateJoiner(epoch, members, self.node_id)
        self._check_bounds(len(members), epoch)
        payload = json.dumps({"step": self._last_committed})
        barrier_ttl = max(self.barrier_timeout_s, self.heartbeat_ttl * 2)
        while time.monotonic() < deadline:
            try:
                # keep the MEMBER lease fresh too: a barrier that waits
                # past heartbeat_ttl must not let every waiter's lease
                # expire, or the first post-install poll sees a mutilated
                # member set and tears the just-installed world again
                self.register()
                self._client().kv_lease(
                    _barrier_prefix(self.job_id, epoch) + self.node_id,
                    payload, barrier_ttl)
                latest = self._read_epoch()
                if latest and (latest["epoch"] > epoch or (
                        latest["epoch"] == epoch
                        and sorted(latest["members"]) != sorted(members))):
                    # a newer epoch OR a same-epoch member list that lost
                    # to ours in the propose race: the stored document is
                    # the one everyone must converge on
                    epoch, members = latest["epoch"], list(latest["members"])
                    if self.node_id not in members:
                        raise LateJoiner(epoch, members, self.node_id)
                    self._check_bounds(len(members), epoch)
                    payload = json.dumps({"step": self._last_committed})
                    continue
                prefix = _barrier_prefix(self.job_id, epoch)
                present = self._client().kv_alive(prefix)
                here = {k[len(prefix):]: v for k, v in present.items()}
                if all(m in here for m in members):
                    # confirm the document did not flip between the read
                    # above and the completeness scan; a change loops back
                    # to the adopt branch next turn
                    confirm = self._read_epoch()
                    if confirm and (confirm["epoch"] != epoch or sorted(
                            confirm["members"]) != sorted(members)):
                        continue
                    return self._install(epoch, members, here)
            except ConnectionError:
                pass  # master hiccup mid-barrier: retry within deadline
            time.sleep(self.poll_interval)
        self.fallbacks += 1
        self._emit("barrier_timeout", epoch=epoch, members=members)
        raise RescaleFallback(
            f"epoch {epoch} barrier timed out after "
            f"{self.barrier_timeout_s}s (members={members}) — escalate to "
            "whole-pod restart")

    def _install(self, epoch: int, members: List[str],
                 barrier_values: Dict[str, str]) -> RescaleEvent:
        peer_steps: Dict[str, Optional[int]] = {}
        for m in members:
            try:
                peer_steps[m] = json.loads(barrier_values[m]).get("step")
            except (KeyError, ValueError, TypeError):
                peer_steps[m] = None
        old = self.view
        new_view = WorldView(epoch, members, self.node_id)
        # reshard BEFORE committing the view: an attached sampler that
        # cannot deal this world (non-power-of-two, world > microbatches)
        # must surface as the documented whole-pod escalation with the
        # coordinator still coherent, not a raw ValueError with the view
        # already bumped and the sampler dealing for the old world
        if self._sampler is not None and hasattr(self._sampler, "set_world"):
            try:
                self._sampler.set_world(new_view.rank, new_view.world)
            except ValueError as e:
                self.fallbacks += 1
                self._emit("reshard_failed", epoch=epoch,
                           world=new_view.world, error=str(e))
                raise RescaleFallback(
                    f"world={new_view.world} cannot reshard the attached "
                    f"sampler ({e}) — escalate to whole-pod restart")
        self.view = new_view
        event = RescaleEvent(old, self.view, peer_steps)
        self.last_event = event
        if old is not None:
            self.rescales += 1
        self._emit("install", kind=event.kind, epoch=epoch,
                   world=self.view.world, rank=self.view.rank)
        self._count(f"elastic_rescale_{event.kind}s"
                    if old is not None else "elastic_formations")
        return event

    def evict_self(self, reason: str = "straggler"):
        """The shrink path, self-directed: deregister this node's lease so
        survivors observe the membership change and rescale in place (what
        FLAGS_elastic_straggler_evict does on a straggler trip)."""
        self.evicted = True
        self._emit("evict", reason=reason)
        self._count("elastic_self_evictions")
        try:
            self._client().kv_del(self._member_key())
        except ConnectionError:
            pass  # the lease will expire on its own — same outcome, later

    # -- serving-fleet autoscale (ISSUE 20) ------------------------------
    def propose_serve_scale(self, target: int, *, reason: str,
                            kind: Optional[str] = None,
                            signals: Optional[Dict[str, Any]] = None,
                            ) -> Optional[int]:
        """Publish a serving-fleet scale proposal (the FrontDoor
        autoscaler's grow/shrink path): one kv_put document under
        ``serve-scale/<job>`` with a monotonically increasing proposal id,
        which the replica manager polls (:func:`read_serve_scale`), acts
        on, and acks. Returns the proposal id, or None when the proposal
        was suppressed: target outside [np_min, np_max], or an identical
        un-acked proposal is already outstanding (exactly-once per scale
        decision — the chaos gate counts proposals)."""
        target = int(target)
        if not (self.np_min <= target <= self.np_max):
            self._emit("serve_scale_refused", target=target,
                       np_min=self.np_min, np_max=self.np_max)
            return None
        stored = read_serve_scale(self._client(), self.job_id)
        if (stored is not None and not stored["acked"]
                and stored["target"] == target):
            return None  # already proposed, not yet acted on
        proposal = (stored["proposal"] + 1) if stored else 1
        if kind is None:  # infer from the previous proposal when unlabeled
            kind = "grow"
            if stored is not None:
                kind = "grow" if target > stored["target"] else (
                    "shrink" if target < stored["target"] else "reaffirm")
        doc = {"proposal": proposal, "target": target, "kind": kind,
               "reason": str(reason), "node": self.node_id, "acked": False}
        if signals:
            doc["signals"] = signals
        self._client().kv_put(serve_scale_key(self.job_id),
                              json.dumps(doc, default=str))
        self._emit("serve_scale_propose", proposal=proposal, target=target,
                   kind=kind, reason=str(reason)[:120])
        return proposal

    def ack_serve_scale(self, proposal: int):
        """Mark a proposal acted on (the replica manager's commit): the
        document stays for observability but stops suppressing follow-up
        proposals."""
        stored = read_serve_scale(self._client(), self.job_id)
        if stored is None or stored["proposal"] != int(proposal):
            return
        stored["acked"] = True
        self._client().kv_put(serve_scale_key(self.job_id),
                              json.dumps(stored, default=str))
        self._emit("serve_scale_ack", proposal=int(proposal))

    # -- observability ---------------------------------------------------
    def accumulation_factor(self) -> Optional[int]:
        sampler = self._sampler
        if sampler is not None and hasattr(sampler, "accumulation_factor"):
            return int(sampler.accumulation_factor)
        return None

    def state(self) -> Dict[str, Any]:
        v = self.view
        return {
            "job": self.job_id,
            "node": self.node_id,
            "epoch": None if v is None else v.epoch,
            "world": None if v is None else v.world,
            "rank": None if v is None else v.rank,
            "members": [] if v is None else list(v.members),
            "rescales": self.rescales,
            "fallbacks": self.fallbacks,
            "evicted": self.evicted,
            "last_committed": self._last_committed,
            "accumulation_factor": self.accumulation_factor(),
            "last_event": (None if self.last_event is None
                           else repr(self.last_event)),
        }

    def _emit(self, phase: str, **attrs):
        try:
            from ...core import dispatch

            dispatch._emit("elastic", site=self.node_id, phase=phase,
                           **attrs)
        except Exception:
            pass  # observability must never take the rescale path down

    @staticmethod
    def _count(key: str, n: float = 1):
        try:
            from ...core import dispatch

            dispatch._counter_add(key, n)
        except Exception:
            pass
