"""Elastic training manager — fault detection, relaunch, rescale.

Reference analogue: python/paddle/distributed/fleet/elastic/manager.py:130
(ElasticManager): pods register in etcd with TTL leases; watchers detect
dead/new pods, rebuild endpoint lists within [np_min, np_max], kill local
trainers and re-exec. Env contract kept: PADDLE_ELASTIC_JOB_ID,
PADDLE_ELASTIC_NP, PADDLE_ELASTIC_TIMEOUT,
PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL.

TPU-native design: membership lives in a TCP lease/KV service (`master=`
— the PS server's KV verbs over the ps_net.h framing, r5) with the same
register/TTL/watch semantics as the reference's etcd leases; a shared
registry DIRECTORY (one heartbeat file per node, mtime = TTL refresh)
remains as the no-network fallback single-host CI exercises. A JAX
collective job cannot re-admit a single process into a running
coordination service, so fault recovery is whole-pod: on any worker death
the manager stops the pod, rebuilds it (new endpoints if membership
changed), and redeploys — the reference does the same for collective mode.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional

__all__ = ["ElasticManager", "ElasticStatus", "start_master"]


def start_master(port: int = 0):
    """Start the TCP lease/KV master (one per job — the etcd replacement).
    Returns the server; its endpoint is 127.0.0.1:server.port locally, or
    <host-ip>:port across hosts."""
    from ..ps import PsServer

    return PsServer(port=port, server_id=0, n_servers=1, n_trainers=0)


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    RESTARTING = "restarting"
    EXIT = "exit"


class ElasticManager:
    """Watches a Pod of trainer Containers; relaunches on faults.

    pod_builder: () -> Pod (fresh containers with current membership env);
    called again on every relaunch so a changed node set produces new
    endpoint lists.
    """

    def __init__(
        self,
        pod_builder: Callable,
        job_id: Optional[str] = None,
        np_min: int = 1,
        np_max: Optional[int] = None,
        max_restarts: int = 3,
        watch_interval: float = 0.5,
        registry_dir: Optional[str] = None,
        heartbeat_ttl: float = 10.0,
        fault_tolerance_level: Optional[int] = None,
        master: Optional[str] = None,
    ):
        self.pod_builder = pod_builder
        self.job_id = job_id or os.getenv("PADDLE_ELASTIC_JOB_ID", "default")
        self.np_min = np_min
        self.np_max = np_max or int(os.getenv("PADDLE_ELASTIC_NP", str(np_min)))
        self.max_restarts = max_restarts
        self.watch_interval = watch_interval
        self.heartbeat_ttl = heartbeat_ttl
        self.level = (
            fault_tolerance_level
            if fault_tolerance_level is not None
            else int(os.getenv("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1"))
        )
        self.registry_dir = registry_dir
        # networked membership: "host:port" of the TCP lease/KV master
        # (start_master) — true cross-host registry, no shared FS needed
        self.master = master or os.getenv("PADDLE_ELASTIC_MASTER") or None
        self._kv = None
        self.restarts = 0
        self.pod = None
        self._node_id = os.getenv("PADDLE_CURRENT_ENDPOINT", f"node-{os.getpid()}")

    def _kv_client(self):
        if self._kv is None:
            from ..ps import PsClient

            self._kv = PsClient([self.master])
        return self._kv

    def _lease_key(self):
        return f"elastic/{self.job_id}/{self._node_id}"

    # --- membership registry (etcd replacement) -------------------------
    def _beat_path(self):
        return os.path.join(self.registry_dir, f"{self.job_id}.{self._node_id}.beat")

    def _master_error(self, what: str):
        """Transient master hiccups are survivable; PERSISTENT failure
        (wrong address) must be visible — warn after 3 consecutive
        failures and at most once a minute after that."""
        self._kv_fails = getattr(self, "_kv_fails", 0) + 1
        now = time.time()
        last = getattr(self, "_kv_warned_at", 0.0)
        if self._kv_fails >= 3 and now - last > 60.0:
            import warnings

            warnings.warn(
                f"elastic: {self._kv_fails} consecutive {what} failures "
                f"against KV master {self.master} — membership/rescale is "
                "inert until it becomes reachable"
            )
            self._kv_warned_at = now

    def register(self):
        if self.master:
            try:
                self._kv_client().kv_lease(
                    self._lease_key(), str(os.getpid()), self.heartbeat_ttl
                )
                self._kv_fails = 0
            except ConnectionError:
                # transient master hiccup: the fault-tolerance manager
                # must not die of one — the next heartbeat retries over a
                # fresh connection (the client reconnects on demand)
                self._master_error("lease")
        elif self.registry_dir:
            os.makedirs(self.registry_dir, exist_ok=True)
            with open(self._beat_path(), "w") as f:
                f.write(str(os.getpid()))

    def heartbeat(self):
        if self.master:
            self.register()  # re-lease = TTL refresh
        elif self.registry_dir:
            try:
                os.utime(self._beat_path())
            except FileNotFoundError:
                self.register()

    def deregister(self):
        if self.master:
            try:
                self._kv_client().kv_del(self._lease_key())
            except ConnectionError:
                pass
        elif self.registry_dir:
            try:
                os.remove(self._beat_path())
            except FileNotFoundError:
                pass

    def alive_nodes(self):
        """Nodes whose lease/heartbeat is fresher than the TTL. Master
        mode returns None when the master is unreachable AND no poll ever
        succeeded — 'no signal yet' must be distinguishable from empty
        membership, or a slow-starting master reads as a rescale."""
        if self.master:
            prefix = f"elastic/{self.job_id}/"
            try:
                alive = self._kv_client().kv_alive(prefix)
            except ConnectionError:
                self._master_error("membership poll")
                # transient outage: last-known membership (None = never
                # successfully polled)
                return getattr(self, "_last_members", None)
            self._kv_fails = 0
            self._last_members = sorted(k[len(prefix):] for k in alive)
            return self._last_members
        if not self.registry_dir or not os.path.isdir(self.registry_dir):
            return []
        now = time.time()
        out = []
        prefix = f"{self.job_id}."
        for fn in os.listdir(self.registry_dir):
            if fn.startswith(prefix) and fn.endswith(".beat"):
                p = os.path.join(self.registry_dir, fn)
                try:
                    if now - os.path.getmtime(p) <= self.heartbeat_ttl:
                        out.append(fn[len(prefix) : -len(".beat")])
                except FileNotFoundError:
                    pass
        return sorted(out)

    # --- fault watch loop ----------------------------------------------
    def launch(self):
        self.register()
        self.pod = self.pod_builder()
        self.pod.deploy()
        return self.pod

    def watch(self, timeout: Optional[float] = None) -> int:
        """Run until the job completes (rc 0), fails permanently, or times
        out. Dead workers trigger a whole-pod relaunch up to max_restarts
        (fault-tolerance level >= 1; level 0 fails fast like the ref)."""
        if self.pod is None:
            self.launch()
        try:
            return self._watch_loop(timeout)
        except KeyboardInterrupt:
            self.pod.stop()
            self.deregister()
            return 1

    def _watch_loop(self, timeout: Optional[float]) -> int:
        t0 = time.time()
        membership = self.alive_nodes()
        while True:
            if timeout is not None and time.time() - t0 > timeout:
                self.pod.stop()
                return 124
            self.heartbeat()
            codes = [c.exit_code for c in self.pod.containers]
            if all(code == 0 for code in codes):
                self.deregister()
                return 0
            failed = [code for code in codes if code not in (None, 0)]
            now_members = self.alive_nodes()
            if now_members is None:
                # master unreachable and never successfully polled: no
                # membership signal — treat as unchanged, never a rescale
                now_members = membership
            elif membership is None:
                membership = now_members  # first successful poll baselines
            rescale = (self.registry_dir or self.master) \
                and now_members is not None \
                and now_members != membership and (
                    self.np_min <= max(len(now_members), 1) <= self.np_max
                )
            if failed or rescale:
                if self.level == 0 and failed:
                    self.pod.stop()
                    return failed[0]
                if self.restarts >= self.max_restarts:
                    self.pod.stop()
                    return failed[0] if failed else 1
                self.restarts += 1
                membership = now_members
                self.pod.stop()
                try:
                    self.pod = self.pod_builder()
                    self.pod.deploy()
                except Exception as e:
                    # a failed rebuild (e.g. endpoint-discovery timeout
                    # while peers are still coming back) consumes this
                    # restart and retries on the next loop turn — it must
                    # not kill the fault-tolerance manager itself
                    import warnings

                    warnings.warn(f"elastic: pod rebuild failed ({e}); "
                                  f"retry {self.restarts}/{self.max_restarts}")
            time.sleep(self.watch_interval)
