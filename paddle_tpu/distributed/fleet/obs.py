"""Fleet-wide observability: per-worker snapshots through the elastic KV
master, merged into one operator view.

The multi-host chaos fleet (``tools/chaos_fleet_probe.py``) was N blind
processes: each worker has a flight recorder, a metrics registry, and (now)
a diagnostics server — but nothing merges them. This module closes that
gap using the SAME elastic TCP lease/KV master the fleet already heartbeats
through (``distributed/fleet/elastic.py`` over the PS wire):

- :class:`ObsPublisher` — each worker publishes a compact JSON snapshot
  (health, key metrics, its diagnostics-server address, wall clock) as a
  TTL lease under ``obs/<job>/<node>`` on its heartbeat cadence. A dead or
  wedged worker's lease expires, so it drops out of the merged view with
  no coordinator — exactly the elastic-membership semantics.

- :class:`FleetAggregator` — merges the live snapshots into:
  * ``merged_prometheus_text()`` — ONE exposition where every family
    carries a ``host`` label per worker (scrape a whole fleet from any
    box that can reach the KV master);
  * ``fleet_health()`` — one table: node, health status/reasons, step,
    snapshot age, engines;
  * ``merged_chrome_trace()`` — pulls each live host's flight ring over
    its diagnostics server (``/flight``) and emits one chrome trace with
    a process lane per host, timestamps aligned by a per-host
    clock-offset handshake (``/clockz``, NTP-style: offset from the
    minimum-RTT sample) — a chaos SIGKILL/partition scenario becomes one
    readable timeline instead of N logs.

Publishers fail SOFT on master outages (the partition chaos scenario:
training must continue while the master is down; snapshots resume on
heal), mirroring ``ElasticManager.heartbeat``.

:class:`MemoryKv` is a process-local stand-in for the TCP master with the
same lease semantics — what the fast tests (and single-process demos) use;
the real wire path is exercised by the slow fleet probe.
"""
from __future__ import annotations

import json
import os
import socket
import time
import urllib.request
from typing import Any, Dict, List, Optional

__all__ = ["FleetAggregator", "MemoryKv", "ObsPublisher",
           "StragglerDetector", "obs_key", "obs_prefix"]


def obs_prefix(job_id: str = "default") -> str:
    return f"obs/{job_id}/"


def obs_key(job_id: str, node_id: str) -> str:
    """The fleet KV key schema: ``obs/<job>/<node>``."""
    return obs_prefix(job_id) + node_id


class MemoryKv:
    """In-memory lease/KV with the master's semantics (kv_lease refreshes
    a TTL; expired keys drop out of kv_alive) — test double for the TCP
    master, NOT a distributed store."""

    def __init__(self):
        self._data: Dict[str, tuple] = {}  # key -> (value, deadline|None)

    def kv_put(self, key: str, value: str):
        self._data[key] = (value, None)

    def kv_lease(self, key: str, value: str, ttl_s: float):
        self._data[key] = (value, time.time() + float(ttl_s))

    def kv_get(self, key: str) -> Optional[str]:
        row = self._data.get(key)
        if row is None:
            return None
        value, deadline = row
        if deadline is not None and time.time() > deadline:
            del self._data[key]
            return None
        return value

    def kv_del(self, key: str):
        self._data.pop(key, None)

    def kv_alive(self, prefix: str) -> Dict[str, str]:
        now = time.time()
        out = {}
        for k in list(self._data):
            if not k.startswith(prefix):
                continue
            value, deadline = self._data[k]
            if deadline is not None and now > deadline:
                del self._data[k]
                continue
            out[k] = value
        return out


def _kv_from_master(master: str):
    from ..ps import PsClient

    return PsClient([master])


class ObsPublisher:
    """Publishes this process's observability snapshot under
    ``obs/<job>/<node>`` with a TTL lease; call :meth:`publish` on the
    heartbeat cadence (next to ``ElasticManager.heartbeat``)."""

    def __init__(self, master: Optional[str] = None, kv=None,
                 job_id: str = "default", node_id: Optional[str] = None,
                 ttl: float = 10.0, diag_addr: Optional[str] = None):
        if kv is None and not master:
            raise ValueError("ObsPublisher needs master= or kv=")
        self._master = master
        self._kv = kv
        self.job_id = job_id
        self.node_id = node_id or os.getenv(
            "PADDLE_CURRENT_ENDPOINT", f"node-{os.getpid()}")
        self.ttl = float(ttl)
        self._diag_addr = diag_addr
        self.publishes = 0
        self.failures = 0
        # per-worker step-progress heartbeat (ISSUE 14 straggler defense):
        # note_step feeds these; the snapshot publishes them so the fleet
        # can compare workers' step cadence without any extra RPC
        self._elastic: Dict[str, Any] = {}
        self._last_step_wall: Optional[float] = None

    @classmethod
    def from_elastic(cls, manager, diag_addr: Optional[str] = None,
                     ttl: Optional[float] = None) -> "ObsPublisher":
        """Build from an :class:`ElasticManager` — same master, job id,
        node id, and TTL, so obs membership expires exactly when the
        elastic lease would."""
        return cls(master=manager.master, job_id=manager.job_id,
                   node_id=manager._node_id,
                   ttl=ttl if ttl is not None else manager.heartbeat_ttl,
                   diag_addr=diag_addr)

    def _client(self):
        if self._kv is None:
            self._kv = _kv_from_master(self._master)
        return self._kv

    def key(self) -> str:
        return obs_key(self.job_id, self.node_id)

    def note_step(self, step: int, step_ms: float, epoch: Optional[int] = None,
                  accum: Optional[int] = None):
        """Record one completed training step — the per-worker
        step-progress heartbeat the straggler detector and fleet_top read.
        `epoch` is the elastic membership epoch; `accum` the current
        accumulation factor. EMA-smoothed (0.5/step): the detector judges
        sustained cadence, not single-step noise."""
        prev = self._elastic.get("step_ms")
        self._elastic.update({
            "step": int(step),
            "step_ms": (float(step_ms) if prev is None
                        else prev + 0.5 * (float(step_ms) - prev)),
        })
        if epoch is not None:
            self._elastic["epoch"] = int(epoch)
        if accum is not None:
            self._elastic["accum"] = int(accum)
        self._last_step_wall = time.time()

    def snapshot(self) -> Dict[str, Any]:
        """The compact per-worker doc: identity + diag address + health +
        flat metrics (histograms reduced to count/sum — the aggregator's
        exposition carries them as counters)."""
        from ...profiler import diag as _diag
        from ...profiler import metrics as _metrics

        _, health = _diag.health_doc()
        try:
            snap = _metrics.snapshot(include_dispatch=True)
            hists = {
                name: {"count": (h or {}).get("count", 0),
                       "sum": (h or {}).get("sum", 0.0)}
                for name, h in snap.get("histograms", {}).items()
            }
            metrics_doc = {"counters": snap.get("counters", {}),
                           "gauges": snap.get("gauges", {}),
                           "histograms": hists}
        except Exception:
            metrics_doc = None
        elastic = dict(self._elastic)
        if self._last_step_wall is not None:
            # step lag: how stale this worker's last completed step is —
            # the fleet-visible "is it making progress" signal
            elastic["step_lag_ms"] = round(
                (time.time() - self._last_step_wall) * 1000.0, 1)
        if "epoch" not in elastic or "accum" not in elastic:
            # fall back to the live RescaleCoordinator for this node
            try:
                from .elastic import state as _estate

                for row in _estate():
                    if row["node"] == self.node_id:
                        elastic.setdefault("epoch", row["epoch"])
                        if row["accumulation_factor"] is not None:
                            elastic.setdefault(
                                "accum", row["accumulation_factor"])
                        break
            except Exception:
                pass
        # attribution layer (ISSUE 15): ship the top measured program
        # costs and the hottest telemetry group so fleet_top --programs
        # and the per-host grad-norm column need no extra RPC
        programs = None
        telemetry = None
        try:
            from ...profiler import attribution as _attribution

            programs = _attribution.costs_summary(5)
            telemetry = _attribution.telemetry_summary()
        except Exception:
            pass
        # whole-step capture tier (ISSUE 18): the per-host dispatch tier —
        # "captured-sharded@dp2mp2 donated", "captured", or None when the
        # capture tier is off/unarmed — so fleet_top shows at a glance which
        # hosts replay 1 program per step
        capture = None
        try:
            from ...core import lazy as _lazy

            cstate = _lazy.step_capture_state()
            tier = cstate.get("tier")
            if tier:
                capture = tier + (f"@{cstate['mesh']}" if cstate.get("mesh")
                                  else "")
                if cstate.get("donated"):
                    capture += " donated"
            elif cstate.get("enabled"):
                capture = "armed" if cstate.get("armed") else "warmup"
        except Exception:
            pass
        # fleet serving front door (ISSUE 20): per-engine routing signals
        # — queue depth, in-flight count, measured prefill/decode cost
        # EMAs, the admission state, and the replica's serve address — so
        # a cross-host FrontDoor dispatches on predicted cost (and honors
        # health) without any extra RPC to the replica
        serving = None
        try:
            rows = [eng.routing_signals() for eng in _diag.engines()]
            if rows:
                serving = rows
        except Exception:
            pass
        return {
            "node": self.node_id,
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "diag": self._diag_addr or _diag.address(),
            "wall": time.time(),
            "step": health.get("step"),
            "elastic": elastic,
            "programs": programs,
            "telemetry": telemetry,
            "capture": capture,
            "serving": serving,
            "health": {
                "status": health.get("status"),
                "reasons": health.get("reasons"),
                "heartbeat_age_ms": health.get("heartbeat_age_ms"),
                "sentinel_tripped": health.get("sentinel_tripped"),
                "engines": health.get("engines"),
            },
            "metrics": metrics_doc,
        }

    def publish(self, raise_errors: bool = False) -> bool:
        """One lease refresh with a fresh snapshot. Master outages fail
        SOFT by default (False returned, failure counted): the partition
        chaos scenario trains through the outage and snapshots resume on
        heal — observability must never take a worker down."""
        try:
            doc = json.dumps(self.snapshot(), default=str)
            self._client().kv_lease(self.key(), doc, self.ttl)
            self.publishes += 1
            return True
        except Exception:
            self.failures += 1
            if raise_errors:
                raise
            return False

    def withdraw(self):
        """Best-effort delete (clean shutdown; expiry handles crashes)."""
        try:
            self._client().kv_del(self.key())
        except Exception:
            pass


class StragglerDetector:
    """Fleet-level straggler defense (ISSUE 14 layer 4): each worker
    compares ITS OWN published step time against the fleet median from the
    live ``obs/<job>/*`` leases. A worker sustained past
    ``FLAGS_elastic_straggler_pct`` slower than the median for
    ``FLAGS_elastic_straggler_sustain`` consecutive checks trips once —
    a sentinel-style ``straggler`` flight event + counter, an external
    sentinel latch (``straggler[<node>]``) that degrades this worker's
    /healthz — and, with ``FLAGS_elastic_straggler_evict`` (or an
    ``on_evict`` callback), evicts the worker through the elastic shrink
    path: the coordinator deregisters its lease, survivors observe the
    membership change and rescale in place.

    Detection is decentralized — no coordinator process: every worker
    runs the same arithmetic over the same KV view and only ever judges
    itself, so a partitioned or dead master simply pauses detection
    (checks fail soft), exactly like the heartbeats."""

    def __init__(self, publisher: ObsPublisher, *, coordinator=None,
                 pct: Optional[float] = None, sustain: Optional[int] = None,
                 evict: Optional[bool] = None, on_evict=None,
                 min_interval_s: float = 0.0):
        from ...core import flags as _flags

        self.publisher = publisher
        self.coordinator = coordinator
        # per-check cost is a kv_alive prefix scan + one JSON decode per
        # worker — O(W^2) master load fleet-wide when called every step.
        # Large fleets should set min_interval_s near their publish
        # cadence so scans amortize; 0 keeps per-step detection (tests,
        # small worlds)
        self.min_interval_s = float(min_interval_s)
        self._last_scan_wall = 0.0
        self.pct = float(pct if pct is not None
                         else _flags.flag("elastic_straggler_pct"))
        self.sustain = max(1, int(
            sustain if sustain is not None
            else _flags.flag("elastic_straggler_sustain")))
        self.evict = bool(evict if evict is not None
                          else _flags.flag("elastic_straggler_evict"))
        self.on_evict = on_evict
        self.breach_streak = 0
        self.tripped = False
        self.trips = 0
        self.evicted = False
        self.last_ratio: Optional[float] = None
        self.tripped_at: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.pct > 0

    def _sentinel_key(self) -> str:
        return f"straggler[{self.publisher.node_id}]"

    def check(self) -> Optional[Dict[str, Any]]:
        """One detection pass (call each step, after
        ``publisher.note_step`` + ``publish``). Returns the trip doc the
        first time this worker trips, else None."""
        if not self.enabled or self.evicted:
            return None
        if self.min_interval_s > 0:
            now = time.time()
            if now - self._last_scan_wall < self.min_interval_s:
                return None
            self._last_scan_wall = now
        try:
            snaps = self.publisher._client().kv_alive(
                obs_prefix(self.publisher.job_id))
        except Exception:
            return None  # master outage: detection pauses, fails soft
        import statistics

        step_ms: Dict[str, float] = {}
        prefix = obs_prefix(self.publisher.job_id)
        for key, value in snaps.items():
            try:
                doc = json.loads(value)
                ms = (doc.get("elastic") or {}).get("step_ms")
                if ms is not None:
                    step_ms[key[len(prefix):]] = float(ms)
            except (ValueError, TypeError):
                continue
        mine = step_ms.get(self.publisher.node_id)
        if mine is None or len(step_ms) < 2:
            return None  # nothing to compare against
        median = statistics.median(step_ms.values())
        if median <= 0:
            return None
        self.last_ratio = mine / median
        slow = mine > median * (1.0 + self.pct / 100.0)
        if self.tripped:
            if not slow:  # recovered: clear the latch, /healthz greens
                self.tripped = False
                self.breach_streak = 0
                self._sentinel("clear")
            return None
        self.breach_streak = self.breach_streak + 1 if slow else 0
        if self.breach_streak < self.sustain:
            return None
        self.tripped = True
        self.trips += 1
        self.breach_streak = 0
        self.tripped_at = time.time()
        doc = {
            "node": self.publisher.node_id,
            "step_ms": round(mine, 3),
            "fleet_median_ms": round(median, 3),
            "ratio": round(self.last_ratio, 3),
            "pct": self.pct,
            "sustain": self.sustain,
        }
        self._sentinel("trip", **doc)
        self._emit("trip", **doc)
        if self.evict or self.on_evict is not None:
            # latch `evicted` only when something actually deregisters the
            # worker; with no mechanism wired, stay merely tripped so the
            # recovery branch can still clear the /healthz latch
            if self.on_evict is not None:
                self.evicted = True
                self._emit("evict", **doc)
                self.on_evict(doc)
            elif self.coordinator is not None:
                self.evicted = True
                self._emit("evict", **doc)
                self.coordinator.evict_self(reason="straggler")
        return doc

    def _sentinel(self, what: str, **attrs):
        try:
            from ...profiler import sentinel as _sent

            if what == "trip":
                drift = ((self.last_ratio or 1.0) - 1.0) * 100.0
                _sent.trip_external(self._sentinel_key(), drift_pct=drift,
                                    **attrs)
            else:
                _sent.clear_external(self._sentinel_key())
        except Exception:
            pass  # the detector must never take the training loop down

    def _emit(self, phase: str, **attrs):
        try:
            from ...core import dispatch

            dispatch._emit("straggler", site=self.publisher.node_id,
                           phase=phase, **attrs)
            dispatch._counter_add(
                "elastic_straggler_trips" if phase == "trip"
                else "elastic_straggler_evictions", 1)
        except Exception:
            pass

    def state(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "pct": self.pct,
            "sustain": self.sustain,
            "evict": self.evict,
            "tripped": self.tripped,
            "trips": self.trips,
            "evicted": self.evicted,
            "breach_streak": self.breach_streak,
            "last_ratio": (None if self.last_ratio is None
                           else round(self.last_ratio, 3)),
        }


def _split_labels(fullname: str):
    """'name{a="b"}' -> ('name', 'a="b"'); 'name' -> ('name', '')."""
    if "{" in fullname and fullname.endswith("}"):
        base, rest = fullname.split("{", 1)
        return base, rest[:-1]
    return fullname, ""


def _http_json(addr: str, path: str, timeout: float) -> Dict[str, Any]:
    with urllib.request.urlopen(f"http://{addr}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read().decode())


class FleetAggregator:
    """Merges the live ``obs/<job>/*`` snapshots into one operator view."""

    def __init__(self, master: Optional[str] = None, kv=None,
                 job_id: str = "default", http_timeout: float = 2.0):
        if kv is None and not master:
            raise ValueError("FleetAggregator needs master= or kv=")
        self._master = master
        self._kv = kv
        self.job_id = job_id
        self.http_timeout = float(http_timeout)

    def _client(self):
        if self._kv is None:
            self._kv = _kv_from_master(self._master)
        return self._kv

    def snapshots(self) -> Dict[str, Dict[str, Any]]:
        """{node_id: snapshot doc} for every UNEXPIRED obs lease — a dead
        host's lease lapses, so it simply isn't here (no stale metrics)."""
        prefix = obs_prefix(self.job_id)
        alive = self._client().kv_alive(prefix)
        out = {}
        for key, value in alive.items():
            node = key[len(prefix):]
            try:
                out[node] = json.loads(value)
            except (ValueError, TypeError):
                continue  # torn/corrupt doc: skip this cycle, not crash
        return out

    # -- merged exposition ----------------------------------------------
    def merged_prometheus_text(self, prefix: str = "paddle_") -> str:
        """One Prometheus exposition for the whole fleet: every family
        from every live host, each sample labeled ``host="<node>"``
        (prepended, so per-host label sets — engine uids etc. — survive
        untouched)."""
        from ...profiler.metrics import _fmt, escape_label_value

        snaps = self.snapshots()
        kinds: Dict[str, str] = {}
        samples: Dict[str, List[str]] = {}

        def add(node, fullname, kind, value):
            base, labels = _split_labels(fullname)
            fam = prefix + base
            inner = f'host="{escape_label_value(node)}"'
            if labels:
                inner += "," + labels
            kinds.setdefault(fam, kind)
            samples.setdefault(fam, []).append(
                f"{fam}{{{inner}}} {_fmt(value)}")

        for node in sorted(snaps):
            m = snaps[node].get("metrics") or {}
            for fullname, v in sorted((m.get("counters") or {}).items()):
                add(node, fullname, "counter", v)
            for fullname, v in sorted((m.get("gauges") or {}).items()):
                add(node, fullname, "gauge", v)
            for fullname, h in sorted((m.get("histograms") or {}).items()):
                base, labels = _split_labels(fullname)
                lbl = "{" + labels + "}" if labels else ""
                add(node, f"{base}_count{lbl}", "counter",
                    (h or {}).get("count", 0))
                add(node, f"{base}_sum{lbl}", "counter",
                    (h or {}).get("sum", 0.0))
        lines: List[str] = []
        for fam in sorted(kinds):
            lines.append(f"# TYPE {fam} {kinds[fam]}")
            lines.extend(samples[fam])
        return "\n".join(lines) + "\n"

    # -- fleet health ----------------------------------------------------
    def fleet_health(self) -> List[Dict[str, Any]]:
        """One row per live node: status, step, snapshot age, engines."""
        now = time.time()
        rows = []
        for node, doc in sorted(self.snapshots().items()):
            h = doc.get("health") or {}
            e = doc.get("elastic") or {}
            t = doc.get("telemetry") or {}
            rows.append({
                "node": node,
                "host": doc.get("host"),
                "pid": doc.get("pid"),
                "status": h.get("status"),
                "reasons": h.get("reasons") or [],
                "step": doc.get("step"),
                "age_s": round(now - float(doc.get("wall") or now), 2),
                "diag": doc.get("diag"),
                "engines": h.get("engines") or {},
                # elastic-rescale columns (ISSUE 14): membership epoch,
                # per-worker step lag, accumulation factor
                "epoch": e.get("epoch"),
                "elastic_step": e.get("step"),
                "step_ms": e.get("step_ms"),
                "step_lag_ms": e.get("step_lag_ms"),
                "accum": e.get("accum"),
                # attribution columns (ISSUE 15): the hottest telemetry
                # group's grad norm, when FLAGS_telemetry is on there
                "grad_norm": t.get("grad_norm"),
                "grad_norm_group": t.get("group"),
                # whole-step capture tier (ISSUE 18), e.g.
                # "captured-sharded@dp2mp2 donated"
                "capture": doc.get("capture"),
            })
        return rows

    # -- fleet-merged program costs (ISSUE 15) ---------------------------
    def fleet_programs(self, k: int = 10) -> List[Dict[str, Any]]:
        """Top-``k`` program costs across the fleet, by measured EMA ms:
        every live host's published ``programs`` summary merged into one
        ranked table (``fleet_top --programs`` renders this)."""
        rows: List[Dict[str, Any]] = []
        for node, doc in sorted(self.snapshots().items()):
            for row in doc.get("programs") or []:
                try:
                    rows.append({
                        "node": node,
                        "key": str(row.get("key")),
                        "category": row.get("category"),
                        "ema_ms": float(row.get("ema_ms") or 0.0),
                        "runs": int(row.get("runs") or 0),
                        "drift_pct": row.get("drift_pct"),
                        "comm_bytes": row.get("comm_bytes"),
                    })
                except (TypeError, ValueError):
                    continue  # torn/hostile row: skip, never crash
        rows.sort(key=lambda r: -r["ema_ms"])
        return rows[:max(1, k)]

    # -- merged chrome trace ---------------------------------------------
    def clock_offset_s(self, addr: str, samples: int = 3) -> float:
        """NTP-style offset of a host's wall clock vs OURS, measured
        against its /clockz endpoint: offset = remote_wall - local_mid,
        taken from the minimum-RTT sample (the KV master hands us the
        address; the handshake runs point-to-point)."""
        best_rtt, best_off = None, 0.0
        for _ in range(max(1, samples)):
            t0 = time.time()
            doc = _http_json(addr, "/clockz", self.http_timeout)
            t1 = time.time()
            rtt = t1 - t0
            off = float(doc["wall"]) - (t0 + t1) / 2.0
            if best_rtt is None or rtt < best_rtt:
                best_rtt, best_off = rtt, off
        return best_off

    def merged_chrome_trace(self, kind: Optional[str] = None,
                            site: Optional[str] = None,
                            last: Optional[int] = None) -> Dict[str, Any]:
        """Pull each live host's flight ring over its diagnostics server
        and merge into ONE chrome trace: a process lane per host (chrome
        ``process_name`` metadata = ``host:<node>``), flight events as
        instants, timestamps mapped into the aggregator's wall clock via
        the per-host offset. Unreachable hosts (no diag server, mid-crash)
        are recorded in the metadata, never fatal."""
        events: List[Dict[str, Any]] = []
        pulled: List[str] = []
        unreachable: List[str] = []
        query = []
        if kind:
            query.append(f"kind={kind}")
        if site:
            query.append(f"site={site}")
        if last is not None:
            query.append(f"last={int(last)}")
        qs = ("?" + "&".join(query)) if query else ""
        snaps = self.snapshots()

        # the per-host pulls (3-sample /clockz handshake + /flight) are
        # independent — run them concurrently, or every dead/partitioned
        # host with a still-published diag address stalls the whole merge
        # by a full connect timeout (the exact chaos window this feeds)
        def pull(addr):
            off = self.clock_offset_s(addr)
            return off, _http_json(addr, f"/flight{qs}", self.http_timeout)

        from concurrent.futures import ThreadPoolExecutor

        nodes = sorted(snaps)
        futures = {}
        with ThreadPoolExecutor(max_workers=min(8, max(1, len(nodes)))) as ex:
            for node in nodes:
                addr = snaps[node].get("diag")
                if addr:
                    futures[node] = ex.submit(pull, addr)
        for lane, node in enumerate(nodes, start=1):
            events.append({"name": "process_name", "ph": "M", "pid": lane,
                           "args": {"name": f"host:{node}"}})
            fut = futures.get(node)
            if fut is None:
                unreachable.append(node)
                continue
            try:
                off, flight = fut.result()
            except Exception:
                unreachable.append(node)
                continue
            pulled.append(node)
            evs = flight.get("events", [])
            # per-request serving lanes: chrome async (b/n/e) events are
            # matched by cat+id GLOBALLY, not per pid — two hosts serving
            # the same request-id space would interleave their spans into
            # one corrupted lane. Prefix the lane id with the host label,
            # escaped exactly like the merged exposition's host label, so
            # cross-host per-request spans stay distinct.
            from ...profiler import metrics as _metrics
            from ...profiler import trace as _trace

            esc_node = _metrics.escape_label_value(node)
            admitted = {
                (e.get("attrs") or {}).get("rid")
                for e in evs
                if e.get("kind") == "serve"
                and (e.get("attrs") or {}).get("phase") == "admit"
            }
            for ev in evs:
                ts_us = (float(ev["ts"]) - off) * 1e6
                if ev.get("kind") == "serve":
                    attrs = dict(ev.get("attrs") or {})
                    phase = attrs.pop("phase", "")
                    rids = attrs.pop("rids", None)
                    if rids is None:
                        rid = attrs.pop("rid", None)
                        rids = [] if rid is None else [rid]
                    lanes = [r for r in rids if r in admitted]
                    for rid in lanes:
                        if phase == "admit":
                            ph = "b"
                        elif phase in _trace._SERVE_END_PHASES:
                            ph = "e"
                        else:
                            ph = "n"
                        events.append({
                            "name": "request", "cat": "serving", "ph": ph,
                            "id": f"{esc_node}:{rid}",
                            "ts": ts_us, "pid": lane, "tid": 1,
                            "args": dict(attrs, phase=phase, rid=rid,
                                         step=ev.get("step"), node=node),
                        })
                    if lanes:
                        continue
                    # engine-scoped serve events (health/restart/...) and
                    # request events whose admit fell outside the pulled
                    # window render as plain instants below
                name = ev.get("kind", "?")
                if ev.get("site"):
                    name += ":" + ev["site"]
                events.append({
                    "name": name, "cat": "fleet", "ph": "i", "s": "t",
                    "ts": ts_us,
                    "pid": lane, "tid": 1,
                    "args": dict(ev.get("attrs") or {}, step=ev.get("step"),
                                 node=node),
                })
        return {
            "traceEvents": events,
            "metadata": {
                "merged_by": "paddle_tpu.distributed.fleet.obs",
                "job_id": self.job_id,
                "hosts": sorted(snaps),
                "hosts_pulled": pulled,
                "hosts_unreachable": unreachable,
                "merged_at": time.time(),
            },
        }
