"""Tree-index retrieval structures (TDM — tree-based deep match).

Reference analogue: paddle/fluid/distributed/index_dataset/
(index_wrapper.{h,cc} TreeIndex over a protobuf tree file,
index_sampler.{h,cc} LayerWiseSampler) and the python facade
python/paddle/distributed/fleet/dataset/index_dataset.py.

TPU-native design: the tree is a complete `branch`-ary array-coded tree in
numpy (code c's children are c*branch+1 .. c*branch+branch, the reference's
coding), built directly from item ids instead of a serialized proto — the
training-side consumers (travel codes, ancestor lookups, layer-wise
negative sampling) are host-side batch producers feeding the device step.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Index", "TreeIndex"]


class Index:
    def __init__(self, name: str):
        self._name = name


class TreeIndex(Index):
    """Complete branch-ary tree over item ids.

    Build from ids (`TreeIndex.build`) or load a saved tree
    (`TreeIndex(name, path)` — the reference's constructor shape).
    Codes: root 0; children of c are c*branch+1..c*branch+branch; layer L
    spans codes [(branch^L - 1)/(branch-1), ...) — identical coding to the
    reference (index_wrapper.h).
    """

    def __init__(self, name: str, path: Optional[str] = None):
        super().__init__(name)
        self._layerwise_sampler = None
        if path is not None:
            self._load(path)

    # -- construction --------------------------------------------------------
    @classmethod
    def build(cls, name: str, ids: Sequence[int], branch: int = 2,
              codes: Optional[Sequence[int]] = None) -> "TreeIndex":
        """Build a complete tree whose leaves hold `ids` (sorted for
        locality, like the reference's kmeans-clustered builder output
        ordering). `codes` optionally pins each id's leaf code."""
        self = cls(name)
        ids = np.asarray(list(ids), np.int64)
        if ids.size == 0:
            raise ValueError("TreeIndex.build needs at least one id")
        branch = int(branch)
        if branch < 2:
            raise ValueError("branch must be >= 2")
        n = ids.size
        height = 1
        while branch ** (height - 1) < n:
            height += 1
        self._branch = branch
        self._height = height  # layers 0..height-1; leaves on height-1
        first_leaf = (branch ** (height - 1) - 1) // (branch - 1)
        if codes is not None:
            codes = np.asarray(list(codes), np.int64)
            if codes.size != n:
                raise ValueError("codes length must match ids")
        else:
            codes = first_leaf + np.arange(n, dtype=np.int64)
        order = np.argsort(ids, kind="stable")
        self._ids = ids[order]
        self._codes = codes[order]
        self._id_to_code: Dict[int, int] = {
            int(i): int(c) for i, c in zip(self._ids, self._codes)
        }
        self._code_to_id: Dict[int, int] = {
            c: i for i, c in self._id_to_code.items()
        }
        return self

    def save(self, path: str):
        np.savez(path, ids=self._ids, codes=self._codes,
                 branch=self._branch, height=self._height)

    def _load(self, path: str):
        if not path.endswith(".npz"):
            path = path + ".npz"
        data = np.load(path)
        self._ids = data["ids"]
        self._codes = data["codes"]
        self._branch = int(data["branch"])
        self._height = int(data["height"])
        self._id_to_code = {
            int(i): int(c) for i, c in zip(self._ids, self._codes)
        }
        self._code_to_id = {c: i for i, c in self._id_to_code.items()}

    # -- reference surface ---------------------------------------------------
    def height(self) -> int:
        return self._height

    def branch(self) -> int:
        return self._branch

    def total_node_nums(self) -> int:
        b, h = self._branch, self._height
        return (b ** h - 1) // (b - 1)

    def emb_size(self) -> int:
        return int(self._ids.size)

    def get_all_leafs(self) -> List[int]:
        return [int(i) for i in self._ids]

    def get_nodes(self, codes: Sequence[int]) -> List[Optional[int]]:
        """Item id stored at each code (None for internal/empty nodes —
        the reference returns node protos; ids are what consumers use)."""
        return [self._code_to_id.get(int(c)) for c in codes]

    def _layer_range(self, level: int):
        b = self._branch
        lo = (b ** level - 1) // (b - 1)
        hi = (b ** (level + 1) - 1) // (b - 1)
        return lo, hi

    def get_layer_codes(self, level: int) -> List[int]:
        if not 0 <= level < self._height:
            raise ValueError(f"level must be in [0, {self._height})")
        lo, hi = self._layer_range(level)
        if level == self._height - 1:
            return [int(c) for c in self._codes]
        # internal layer: only ancestors of live leaves exist
        codes = set()
        for c in self._codes:
            c = int(c)
            while c >= hi:
                c = (c - 1) // self._branch
            codes.add(c)
        return sorted(codes)

    def get_travel_codes(self, id: int, start_level: int = 0) -> List[int]:
        """Leaf-to-root ancestor codes of an item, stopping above
        start_level (reference: get_travel_codes — ordered leaf first)."""
        c = self._id_to_code.get(int(id))
        if c is None:
            raise KeyError(f"id {id} is not in tree {self._name!r}")
        out = []
        level = self._height - 1
        while level >= start_level:
            out.append(int(c))
            c = (c - 1) // self._branch
            level -= 1
        return out

    def get_ancestor_codes(self, ids: Sequence[int], level: int) -> List[int]:
        out = []
        for i in ids:
            c = self._id_to_code.get(int(i))
            if c is None:
                raise KeyError(f"id {i} is not in tree {self._name!r}")
            cur = self._height - 1
            while cur > level:
                c = (c - 1) // self._branch
                cur -= 1
            out.append(int(c))
        return out

    def get_children_codes(self, ancestor: int, level: int) -> List[int]:
        """Codes at `level` under `ancestor` that lead to live leaves."""
        lo, hi = self._layer_range(level)
        out = []
        for c in self.get_layer_codes(level):
            a = c
            while a > ancestor:
                a = (a - 1) // self._branch
            if a == ancestor:
                out.append(c)
        return out

    def get_travel_path(self, child: int, ancestor: int) -> List[int]:
        res = []
        while child > ancestor:
            res.append(int(child))
            child = (child - 1) // self._branch
        return res

    def get_pi_relation(self, ids: Sequence[int], level: int):
        codes = self.get_ancestor_codes(ids, level)
        return dict(zip([int(i) for i in ids], codes))

    # -- layerwise sampler ---------------------------------------------------
    def init_layerwise_sampler(self, layer_sample_counts: Sequence[int],
                               start_sample_layer: int = 1, seed: int = 0):
        """reference: index_sampler.h LayerWiseSampler —
        layer_sample_counts[k] negatives per sampled layer, starting at
        start_sample_layer."""
        if self._layerwise_sampler is not None:
            raise AssertionError("layerwise sampler already initialized")
        n_layers = self._height - start_sample_layer
        if len(layer_sample_counts) != n_layers:
            raise ValueError(
                f"layer_sample_counts needs {n_layers} entries "
                f"(layers {start_sample_layer}..{self._height - 1})"
            )
        self._layerwise_sampler = _LayerWiseSampler(
            self, list(layer_sample_counts), start_sample_layer, seed
        )

    def layerwise_sample(self, user_input, index_input,
                         with_hierarchy: bool = False):
        if self._layerwise_sampler is None:
            raise ValueError("please init layerwise_sampler first.")
        return self._layerwise_sampler.sample(
            user_input, index_input, with_hierarchy
        )


class _LayerWiseSampler:
    """Per-layer positive + sampled-negative batches for TDM training:
    for each (user, target-item) pair and each layer, emit the target's
    ancestor as the positive (label 1) and `count` other codes from the
    same layer as negatives (label 0)."""

    def __init__(self, tree: TreeIndex, counts: List[int],
                 start_layer: int, seed: int):
        self.tree = tree
        self.counts = counts
        self.start = start_layer
        self.rng = np.random.default_rng(seed)
        self._layer_codes = {
            lvl: np.asarray(tree.get_layer_codes(lvl), np.int64)
            for lvl in range(start_layer, tree.height())
        }

    def sample(self, user_input, index_input, with_hierarchy=False):
        """Returns (user_rows, code_col, label_col) — the reference's
        flattened sample layout: one row per (pair, layer, pos|neg)."""
        users_out, codes_out, labels_out = [], [], []
        for user, item in zip(user_input, index_input):
            travel = self.tree.get_travel_codes(int(item), self.start)
            # travel is leaf->start; walk layers top-down like the ref
            for k, lvl in enumerate(range(self.start, self.tree.height())):
                pos = travel[self.tree.height() - 1 - lvl]
                layer = self._layer_codes[lvl]
                count = self.counts[k]
                users_out.append(list(user))
                codes_out.append(int(pos))
                labels_out.append(1)
                pool = layer[layer != pos]
                if pool.size and count > 0:
                    take = self.rng.choice(
                        pool, size=min(count, pool.size), replace=False
                    )
                    for c in take:
                        users_out.append(list(user))
                        codes_out.append(int(c))
                        labels_out.append(0)
        return users_out, codes_out, labels_out
