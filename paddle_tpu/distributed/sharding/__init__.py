"""paddle.distributed.sharding — group-sharded (ZeRO) user API.

Reference analogue: python/paddle/distributed/sharding/group_sharded.py.
"""
from ..compat import (  # noqa: F401
    group_sharded_parallel,
    save_group_sharded_model,
)

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]
