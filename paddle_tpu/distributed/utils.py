"""paddle.distributed.utils — launch-era cluster helpers + MoE collectives.

Reference analogue: python/paddle/distributed/utils.py (Cluster/Pod/
Trainer/JobServer models, get_cluster, port helpers, logger, and the MoE
global_scatter/global_gather collectives).
"""
from .compat import (  # noqa: F401
    Cluster,
    pull_worker_log,
    start_local_trainers,
    terminate_local_procs,
    watch_local_trainers,
    Hdfs,
    JobServer,
    Pod,
    Trainer,
    TrainerProc,
    add_arguments,
    find_free_ports,
    get_cluster,
    get_host_name_ip,
    get_logger,
)
from ..incubate.moe import global_gather, global_scatter  # noqa: F401

__all__ = [
    "get_host_name_ip", "Trainer", "get_cluster", "start_local_trainers",
    "watch_local_trainers", "find_free_ports", "JobServer", "Cluster",
    "Pod", "Hdfs", "add_arguments", "terminate_local_procs", "TrainerProc",
    "get_logger", "pull_worker_log", "global_scatter", "global_gather",
]
