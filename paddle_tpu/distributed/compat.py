"""paddle.distributed surface completion: ParallelMode, PS entry configs,
gloo shims, launch-era cluster helpers, sharding API, and pass framework.

Reference analogue: python/paddle/distributed/__init__.py __all__,
distributed/entry_attr.py, distributed/utils.py, distributed/sharding/,
distributed/passes/pass_base.py.
"""
from __future__ import annotations

import logging
import os
import socket

import numpy as np

__all__ = [
    "ParallelMode",
    "CountFilterEntry",
    "ProbabilityEntry",
    "ShowClickEntry",
    "gloo_init_parallel_env",
    "gloo_barrier",
    "gloo_release",
]


class ParallelMode:
    """reference: fleet/base/topology.py ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


# --- PS sparse-entry configs (reference: distributed/entry_attr.py) --------
class EntryAttr:
    def _to_attr(self):
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    """Sample rows into the sparse table with a probability (reference:
    entry_attr.py ProbabilityEntry)."""

    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self._probability = probability

    def _to_attr(self):
        return f"probability_entry:{self._probability}"


class CountFilterEntry(EntryAttr):
    """Admit rows only after `count_filter` occurrences (reference:
    entry_attr.py CountFilterEntry)."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._count_filter = count_filter

    def _to_attr(self):
        return f"count_filter_entry:{self._count_filter}"


class ShowClickEntry(EntryAttr):
    """Show/click weighted entry (reference: entry_attr.py ShowClickEntry)."""

    def __init__(self, show_name, click_name):
        self._show_name = show_name
        self._click_name = click_name

    def _to_attr(self):
        return f"show_click_entry:{self._show_name}:{self._click_name}"


# --- gloo CPU barrier shims (reference: distributed/parallel.py gloo_*) ----
def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-side rendezvous (reference inits a gloo context; the jax
    coordination service plays that role — see parallel.init_parallel_env)."""
    from .parallel import init_parallel_env

    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(rank_num))
    if rank_num > 1:
        init_parallel_env()


def gloo_barrier():
    from .collective import barrier

    barrier()


def gloo_release():
    """Release the CPU rendezvous context (no-op: the coordination service
    lives for the process)."""


# --- launch-era cluster model (reference: distributed/utils.py) ------------
def get_logger(log_level=20, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s-%(levelname)s: %(message)s"
        ))
        logger.addHandler(h)
    return logger


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(socket.getfqdn(host))
    except OSError:
        return None


def find_free_ports(num):
    ports = set()
    for _ in range(num * 4):
        if len(ports) >= num:
            break
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("", 0))
            ports.add(s.getsockname()[1])
    return ports if len(ports) >= num else None


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """reference: distributed/utils.py add_arguments (argparse helper)."""
    argparser.add_argument(
        "--" + argname, default=default, type=type,
        help=help + f" Default: %(default)s.", **kwargs,
    )


class Trainer:
    def __init__(self):
        self.gpus = []
        self.endpoint = None
        self.rank = None

    def __str__(self):
        return f"gpu:{self.gpus} endpoint:{self.endpoint} rank:{self.rank}"

    def __eq__(self, t):
        return (self.gpus == t.gpus and self.endpoint == t.endpoint
                and self.rank == t.rank)

    def __ne__(self, t):
        return not self == t

    def rank_str(self):
        return str(self.rank)


class Pod:
    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []
        self.servers = []
        self.workers = []
        self.heter_workers = []
        self.gpus = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} "
                f"port:{self.port} trainers:{[str(t) for t in self.trainers]}")

    def __eq__(self, pod):
        return (self.rank == pod.rank and self.id == pod.id
                and self.addr == pod.addr and self.port == pod.port
                and self.trainers == pod.trainers)

    def __ne__(self, pod):
        return not self == pod

    def rank_str(self):
        return str(self.rank)

    def get_visible_gpus(self):
        return ",".join(str(g) for g in self.gpus)


class JobServer:
    def __init__(self):
        self.endpoint = None

    def __str__(self):
        return str(self.endpoint)

    def __eq__(self, j):
        return self.endpoint == j.endpoint

    def __ne__(self, j):
        return not self == j


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = None
        self.rank = None
        self.local_rank = None
        self.cmd = None


class Hdfs:
    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return all((self.hdfs_ugi, self.hdfs_name, self.hdfs_path))

    def __str__(self):
        return (f"hdfs_ugi:{self.hdfs_ugi} hdfs_name:{self.hdfs_name} "
                f"hdfs_path:{self.hdfs_path}")

    def __eq__(self, n):
        return str(self) == str(n)

    def __ne__(self, n):
        return not self == n


class Cluster:
    """reference: distributed/utils.py Cluster — pods of trainers."""

    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs
        self.job_stage_flag = None

    def __str__(self):
        return f"pods:{[str(p) for p in self.pods]}"

    def __eq__(self, c):
        return (len(self.pods) == len(c.pods)
                and all(a == b for a, b in zip(self.pods, c.pods)))

    def __ne__(self, c):
        return not self == c

    def update_pods(self, cluster):
        self.pods = list(cluster.pods)

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def pods_nranks(self):
        return len(self.pods)

    def trainers_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def pod_by_id(self, pod_id):
        for p in self.pods:
            if str(p.id) == str(pod_id):
                return p
        return None


def get_cluster(node_ips, node_ip, trainer_endpoints, device_mode=None,
                devices_per_proc=None):
    """Build a Cluster from endpoint lists (reference:
    distributed/utils.py get_cluster)."""
    cluster = Cluster()
    rank = 0
    for pod_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = pod_rank
        pod.addr = ip
        pod.id = pod_rank
        eps = (trainer_endpoints[pod_rank]
               if trainer_endpoints and isinstance(trainer_endpoints[0], list)
               else [e for e in (trainer_endpoints or []) if e.split(":")[0] == ip])
        n = len(eps) or len(devices_per_proc or [0])
        for i in range(n):
            t = Trainer()
            t.gpus = ([devices_per_proc[i]] if devices_per_proc
                      and i < len(devices_per_proc) else [i])
            t.endpoint = eps[i] if i < len(eps) else f"{ip}:617{i}"
            t.rank = rank
            rank += 1
            pod.trainers.append(t)
        cluster.pods.append(pod)
    cluster.pods[0].port = int(
        cluster.pods[0].trainers[0].endpoint.split(":")[-1]
    ) if cluster.pods[0].trainers else 6170
    return cluster, cluster.pods[min(
        node_ips.index(node_ip) if node_ip in node_ips else 0,
        len(cluster.pods) - 1)]


# --- group-sharded (ZeRO) user API (reference: distributed/sharding/) ------
def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False):
    """Wrap model+optimizer in ZeRO sharding (reference:
    sharding/group_sharded.py group_sharded_parallel; levels os / os_g /
    p_g_os = stages 1/2/3). On this stack sharding is a GSPMD param-spec:
    shard_params installs the specs, the compiled step does the rest."""
    from ..parallel.sharding import shard_params

    stage = {"os": 1, "os_g": 2, "p_g_os": 3}.get(level)
    if stage is None:
        raise ValueError(
            f"level must be one of os|os_g|p_g_os, got {level!r}"
        )
    shard_params(model, zero_stage=stage)
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    """reference: sharding/group_sharded.py save_group_sharded_model."""
    import paddle_tpu as paddle

    if output.endswith((".pdparams", ".pdopt", ".pdmodel")):
        raise ValueError(
            "save_group_sharded_model expects a directory/prefix, got a "
            f"file suffix: {output}"
        )
    os.makedirs(output, exist_ok=True)
    paddle.save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        paddle.save(optimizer.state_dict(),
                    os.path.join(output, "model.pdopt"))


# --- program-pass framework (reference: distributed/passes/pass_base.py) ---
_pass_registry = {}


class PassContext:
    """Carries pass inputs/outputs (reference: pass_base.py PassContext)."""

    def __init__(self):
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class PassBase:
    name = None

    def __init__(self):
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    def check_before_apply(self, main_program, startup_program, context):
        return True

    def apply(self, main_programs, startup_programs, context=None):
        context = context or PassContext()
        mains = main_programs if isinstance(main_programs, list) else [main_programs]
        starts = (startup_programs if isinstance(startup_programs, list)
                  else [startup_programs])
        for m, s in zip(mains, starts):
            self._apply_single_impl(m, s, context)
        return context

    def _apply_single_impl(self, main_program, startup_program, context):
        raise NotImplementedError


def register_pass(name):
    def deco(cls):
        cls.name = name
        _pass_registry[name] = cls
        return cls

    return deco


def new_pass(name, pass_attrs=None):
    """Instantiate a registered pass (reference: pass_base.py new_pass)."""
    if name not in _pass_registry:
        raise ValueError(
            f"no pass named {name!r}; registered: {sorted(_pass_registry)}"
        )
    p = _pass_registry[name]()
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassManager:
    """Apply a list of passes in order (reference: pass_base.py
    PassManager)."""

    def __init__(self, passes):
        self._passes = list(passes)
        self.context = PassContext()

    @property
    def names(self):
        return [p.name for p in self._passes]

    def apply(self, main_programs, startup_programs):
        for p in self._passes:
            p.apply(main_programs, startup_programs, self.context)
        return self.context


# --- local trainer process management (reference: distributed/utils.py
# start_local_trainers/watch_local_trainers/terminate_local_procs) ----------
def start_local_trainers(cluster, pod, training_script, training_script_args,
                         log_dir=None, envs=None):
    """Spawn one subprocess per trainer in `pod` with the PADDLE_* env
    contract (reference: distributed/utils.py start_local_trainers)."""
    import subprocess
    import sys

    current_env = dict(os.environ)
    current_env.update(envs or {})
    procs = []
    for idx, t in enumerate(pod.trainers):
        proc_env = {
            "PADDLE_TRAINER_ID": str(t.rank),
            "PADDLE_CURRENT_ENDPOINT": str(t.endpoint),
            "PADDLE_TRAINERS_NUM": str(cluster.trainers_nranks()),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(cluster.trainers_endpoints()),
        }
        env = dict(current_env)
        env.update(proc_env)
        cmd = [sys.executable, "-u", training_script] + list(
            training_script_args or []
        )
        fn = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            fn = open(os.path.join(log_dir, f"workerlog.{idx}"), "a")
            proc = subprocess.Popen(cmd, env=env, stdout=fn, stderr=fn)
        else:
            proc = subprocess.Popen(cmd, env=env)
        tp = TrainerProc()
        tp.proc = proc
        tp.rank = t.rank
        tp.local_rank = idx
        tp.log_fn = fn
        tp.cmd = cmd
        procs.append(tp)
    return procs


def watch_local_trainers(procs, nranks):
    """Poll trainer procs; raise if any failed, return alive list
    (reference: distributed/utils.py watch_local_trainers)."""
    alive = []
    error = False
    for p in procs:
        ret = p.proc.poll()
        if ret is None:
            alive.append(p)
        elif ret != 0:
            error = True
    if error:
        terminate_local_procs(procs)
        raise RuntimeError("ABORT!!! Out of all trainers, one failed")
    return alive


def terminate_local_procs(procs):
    """Kill remaining trainer procs (reference: terminate_local_procs)."""
    import time

    for p in procs:
        if p.proc.poll() is None:
            p.proc.terminate()
            if p.log_fn:
                p.log_fn.close()
    time.sleep(1)
    for p in procs:
        if p.proc.poll() is None:
            p.proc.kill()


def pull_worker_log(tp):
    """Tail a trainer's log file to stdout (reference: pull_worker_log)."""
    if tp.log_fn is None:
        return
    with open(tp.log_fn.name) as f:
        f.seek(tp.log_offset or 0)
        data = f.read()
        tp.log_offset = f.tell()
    if data:
        print(data, end="")
