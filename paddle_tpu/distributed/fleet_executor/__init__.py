"""FleetExecutor — C++ actor runtime driving task-DAG pipeline schedules.

Reference analogue: paddle/fluid/distributed/fleet_executor/
(fleet_executor.h:35 FleetExecutor, carrier.h:49, interceptor.h:43,
task_node.h, dist_model.cc). The reference compiles a Program into TaskNodes
and runs them as actors exchanging InterceptorMessages over brpc; here the
carrier/interceptor core is the same design in csrc/fleet_executor.cc
(threads + queues, C ABI), and the payload of each task is a Python
callable — typically a jitted XLA program per pipeline stage, so the actor
threads orchestrate while XLA computes.
"""
from __future__ import annotations

import ctypes
import threading
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["TaskNode", "FleetExecutor", "MessageBus"]

_lib = None
_COMPUTE_FN = ctypes.CFUNCTYPE(ctypes.c_int32, ctypes.c_int64, ctypes.c_int64)


def _load_lib():
    global _lib
    if _lib is None:
        import os

        from ...utils import cpp_extension

        csrc = os.path.join(os.path.dirname(__file__), "csrc")
        src = os.path.join(csrc, "fleet_executor.cc")
        ps_net = os.path.join(
            os.path.dirname(os.path.dirname(csrc)), "ps", "csrc", "ps_net.h"
        )
        _lib = cpp_extension.load("fleet_executor", [src], depends=[ps_net])
        _lib.carrier_create.restype = ctypes.c_void_p
        _lib.carrier_add_task.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _COMPUTE_FN, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ]
        _lib.carrier_start.argtypes = [ctypes.c_void_p]
        _lib.carrier_stop.argtypes = [ctypes.c_void_p]
        _lib.carrier_wait.restype = ctypes.c_int32
        _lib.carrier_wait.argtypes = [ctypes.c_void_p]
        _lib.carrier_destroy.argtypes = [ctypes.c_void_p]
        _lib.bus_create.restype = ctypes.c_void_p
        _lib.bus_create.argtypes = [ctypes.c_int, ctypes.c_char_p]
        _lib.bus_port.restype = ctypes.c_int
        _lib.bus_port.argtypes = [ctypes.c_void_p]
        _lib.bus_attach.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        _lib.bus_detach.argtypes = [ctypes.c_void_p]
        _lib.bus_set_task_rank.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ]
        _lib.bus_put.restype = ctypes.c_int
        _lib.bus_put.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        _lib.bus_get_size.restype = ctypes.c_int64
        _lib.bus_get_size.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        _lib.bus_take.restype = ctypes.c_int64
        _lib.bus_take.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64,
        ]
        _lib.bus_stop.argtypes = [ctypes.c_void_p]
        _lib.bus_destroy.argtypes = [ctypes.c_void_p]
    return _lib


class MessageBus:
    """Cross-carrier transport (reference: message_bus.h:40 — brpc there,
    framed TCP here). Routes interceptor control messages between ranks and
    parks tensor payload blobs keyed by (task, scope) until fetched.

    `endpoints` is one "host:port" per rank; this process serves
    endpoints[rank]. put()/get() move numpy arrays (serialized with their
    dtype+shape) between pipeline stages on different processes/hosts.
    """

    def __init__(self, rank: int, endpoints: Sequence[str]):
        self._lib = _load_lib()
        self.rank = int(rank)
        self.endpoints = list(endpoints)
        self._h = self._lib.bus_create(
            self.rank, ",".join(self.endpoints).encode()
        )
        if not self._h:
            raise RuntimeError(
                f"MessageBus rank {rank} failed to bind {endpoints[rank]}"
            )

    @property
    def port(self) -> int:
        return self._lib.bus_port(self._h)

    def set_task_rank(self, task_id: int, rank: int):
        self._lib.bus_set_task_rank(self._h, task_id, rank)

    def put(self, task_id: int, scope: int, array) -> None:
        """Ship a numpy array to (task, scope) — local store or remote rank."""
        import io

        import numpy as np

        bio = io.BytesIO()
        np.save(bio, np.ascontiguousarray(array), allow_pickle=False)
        data = bio.getvalue()
        if self._lib.bus_put(self._h, task_id, scope, data, len(data)) != 0:
            raise ConnectionError(
                f"bus_put to task {task_id} scope {scope} failed"
            )

    def get(self, task_id: int, scope: int, timeout: float = 60.0):
        """Blocking fetch of the array shipped to (task, scope)."""
        import io

        import numpy as np

        n = self._lib.bus_get_size(
            self._h, task_id, scope, int(timeout * 1000)
        )
        if n < 0:
            raise TimeoutError(
                f"no payload for task {task_id} scope {scope} within {timeout}s"
            )
        buf = (ctypes.c_char * n)()
        got = self._lib.bus_take(self._h, task_id, scope, buf, n)
        if got != n:
            raise RuntimeError(
                "bus payload changed between size and take "
                f"(expected {n} bytes, take returned {got})"
            )
        return np.load(io.BytesIO(bytes(buf)), allow_pickle=False)

    def stop(self):
        if getattr(self, "_h", None):
            self._lib.bus_stop(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.bus_destroy(self._h)
                self._h = None
        except Exception:
            pass


class TaskNode:
    """One DAG node (reference: task_node.h): a callable run once per
    microbatch, gated on all upstream nodes having run that microbatch."""

    def __init__(self, task_id: int, fn: Optional[Callable] = None,
                 max_run_times: int = 1):
        self.task_id = int(task_id)
        self.fn = fn
        self.max_run_times = int(max_run_times)
        self.upstream: List[int] = []
        self.downstream: List[int] = []

    def add_upstream_task(self, task_id: int):
        self.upstream.append(int(task_id))
        return self

    def add_downstream_task(self, task_id: int):
        self.downstream.append(int(task_id))
        return self


class FleetExecutor:
    """Build a carrier from TaskNodes and run the actor schedule.

    Each interceptor is a native thread; Python callbacks run under the GIL
    but jax dispatch releases it, so stage compute genuinely overlaps
    (microbatch t on stage k runs while t+1 runs on stage k-1 — the 1F1B-
    style host schedule the reference's SectionWorker/interceptors give).
    """

    def __init__(self, nodes: Sequence[TaskNode], bus: Optional[MessageBus] = None,
                 task_ranks: Optional[Dict[int, int]] = None):
        """`bus` + `task_ranks` turn this into one rank of a multi-process
        executor (reference: FleetExecutor::Init registering the carrier on
        the MessageBus): every rank declares the FULL task DAG, but only
        tasks with task_ranks[id] == bus.rank run locally — control
        messages to/from the rest ride the bus."""
        self._nodes: Dict[int, TaskNode] = {n.task_id: n for n in nodes}
        if len(self._nodes) != len(nodes):
            raise ValueError("duplicate task ids")
        self._bus = bus
        self._task_ranks = dict(task_ranks or {})
        if (bus is None) != (not self._task_ranks):
            raise ValueError("bus and task_ranks go together")
        if bus is not None:
            missing = [i for i in self._nodes if i not in self._task_ranks]
            if missing:
                raise ValueError(f"task_ranks missing entries for {missing}")
        # validate BOTH edge directions and their symmetry: an asymmetric
        # edge would silently hang (upstream never fed) or silently drop
        # messages (downstream unknown)
        for n in nodes:
            for u in n.upstream:
                if u not in self._nodes:
                    raise ValueError(f"task {n.task_id} upstream {u} unknown")
                if n.task_id not in self._nodes[u].downstream:
                    raise ValueError(
                        f"task {n.task_id} lists {u} upstream but {u} does "
                        f"not list {n.task_id} downstream (asymmetric edge)"
                    )
            for d in n.downstream:
                if d not in self._nodes:
                    raise ValueError(f"task {n.task_id} downstream {d} unknown")
                if n.task_id not in self._nodes[d].upstream:
                    raise ValueError(
                        f"task {n.task_id} lists {d} downstream but {d} does "
                        f"not list {n.task_id} upstream (asymmetric edge)"
                    )
                if self._nodes[d].max_run_times > n.max_run_times:
                    raise ValueError(
                        f"task {d} expects {self._nodes[d].max_run_times} "
                        f"microbatches but upstream {n.task_id} only emits "
                        f"{n.max_run_times} — the extra scopes would hang"
                    )
        self._errors: Dict[int, BaseException] = {}
        self._lock = threading.Lock()

    def run(self, timeout: Optional[float] = None) -> None:
        """Execute all microbatches; raises the first task exception.
        On timeout the carrier is aborted (STOP broadcast) and TimeoutError
        raised."""
        lib = _load_lib()
        carrier = lib.carrier_create()
        with self._lock:
            self._errors.clear()
        thunks = []  # keep CFUNCTYPE objects alive for the whole run
        try:
            my_rank = self._bus.rank if self._bus is not None else None
            for n in self._nodes.values():
                if my_rank is not None and self._task_ranks[n.task_id] != my_rank:
                    continue  # remote task — control flows via the bus
                fn = n.fn

                def thunk(task_id, scope, _fn=fn):
                    if _fn is None:
                        return 0
                    try:
                        _fn(scope)
                        return 0
                    except BaseException as e:  # propagate into carrier_wait
                        with self._lock:
                            self._errors[int(task_id)] = e
                        return 1

                cfn = _COMPUTE_FN(thunk)
                thunks.append(cfn)
                ups = (ctypes.c_int64 * len(n.upstream))(*n.upstream)
                downs = (ctypes.c_int64 * len(n.downstream))(*n.downstream)
                lib.carrier_add_task(
                    carrier, n.task_id, cfn, n.max_run_times,
                    ups, len(n.upstream), downs, len(n.downstream),
                )
            if self._bus is not None:
                for tid, r in self._task_ranks.items():
                    self._bus.set_task_rank(tid, r)
                lib.bus_attach(self._bus._h, carrier)
            lib.carrier_start(carrier)
            if timeout is None:
                rc = lib.carrier_wait(carrier)
            else:
                result = {}
                waiter = threading.Thread(
                    target=lambda: result.update(rc=lib.carrier_wait(carrier))
                )
                waiter.start()
                waiter.join(timeout)
                if waiter.is_alive():
                    lib.carrier_stop(carrier)
                    # STOP only lands between messages; a callback stuck
                    # inside a stage can't be interrupted — bound this join
                    # and, if still stuck, leak the carrier (destroying it
                    # would join the stuck thread forever)
                    waiter.join(10.0)
                    if waiter.is_alive():
                        carrier = None
                    raise TimeoutError(
                        f"fleet executor did not finish within {timeout}s"
                    )
                rc = result["rc"]
            if rc != 0:
                with self._lock:
                    err = next(iter(self._errors.values()), None)
                if err is not None:
                    raise err
                raise RuntimeError(f"fleet executor failed rc={rc}")
        finally:
            if self._bus is not None:
                # bus read threads must never deliver into a dead carrier
                lib.bus_detach(self._bus._h)
            if carrier is not None:
                lib.carrier_destroy(carrier)

    @staticmethod
    def pipeline(stages: Sequence[Callable], num_micro: int) -> "FleetExecutor":
        """Linear pipeline sugar: stage k's microbatch t runs after stage
        k-1's microbatch t (reference: the origin_scheduler task chain)."""
        nodes = []
        for i, fn in enumerate(stages):
            n = TaskNode(i, fn, max_run_times=num_micro)
            if i > 0:
                n.add_upstream_task(i - 1)
            if i < len(stages) - 1:
                n.add_downstream_task(i + 1)
            nodes.append(n)
        return FleetExecutor(nodes)
